"""The sliding-window drive loop: close -> retract -> fold -> emit.

:class:`SlidingGraphAggregator` sequences the event-time machinery into
one driver with an explicit, crash-recoverable order per closed pane:

1. **close** — the pane assembler hands over the pane the merged
   watermark passed (``eventtime.pane_close``, the PANE-CLOSE story
   line);
2. **retract** — panes that age out of the new window span expire
   through the decremental summaries (forest repair, degree
   subtraction, cover repair + latch re-resolution;
   ``eventtime.retract``, the RETRACT line);
3. **fold** — the new pane's edges union in (the add-only path the
   repo always had);
4. **commit** — when a ``commit_dir`` is configured, the whole state
   (summaries + live panes + clocks) commits as ONE atomic checksummed
   artifact (``resilience/integrity.py`` discipline) BEFORE the
   window result is emitted.

The fault hook ``eventtime.retract`` fires between steps 3 and 4 —
exactly the kill the chaos satellite aims at: the summaries have
already mutated, the commit has not happened. Recovery restores the
last committed state (pane boundary ``done_panes``) and the source
replays; records of already-committed panes drop as late (their slot
closed — the pane assembler's dedup), panes from ``done_panes`` on
re-close and re-fold, and the final answers are oracle-identical
(``tests/test_eventtime.py`` pins it).

``verify=True`` turns on the self-check: every emission is compared
against the from-scratch oracles on the surviving edge multiset and a
mismatch raises — the zero-mismatch contract ``bench.py --eventtime``
runs under.
"""

from __future__ import annotations

import dataclasses
import io
import os
import time
from typing import Iterator, List, Optional, Tuple

import numpy as np

from ..obs.registry import get_registry
from ..resilience import faults as _faults
from ..resilience.integrity import (
    replace_atomic,
    unwrap_checksummed,
    wrap_checksummed,
)
from .panes import EventTimeSlidingWindow, Pane, PaneAssembler
from .retract import (
    DecBipartite,
    DecDegree,
    DecForest,
    oracle_bipartite,
    oracle_degrees,
    oracle_labels,
)
from .watermark import NO_WATERMARK, WatermarkTracker

#: the committed state artifact's filename inside ``commit_dir``
STATE_FILE = "eventtime_state.bin"

_SUMMARIES = ("cc", "degree", "bipartite")


@dataclasses.dataclass
class WindowResult:
    """One emitted sliding window: the summaries over the surviving
    edge multiset of panes ``[end_pane - panes_per_window + 1,
    end_pane]``, stamped with the event-time watermark at emission
    (what serving forwards as ``Answer.event_ts``)."""

    index: int          # the window's END pane index
    start: int          # event-time start (may predate the stream)
    end: int            # event-time end, exclusive
    event_ts: int       # merged watermark at emission
    n_edges: int        # live multiset size
    labels: Optional[np.ndarray] = None
    degrees: Optional[np.ndarray] = None
    top: Optional[list] = None
    bipartite: Optional[bool] = None
    witness: Optional[int] = None
    repair: Optional[dict] = None  # last retraction's bounded-recompute stats


class SlidingGraphAggregator:
    """Event-time sliding CC/degree/bipartiteness with retraction.

    ``size``/``slide`` are event-time units (``slide=None`` —
    tumbling); ``allowed_lateness`` the lateness policy threaded to the
    pane assembler; ``nshards`` the watermark tracker's width (the
    cross-shard min-merge rule). ``summaries`` picks which decremental
    summaries run. Timestamps arrive as a per-record i64 column —
    :meth:`push` — and the clock advances from data per shard, or
    explicitly via :meth:`advance_watermark` (tests, punctuation).
    Single-writer, like every carry in this repo.
    """

    def __init__(
        self,
        size: int,
        slide: Optional[int] = None,
        *,
        allowed_lateness: int = 0,
        nshards: int = 1,
        summaries: Tuple[str, ...] = _SUMMARIES,
        heavy_k: int = 8,
        commit_dir: Optional[str] = None,
        verify: bool = False,
    ):
        for s in summaries:
            if s not in _SUMMARIES:
                raise ValueError(
                    f"unknown summary {s!r}; pick from {_SUMMARIES}"
                )
        self.policy = EventTimeSlidingWindow(size, slide)
        self.assembler = PaneAssembler(
            self.policy, allowed_lateness=allowed_lateness
        )
        self.tracker = WatermarkTracker(nshards)
        self.summaries = tuple(summaries)
        self.heavy_k = int(heavy_k)
        self.commit_dir = commit_dir
        self.verify = bool(verify)
        self._cc = DecForest() if "cc" in summaries else None
        self._deg = DecDegree() if "degree" in summaries else None
        self._bip = DecBipartite() if "bipartite" in summaries else None
        self._live: List[Pane] = []   # panes inside the current span
        self._done_panes: Optional[int] = None  # next pane index to fold
        self._pane_close = None  # lazy counters
        self._retract = None
        self._replayed = None
        self._finished = False

    # ------------------------------------------------------------------ #
    # Ingest
    # ------------------------------------------------------------------ #
    def push(self, src, dst, ts, *, shard: int = 0) -> List[WindowResult]:
        """Feed one timestamped column chunk from ``shard``; returns
        the window results its watermark advance released (possibly
        none — one slow shard holds the clock, the min-merge rule)."""
        # records precede their watermark: the chunk buffers against
        # the PRIOR merged clock (a watermark promises about FUTURE
        # records, never the chunk that carried it), then the clock
        # advances and closes whatever panes it passed
        self.assembler.add(src, dst, ts, self.tracker.current())
        wm = self.tracker.observe(shard, ts)
        return self._drain(wm)

    def advance_watermark(self, watermark: int, *,
                          shard: int = 0) -> List[WindowResult]:
        """Explicit per-shard watermark punctuation (no records)."""
        wm = self.tracker.observe(shard, np.int64(watermark))
        return self._drain(wm)

    def finish(self) -> List[WindowResult]:
        """End of stream: every shard's promise becomes total, every
        open pane closes, the tail windows emit."""
        if self._finished:
            return []
        self._finished = True
        for s in range(self.tracker.nshards):
            self.tracker.finish(s)
        return self._process(self.assembler.flush())

    def _drain(self, wm: int) -> List[WindowResult]:
        return self._process(self.assembler.advance(wm))

    # ------------------------------------------------------------------ #
    # The pane cycle
    # ------------------------------------------------------------------ #
    def _process(self, panes: List[Pane]) -> List[WindowResult]:
        out: List[WindowResult] = []
        for pane in panes:
            if self._done_panes is not None and \
                    pane.index < self._done_panes:
                # at-least-once replay after a restore: the committed
                # state already folded this pane — counted, not silent
                if self._replayed is None:
                    self._replayed = get_registry().counter(
                        "eventtime.replayed_panes"
                    )
                self._replayed.inc()
                continue
            out.append(self._cycle(pane))
        return out

    def _cycle(self, pane: Pane) -> WindowResult:
        if self._pane_close is None:
            self._pane_close = get_registry().counter(
                "eventtime.pane_close"
            )
            self._retract = get_registry().counter("eventtime.retract")
        self._pane_close.inc()
        self._grow_for(pane)
        nw = self.policy.panes_per_window
        # retract FIRST: panes leaving the span as `pane` enters it
        expired = []
        while self._live and self._live[0].index <= pane.index - nw:
            expired.append(self._live.pop(0))
        repair_stats = None
        if expired and any(len(p) for p in expired):
            exp_s = np.concatenate([p.src for p in expired])
            exp_d = np.concatenate([p.dst for p in expired])
            sur_s, sur_d = self._live_cols()
            if self._deg is not None:
                self._deg.retract(exp_s, exp_d)
            if self._cc is not None:
                repair_stats = self._cc.retract(
                    exp_s, exp_d, sur_s, sur_d
                )
            if self._bip is not None:
                self._bip.retract(exp_s, exp_d, sur_s, sur_d)
            self._retract.inc()
        # fold the new pane in (the add-only path)
        if len(pane):
            if self._deg is not None:
                self._deg.add(pane.src, pane.dst)
            if self._cc is not None:
                self._cc.add(pane.src, pane.dst)
            if self._bip is not None:
                self._bip.add(pane.src, pane.dst)
        self._live.append(pane)
        self._done_panes = pane.index + 1
        # the chaos target: summaries mutated, commit not yet durable
        if _faults.active():
            _faults.fire("eventtime.retract", index=pane.index)
        if self.commit_dir is not None:
            self.commit()
        res = self._emit(pane, repair_stats)
        if self.verify:
            self._self_check(res)
        return res

    def _grow_for(self, pane: Pane) -> None:
        if not len(pane):
            return
        need = int(max(pane.src.max(), pane.dst.max())) + 1
        if self._deg is not None:
            self._deg.grow(need)
        if self._cc is not None:
            self._cc.grow(need)
        if self._bip is not None:
            self._bip.grow(need)

    def _live_cols(self) -> Tuple[np.ndarray, np.ndarray]:
        if not self._live:
            z = np.zeros(0, np.int64)
            return z, z
        return (
            np.concatenate([p.src for p in self._live]),
            np.concatenate([p.dst for p in self._live]),
        )

    def _emit(self, pane: Pane,
              repair_stats: Optional[dict]) -> WindowResult:
        n_live = sum(len(p) for p in self._live)
        res = WindowResult(
            index=pane.index,
            start=pane.end - self.policy.size,
            end=pane.end,
            event_ts=self.tracker.current(),
            n_edges=int(n_live),
            repair=repair_stats,
        )
        if self._cc is not None:
            res.labels = self._cc.labels().copy()
        if self._deg is not None:
            res.degrees = self._deg.deg.copy()
            res.top = self._deg.top_k(self.heavy_k)
        if self._bip is not None:
            res.bipartite = self._bip.is_bipartite()
            res.witness = self._bip.conflict_witness()
        return res

    # ------------------------------------------------------------------ #
    # Oracle self-check (the zero-mismatch contract)
    # ------------------------------------------------------------------ #
    def _self_check(self, res: WindowResult) -> None:
        s, d = self._live_cols()
        if res.labels is not None:
            want = oracle_labels(self._cc.vcap, s, d)
            if not np.array_equal(res.labels, want):
                raise AssertionError(
                    f"window {res.index}: CC labels diverge from the "
                    "from-scratch oracle on the surviving multiset"
                )
        if res.degrees is not None:
            want = oracle_degrees(self._deg.vcap, s, d)
            if not np.array_equal(res.degrees, want):
                raise AssertionError(
                    f"window {res.index}: degrees diverge from the "
                    "from-scratch oracle on the surviving multiset"
                )
        if res.bipartite is not None:
            want = oracle_bipartite(self._bip.vcap, s, d)
            if res.bipartite != want:
                raise AssertionError(
                    f"window {res.index}: bipartite verdict "
                    f"{res.bipartite} diverges from the oracle {want}"
                )

    # ------------------------------------------------------------------ #
    # Commit / restore (atomic, checksummed — the chaos contract)
    # ------------------------------------------------------------------ #
    def commit(self) -> str:
        """Commit the full state as ONE atomic checksummed artifact;
        returns the committed path. The barrier rule: everything or
        nothing — live panes, summaries, clocks and the pane cursor
        travel together, so a restore can never pair a post-retraction
        summary with a pre-retraction pane list."""
        if self.commit_dir is None:
            raise RuntimeError("no commit_dir configured")
        os.makedirs(self.commit_dir, exist_ok=True)
        arrays = {
            "done_panes": np.asarray(
                [-1 if self._done_panes is None else self._done_panes],
                np.int64,
            ),
            "marks": np.asarray(
                self.tracker.state_dict()["marks"], np.int64
            ),
            "live_meta": np.asarray(
                [[p.index, p.start, p.end] for p in self._live],
                np.int64,
            ).reshape(-1, 3),
        }
        for i, p in enumerate(self._live):
            arrays[f"pane{i}_src"] = p.src
            arrays[f"pane{i}_dst"] = p.dst
            arrays[f"pane{i}_ts"] = p.ts
        if self._cc is not None:
            arrays["cc_lab"] = self._cc.lab
        if self._deg is not None:
            arrays["deg"] = self._deg.deg
        if self._bip is not None:
            arrays["cover"] = self._bip.cover
            arrays["bip_vcap"] = np.asarray([self._bip.vcap], np.int64)
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        path = os.path.join(self.commit_dir, STATE_FILE)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(wrap_checksummed(buf.getvalue()))
        replace_atomic(tmp, path)
        return path

    def restore(self) -> bool:
        """Load the last committed state; False when none exists. A
        corrupt artifact raises through ``unwrap_checksummed`` (a
        counted rejection — the integrity contract), it is never
        half-loaded."""
        if self.commit_dir is None:
            raise RuntimeError("no commit_dir configured")
        path = os.path.join(self.commit_dir, STATE_FILE)
        if not os.path.exists(path):
            return False
        with open(path, "rb") as f:
            payload = unwrap_checksummed(f.read(), origin=path)
        data = np.load(io.BytesIO(payload))
        done = int(data["done_panes"][0])
        self._done_panes = None if done < 0 else done
        marks = data["marks"].tolist()
        self.tracker.load_state_dict({
            "marks": marks,
            "live": [True] * len(marks),
            "merged": NO_WATERMARK,
        })
        # re-merge from the restored marks (advances the gauge too)
        self.tracker.observe(0, np.zeros(0, np.int64))
        meta = data["live_meta"]
        self._live = [
            Pane(
                int(meta[i][0]), int(meta[i][1]), int(meta[i][2]),
                np.asarray(data[f"pane{i}_src"], np.int64),
                np.asarray(data[f"pane{i}_dst"], np.int64),
                np.asarray(data[f"pane{i}_ts"], np.int64),
            )
            for i in range(meta.shape[0])
        ]
        if self._cc is not None and "cc_lab" in data:
            self._cc.load_state_dict({"lab": data["cc_lab"]})
        if self._deg is not None and "deg" in data:
            self._deg.load_state_dict({"deg": data["deg"]})
        if self._bip is not None and "cover" in data:
            self._bip.load_state_dict({
                "vcap": int(data["bip_vcap"][0]),
                "cover": data["cover"],
            })
        # replayed records for already-folded panes must drop as late:
        # the assembler's closed-slot cursor is the committed cursor
        # (and it is AUTHORITATIVE — sealed — so replays below it drop)
        if self._done_panes is not None:
            self.assembler._next_pane = self._done_panes
            self.assembler._sealed = True
        return True

    # ------------------------------------------------------------------ #
    def servable_payload(self) -> dict:
        """The serving-shape snapshot payload: the summaries plus the
        ``event_ts`` watermark stamp the snapshot store publishes and
        :class:`~gelly_streaming_tpu.serving.query.Answer` reports."""
        payload: dict = {"event_ts": int(self.tracker.current())}
        if self._cc is not None:
            payload["labels"] = self._cc.labels().copy()
        if self._deg is not None:
            payload["deg"] = self._deg.deg.copy()
        if self._bip is not None:
            payload["bipartite"] = self._bip.is_bipartite()
        return payload


def drive_sliding(
    windows_ts: Iterator, agg: SlidingGraphAggregator, *,
    deadline_s: Optional[float] = None,
) -> List[WindowResult]:
    """Drive an aggregator from a ``windows_ts()``-shaped iterator
    (``(shard, src, dst, val|None, ts)`` tuples — what
    :meth:`~gelly_streaming_tpu.core.ingest.ShardedEdgeSource.windows_ts`
    yields). ``deadline_s`` is a TOTAL wall budget: once spent, the
    drive stops consuming and flushes what it has (the smoke/bench
    bound, not a correctness knob)."""
    deadline = (
        None if deadline_s is None else time.monotonic() + deadline_s
    )
    results: List[WindowResult] = []
    for shard, src, dst, _val, ts in windows_ts:
        results.extend(agg.push(src, dst, ts, shard=shard))
        if deadline is not None and time.monotonic() >= deadline:
            break
    results.extend(agg.finish())
    return results
