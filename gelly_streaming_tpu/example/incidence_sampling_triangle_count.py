"""Incidence-sampling triangle-count estimate CLI
(``example/IncidenceSamplingTriangleCount.java:38-60``)."""

from __future__ import annotations

from typing import List

from ..library.sampling import IncidenceSamplingTriangleCount
from .broadcast_triangle_count import (
    DEFAULT_SAMPLES,
    DEFAULT_VERTEX_COUNT,
    run as _run_shared,
)
from .common import default_chain_edges, read_edges, run_main, usage


def run(edges, vertex_count, samples, output_path=None):
    return _run_shared(
        edges, vertex_count, samples, output_path,
        estimator_cls=IncidenceSamplingTriangleCount,
    )


def main(args: List[str]) -> None:
    if args:
        if len(args) not in (3, 4):
            print(
                "Usage: incidence_sampling_triangle_count <input edges path> "
                "<vertex count> <samples> [output path]"
            )
            return
        edges = read_edges(args[0])
        run(edges, int(args[1]), int(args[2]), args[3] if len(args) > 3 else None)
    else:
        usage(
            "incidence_sampling_triangle_count",
            "<input edges path> <vertex count> <samples> [output path]",
        )
        run(default_chain_edges(), DEFAULT_VERTEX_COUNT, DEFAULT_SAMPLES)


if __name__ == "__main__":
    run_main(main)
