"""k-Spanner CLI (``example/SpannerExample.java:49-166``; default k=3 from
``:80``)."""

from __future__ import annotations

from typing import List, Optional

from ..core.stream import SimpleEdgeStream
from ..core.window import CountWindow
from ..library import Spanner
from .common import default_chain_edges, read_edges, run_main, usage, write_lines


def run(
    edges,
    window_size: int,
    k: int = 3,
    output_path: Optional[str] = None,
    device: bool = False,
):
    """``device=True`` runs the batched :class:`DeviceSpanner` (per-window
    k-reachability on device, zero mid-stream D2H) instead of the
    host-exact sequential fold — same k-spanner guarantee, may keep more
    edges (the documented windowing relaxation)."""
    stream = SimpleEdgeStream(edges, window=CountWindow(window_size))
    if device:
        from ..library.spanner import DeviceSpanner

        sp = DeviceSpanner(k=k)
        for _ in sp.run(stream):
            pass
        lines = sorted(f"{u} {v}" for u, v in sp.edges())
        write_lines(output_path, lines)
        return sp
    last = None
    for spanner in stream.aggregate(Spanner(k=k)):
        last = spanner
    lines = (
        sorted(f"{u} {v}" for u, v in last.edges()) if last is not None else []
    )
    write_lines(output_path, lines)
    return last


def main(args: List[str]) -> None:
    if args:
        device = "--device" in args
        args = [a for a in args if a != "--device"]
        if len(args) not in (3, 4):
            print(
                "Usage: spanner <input edges path> <merge window size (edges)> "
                "<k> [output path] [--device]"
            )
            return
        edges = read_edges(args[0])
        run(edges, int(args[1]), int(args[2]),
            args[3] if len(args) > 3 else None, device=device)
    else:
        usage(
            "spanner",
            "<input edges path> <merge window size (edges)> <k> [output path] "
            "[--device]",
        )
        run(default_chain_edges(), 100, 3)


if __name__ == "__main__":
    run_main(main)
