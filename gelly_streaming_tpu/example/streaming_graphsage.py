"""Streaming GraphSAGE encoder CLI (BASELINE config #5; no reference
analog). Embeds the accumulated graph once per window with random
features; output: the final embedding norms per vertex."""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.stream import SimpleEdgeStream
from ..core.window import CountWindow
from .common import default_chain_edges, read_edges, run_main, usage, write_lines


def run(
    edges,
    window_size: int,
    feature_dim: int = 32,
    output_path: Optional[str] = None,
    seed: int = 0,
):
    import jax

    from ..models.graphsage import StreamingGraphSAGE, init_graphsage

    params = init_graphsage(jax.random.PRNGKey(seed), [feature_dim, 64, 32])
    rng = np.random.default_rng(seed)
    verts = sorted({v for e in edges for v in e[:2]})
    feats = {v: rng.normal(size=feature_dim).astype(np.float32) for v in verts}
    stream = SimpleEdgeStream(edges, window=CountWindow(window_size))
    sage = StreamingGraphSAGE(params, feature_dim=feature_dim)
    out = None
    for out in sage.run(stream, feats):
        pass
    if out is None:  # empty stream: no windows, nothing to embed
        write_lines(output_path, [])
        return None
    norms = np.linalg.norm(np.asarray(out, np.float32), axis=1)
    vdict = stream.vertex_dict
    raw = vdict.decode(np.arange(len(norms)))
    write_lines(
        output_path,
        [f"({int(v)},{n:.4f})" for v, n in zip(raw, norms)],
    )
    return out


def main(args: List[str]) -> None:
    if args:
        if len(args) not in (2, 3):
            print(
                "Usage: streaming_graphsage <input edges path> "
                "<window size (edges)> [output path]"
            )
            return
        edges = read_edges(args[0])
        run(edges, int(args[1]), output_path=args[2] if len(args) > 2 else None)
    else:
        usage(
            "streaming_graphsage",
            "<input edges path> <window size (edges)> [output path]",
        )
        run(default_chain_edges(), 25)


if __name__ == "__main__":
    run_main(main)
