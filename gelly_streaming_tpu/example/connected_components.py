"""Streaming Connected Components CLI
(``example/ConnectedComponentsExample.java:49-169``).

The reference merges per-window DisjointSets and prints the flattened
component sets per print window; here each window emits the running
:class:`Components` summary and the last state per print interval is
written, one component per line (``root=[members]``, the DisjointSet
``toString`` format its test parses).
"""

from __future__ import annotations

from typing import List, Optional

from ..core.stream import SimpleEdgeStream
from ..core.window import CountWindow
from ..library import ConnectedComponents
from .common import default_chain_edges, read_edges, run_main, usage, write_lines


def run(edges, window_size: int, output_path: Optional[str] = None):
    stream = SimpleEdgeStream(edges, window=CountWindow(window_size))
    last = None
    for comps in stream.aggregate(ConnectedComponents()):
        last = comps
    lines = [
        f"{root}={members}"
        for root, members in sorted(last.components.items())
    ] if last else []
    write_lines(output_path, lines)
    return last


def main(args: List[str]) -> None:
    if args:
        if len(args) not in (2, 3):
            print(
                "Usage: connected_components <input edges path> "
                "<merge window size (edges)> [output path]"
            )
            return
        edges = read_edges(args[0])
        run(edges, int(args[1]), args[2] if len(args) > 2 else None)
    else:
        usage(
            "connected_components",
            "<input edges path> <merge window size (edges)> [output path]",
        )
        run(default_chain_edges(), 100)


if __name__ == "__main__":
    run_main(main)
