"""Streaming Connected Components CLI
(``example/ConnectedComponentsExample.java:49-169``).

The reference merges per-window DisjointSets and prints the flattened
component sets per print window; here each window emits the running
:class:`Components` summary and the last state per print interval is
written, one component per line (``root=[members]``, the DisjointSet
``toString`` format its test parses).
"""

from __future__ import annotations

from typing import List, Optional

from ..core.stream import SimpleEdgeStream
from ..core.window import CountWindow
from ..library import ConnectedComponents
from .common import (
    default_chain_edges,
    read_edges,
    run_main,
    supervised_emissions,
    usage,
    write_lines,
)


def run(
    edges,
    window_size: int,
    output_path: Optional[str] = None,
    checkpoint_path: Optional[str] = None,
    checkpoint_every=64,
    resume: bool = True,
):
    """``checkpoint_path`` enables transparent fault tolerance, now
    SUPERVISED (ISSUE 5 satellite): an atomic barrier every
    ``checkpoint_every`` windows (``"auto"`` tunes the cadence so
    barriers cost at most ~5% of wall time), restart-with-backoff on
    transient faults via the resilience layer's ``Supervisor``, and
    transparent restore — re-running the same command after a crash
    resumes from the last barrier and ends with identical output
    (``aggregate/autockpt.py`` + ``resilience/supervisor.py``; the
    reference gets this from Flink checkpointing plus its restart
    strategy, ``SummaryAggregation.java:127-135``). Resuming is the
    default (the crash-recovery contract); ``resume=False`` (CLI
    ``--fresh``) starts over, discarding any stale barrier at the
    path."""
    if checkpoint_path is not None:
        import time

        agg = ConnectedComponents()
        emissions, ac = supervised_emissions(
            checkpoint_path, checkpoint_every,
            lambda vd: SimpleEdgeStream(
                edges, window=CountWindow(window_size), vertex_dict=vd
            ),
            agg,
            resume=resume,
        )
        done = ac.windows_done()
        if done:
            print(f"resuming from barrier at window {done}")
        last = None
        t0 = time.perf_counter()
        for last in emissions:
            pass
        runtime_ms = (time.perf_counter() - t0) * 1000
        if last is None and done:
            # the barrier already covers the whole stream: emit the
            # restored summary instead of an empty re-run
            last = ac.restored_emission(agg)
        return _emit(last, output_path, runtime_ms)
    stream = SimpleEdgeStream(edges, window=CountWindow(window_size))
    return _drain(stream, output_path)


def _emit(last, output_path: Optional[str], runtime_ms: float):
    """Shared emission tail: BOTH the plain and checkpoint-resumed paths
    must format identically for the resume-parity guarantee to hold."""
    lines = [
        f"{root}={members}"
        for root, members in sorted(last.components.items())
    ] if last else []
    write_lines(output_path, lines)
    print(f"Runtime: {runtime_ms:.1f}")
    return last


def _drain(stream, output_path: Optional[str] = None):
    import time

    last = None
    t0 = time.perf_counter()
    for comps in stream.aggregate(ConnectedComponents()):
        last = comps
    runtime_ms = (time.perf_counter() - t0) * 1000
    return _emit(last, output_path, runtime_ms)


def run_corpus(
    name_or_path: str,
    window_size: int = 1 << 20,
    device_encode: bool = False,
    id_bound: int = 0,
    carry: str = "auto",
):
    """Stream a BASELINE corpus (by registry name or file path) through
    the flagship workload — the measured end-to-end path of bench.py as a
    runnable CLI. ``device_encode`` moves the vertex mapping onto the
    accelerator (dense-id corpora; pass the id bound); ``carry`` pins the
    CC carry strategy (auto/forest/host/dense —
    ``library/connected_components.py``)."""
    from .. import datasets

    if name_or_path in datasets.CORPORA:
        path, is_real = datasets.ensure_corpus(name_or_path)
        print(f"corpus: {path} ({'real' if is_real else 'surrogate'})")
    else:
        path = name_or_path
    kw = {}
    if device_encode:
        kw = dict(device_encode=True, min_vertex_capacity=id_bound)
    stream = datasets.stream_file(
        path, window=CountWindow(window_size), **kw
    )
    import time

    agg = ConnectedComponents(carry=carry)
    last = None
    t0 = time.perf_counter()
    for comps in stream.aggregate(agg):
        last = comps
    runtime_ms = (time.perf_counter() - t0) * 1000
    _emit(last, None, runtime_ms)
    if last is not None:
        print(f"components: {len(last.components)} (carry: {agg._cc_mode})")
    return last


def main(args: List[str]) -> None:
    if args and args[0] == "--corpus":
        # connected_components --corpus livejournal [window]
        #   [--device-encode id_bound] [--carry auto|forest|host|dense]
        rest = args[1:]
        name = rest[0] if rest else "livejournal"
        window = int(rest[1]) if len(rest) > 1 and rest[1].isdigit() else 1 << 20
        dev = "--device-encode" in rest
        bound = int(rest[rest.index("--device-encode") + 1]) if dev else 0
        carry = (
            rest[rest.index("--carry") + 1] if "--carry" in rest else "auto"
        )
        run_corpus(name, window, device_encode=dev, id_bound=bound,
                   carry=carry)
        return
    if args:
        usage_line = (
            "Usage: connected_components [--corpus <name|path> [window] "
            "[--device-encode <id bound>]] | <input edges path> "
            "<merge window size (edges)> [output path] "
            "[--checkpoint <path> | --checkpoint-dir <dir>] "
            "[--every <windows|auto>] [--resume | --fresh]"
        )
        try:
            from .common import checkpoint_path_in, parse_checkpoint_flags

            args, spec = parse_checkpoint_flags(args)
            ckpt = every = None
            resume = True
            if spec is not None:
                ckpt = checkpoint_path_in(spec, "cc.ckpt")
                every = spec["every"]
                resume = spec["resume"]
            if len(args) not in (2, 3):
                print(usage_line)
                return
            window = int(args[1])
        except (IndexError, ValueError):
            print(usage_line)
            return
        edges = read_edges(args[0])
        run(edges, window, args[2] if len(args) > 2 else None,
            checkpoint_path=ckpt,
            checkpoint_every=64 if every is None else every,
            resume=resume)
    else:
        usage(
            "connected_components",
            "[--corpus <name|path> [window] [--device-encode <id bound>]] | "
            "<input edges path> <merge window size (edges)> [output path]",
        )
        run(default_chain_edges(), 100)


if __name__ == "__main__":
    run_main(main)
