"""Streaming Connected Components CLI
(``example/ConnectedComponentsExample.java:49-169``).

The reference merges per-window DisjointSets and prints the flattened
component sets per print window; here each window emits the running
:class:`Components` summary and the last state per print interval is
written, one component per line (``root=[members]``, the DisjointSet
``toString`` format its test parses).
"""

from __future__ import annotations

from typing import List, Optional

from ..core.stream import SimpleEdgeStream
from ..core.window import CountWindow
from ..library import ConnectedComponents
from .common import default_chain_edges, read_edges, run_main, usage, write_lines


def run(edges, window_size: int, output_path: Optional[str] = None):
    stream = SimpleEdgeStream(edges, window=CountWindow(window_size))
    return _drain(stream, output_path)


def _drain(stream, output_path: Optional[str] = None):
    import time

    last = None
    t0 = time.perf_counter()
    for comps in stream.aggregate(ConnectedComponents()):
        last = comps
    runtime_ms = (time.perf_counter() - t0) * 1000
    lines = [
        f"{root}={members}"
        for root, members in sorted(last.components.items())
    ] if last else []
    write_lines(output_path, lines)
    print(f"Runtime: {runtime_ms:.1f}")
    return last


def run_corpus(
    name_or_path: str,
    window_size: int = 1 << 20,
    device_encode: bool = False,
    id_bound: int = 0,
):
    """Stream a BASELINE corpus (by registry name or file path) through
    the flagship workload — the measured end-to-end path of bench.py as a
    runnable CLI. ``device_encode`` moves the vertex mapping onto the
    accelerator (dense-id corpora; pass the id bound)."""
    from .. import datasets

    if name_or_path in datasets.CORPORA:
        path, is_real = datasets.ensure_corpus(name_or_path)
        print(f"corpus: {path} ({'real' if is_real else 'surrogate'})")
    else:
        path = name_or_path
    kw = {}
    if device_encode:
        kw = dict(device_encode=True, min_vertex_capacity=id_bound)
    stream = datasets.stream_file(
        path, window=CountWindow(window_size), **kw
    )
    last = _drain(stream)
    if last is not None:
        print(f"components: {len(last.components)}")
    return last


def main(args: List[str]) -> None:
    if args and args[0] == "--corpus":
        # connected_components --corpus livejournal [window] [--device-encode id_bound]
        rest = args[1:]
        name = rest[0] if rest else "livejournal"
        window = int(rest[1]) if len(rest) > 1 and rest[1].isdigit() else 1 << 20
        dev = "--device-encode" in rest
        bound = int(rest[rest.index("--device-encode") + 1]) if dev else 0
        run_corpus(name, window, device_encode=dev, id_bound=bound)
        return
    if args:
        if len(args) not in (2, 3):
            print(
                "Usage: connected_components [--corpus <name|path> [window] "
                "[--device-encode <id bound>]] | <input edges path> "
                "<merge window size (edges)> [output path]"
            )
            return
        edges = read_edges(args[0])
        run(edges, int(args[1]), args[2] if len(args) > 2 else None)
    else:
        usage(
            "connected_components",
            "[--corpus <name|path> [window] [--device-encode <id bound>]] | "
            "<input edges path> <merge window size (edges)> [output path]",
        )
        run(default_chain_edges(), 100)


if __name__ == "__main__":
    run_main(main)
