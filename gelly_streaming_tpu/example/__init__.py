"""Example programs (the reference's L6 layer, ``example/`` — 10 CLI
programs, ``SURVEY.md`` §2.4) plus the two BASELINE additions
(incremental PageRank, streaming GraphSAGE)."""
