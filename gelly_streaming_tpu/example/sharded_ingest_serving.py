"""Live sharded ingest feeding an aggregation + serving stack.

PR 11 built the million-writes path (``core/ingest.py``:
``ShardedEdgeSource`` — N TCP connections partitioned by the
``shard_of`` endpoint hash, GSEW binary wire, bounded-queue
backpressure) but only the bench consumed it. This example closes that
residual: the SAME sharded wire feeds a LIVE ``ConnectedComponents``
aggregation whose summary is served by a ``StreamServer`` while the
connections are still streaming — writes arrive over N sockets, reads
are answered from the freshest published snapshot, one process.

The peer half is the serve-from-memory load generator
(``core/ingest.py:serve_blobs``): the stream is synthesized, split with
``shard_of`` (the one partition rule), pre-encoded as GSEW frames, and
served one shard per port.

Usage::

    python -m gelly_streaming_tpu.example.sharded_ingest_serving \
        [nshards] [window_size] [n_edges] [u,v ...]
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..core.ingest import (
    ShardedEdgeSource,
    ShardedEdgeStream,
    encode_shard_frames,
    partition_edges,
    serve_blobs,
)
from ..datasets import IdentityDict
from ..library import ConnectedComponents
from ..serving import ConnectedQuery, StreamServer
from .common import run_main, usage


def run(
    nshards: int = 2,
    window_size: int = 256,
    n_edges: int = 1 << 14,
    queries: Optional[Sequence[Tuple[int, int]]] = None,
    n_vertices: int = 1 << 10,
    seed: int = 23,
) -> List[str]:
    """Returns the printed lines (tests call this directly)."""
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n_vertices, n_edges, dtype=np.int64)
    dst = rng.integers(0, n_vertices, n_edges, dtype=np.int64)
    if queries is None:
        pairs = rng.integers(0, n_vertices, (4, 2))
        queries = [(int(a), int(b)) for a, b in pairs]

    parts = partition_edges(src, dst, None, nshards)
    blobs = [encode_shard_frames(s, d) for s, d, _v in parts]
    ports, threads, stop = serve_blobs(blobs)
    lines: List[str] = []
    try:
        source = ShardedEdgeSource(
            [("127.0.0.1", p) for p in ports], window=window_size
        )
        stream = ShardedEdgeStream(
            source, vertex_dict=IdentityDict(n_vertices)
        )
        agg = ConnectedComponents()
        with StreamServer(agg.servable(), stream) as server:
            # live phase: ask while the sharded wire is still ingesting
            for u, v in queries:
                ans = server.ask(ConnectedQuery(u, v), timeout=120)
                lines.append(
                    f"live connected({u},{v}) = {bool(ans.value)} "
                    f"[window {ans.window}, staleness {ans.staleness}]"
                )
            server.join(600)  # all shard connections drained
            for u, v in queries:
                ans = server.ask(ConnectedQuery(u, v), timeout=120)
                lines.append(
                    f"final connected({u},{v}) = {bool(ans.value)} "
                    f"[window {ans.window}]"
                )
            stats = server.stats.snapshot()
            q = stats["queries"].get("ConnectedQuery", {})
            lines.append(
                f"served {q.get('count', 0)} queries over "
                f"{nshards}-shard live ingest "
                f"(p50={q.get('p50_ms', 0.0):.2f}ms)"
            )
    finally:
        stop.set()
        for t in threads:
            t.join(10)
    return lines


def main(argv: List[str]) -> None:
    if not argv:
        usage("ShardedIngestServing",
              "[nshards] [window_size] [n_edges] [u,v ...]")
    nshards = int(argv[0]) if argv else 2
    window = int(argv[1]) if len(argv) > 1 else 256
    n_edges = int(argv[2]) if len(argv) > 2 else 1 << 14
    queries = [
        tuple(int(x) for x in q.split(","))[:2] for q in argv[3:]
    ] or None
    for line in run(nshards, window, n_edges, queries):
        print(line)


if __name__ == "__main__":
    run_main(main)
