"""Bipartiteness check CLI (``example/BipartitenessCheckExample.java:40-125``)."""

from __future__ import annotations

from typing import List, Optional

from ..core.stream import SimpleEdgeStream
from ..core.window import CountWindow
from ..library import BipartitenessCheck
from .common import default_chain_edges, read_edges, run_main, usage, write_lines


def run(edges, window_size: int, output_path: Optional[str] = None):
    stream = SimpleEdgeStream(edges, window=CountWindow(window_size))
    last = None
    for cand in stream.aggregate(BipartitenessCheck()):
        last = cand
    write_lines(output_path, [str(last)] if last is not None else [])
    return last


def main(args: List[str]) -> None:
    if args:
        if len(args) not in (2, 3):
            print(
                "Usage: bipartiteness_check <input edges path> "
                "<merge window size (edges)> [output path]"
            )
            return
        edges = read_edges(args[0])
        run(edges, int(args[1]), args[2] if len(args) > 2 else None)
    else:
        usage(
            "bipartiteness_check",
            "<input edges path> <merge window size (edges)> [output path]",
        )
        run(default_chain_edges(), 100)


if __name__ == "__main__":
    run_main(main)
