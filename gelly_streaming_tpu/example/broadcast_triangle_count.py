"""Broadcast triangle-count estimate CLI
(``example/BroadcastTriangleCount.java:180-230``; defaults
vertexCount=1000, samples=10000 from ``:216-217``)."""

from __future__ import annotations

from typing import List, Optional

from ..library.sampling import BroadcastTriangleCount
from .common import default_chain_edges, read_edges, run_main, usage, write_lines

DEFAULT_VERTEX_COUNT = 1000
DEFAULT_SAMPLES = 10000


def run(
    edges,
    vertex_count: int,
    samples: int,
    output_path: Optional[str] = None,
    estimator_cls=BroadcastTriangleCount,
):
    est = estimator_cls(vertex_count=vertex_count, samples=samples)
    results = list(est.run(edges))
    write_lines(output_path, [f"({m},{e})" for m, e in results])
    return results


def main(args: List[str]) -> None:
    if args:
        if len(args) not in (3, 4):
            print(
                "Usage: broadcast_triangle_count <input edges path> "
                "<vertex count> <samples> [output path]"
            )
            return
        edges = read_edges(args[0])
        run(edges, int(args[1]), int(args[2]), args[3] if len(args) > 3 else None)
    else:
        usage(
            "broadcast_triangle_count",
            "<input edges path> <vertex count> <samples> [output path]",
        )
        run(default_chain_edges(), DEFAULT_VERTEX_COUNT, DEFAULT_SAMPLES)


if __name__ == "__main__":
    run_main(main)
