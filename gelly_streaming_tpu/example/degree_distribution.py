"""Dynamic degree distribution CLI
(``example/DegreeDistribution.java:42-73``). Input lines: ``src trg +`` /
``src trg -``; output: ``(degree,count)`` change lines per window."""

from __future__ import annotations

from typing import List, Optional

from ..core.window import CountWindow
from ..library.degrees import DegreeDistribution
from .common import read_edges, run_main, usage, write_lines


def run(events, window_size: int, output_path: Optional[str] = None):
    dd = DegreeDistribution(CountWindow(window_size))
    lines = []
    for changes in dd.run(events):
        lines.extend(f"({d},{c})" for d, c in changes)
    write_lines(output_path, lines)
    return dd


def main(args: List[str]) -> None:
    if args:
        if len(args) not in (2, 3):
            print(
                "Usage: degree_distribution <input events path> "
                "<window size (events)> [output path]"
            )
            return
        events = read_edges(args[0], n_fields=3, val_fn=str)
        run(events, int(args[1]), args[2] if len(args) > 2 else None)
    else:
        usage(
            "degree_distribution",
            "<input events path> <window size (events)> [output path]",
        )
        run([(1, 2, "+"), (2, 3, "+"), (1, 3, "+"), (2, 3, "-")], 1)


if __name__ == "__main__":
    run_main(main)
