"""Exact streaming triangle count CLI
(``example/ExactTriangleCount.java:44-66``). Output: the final
``(vertex,count)`` lines, vertex -1 being the global total."""

from __future__ import annotations

from typing import List, Optional

from ..core.stream import SimpleEdgeStream
from ..core.window import CountWindow
from ..library.triangles import ExactTriangleCount
from .common import default_chain_edges, read_edges, run_main, usage, write_lines


def run(edges, window_size: int, output_path: Optional[str] = None):
    stream = SimpleEdgeStream(edges, window=CountWindow(window_size))
    final = {}
    for emissions in ExactTriangleCount().run(stream):
        final.update(dict(emissions))
    lines = [f"({v},{c})" for v, c in sorted(final.items())]
    write_lines(output_path, lines)
    return final


def main(args: List[str]) -> None:
    if args:
        if len(args) not in (2, 3):
            print(
                "Usage: exact_triangle_count <input edges path> "
                "<window size (edges)> [output path]"
            )
            return
        edges = read_edges(args[0])
        run(edges, int(args[1]), args[2] if len(args) > 2 else None)
    else:
        usage(
            "exact_triangle_count",
            "<input edges path> <window size (edges)> [output path]",
        )
        run(default_chain_edges(), 100)


if __name__ == "__main__":
    run_main(main)
