"""Live query serving demo: streaming CC + concurrent point queries.

No reference analog — gelly-streaming's summaries are write-only. This
example runs the flagship CC aggregation behind a
:class:`~gelly_streaming_tpu.serving.server.StreamServer` and answers
``connected(u, v)`` / component-size point queries WHILE the stream
ingests, printing each answer with the snapshot window and staleness it
was served at, then the per-class latency stats.

Usage::

    python -m gelly_streaming_tpu.example.serving_queries \
        [edge_file] [window_size] [u,v ...]
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..core.stream import SimpleEdgeStream
from ..core.window import CountWindow
from ..library import ConnectedComponents
from ..serving import ComponentSizeQuery, ConnectedQuery, StreamServer
from .common import default_chain_edges, read_edges, run_main, usage


def run(
    edges,
    window_size: int,
    queries: Optional[List[Tuple[int, int]]] = None,
) -> List[str]:
    stream = SimpleEdgeStream(edges, window=CountWindow(window_size))
    agg = ConnectedComponents()
    if queries is None:
        # default chain data: 1 and 5 share the odd chain; 1 and 2 never
        # connect (odd vs even chain)
        queries = [(1, 5), (1, 2), (2, 6)]
    lines: List[str] = []
    with StreamServer(agg.servable(), stream) as server:
        # live phase: ask while ingest runs (answers carry staleness)
        for u, v in queries:
            ans = server.ask(ConnectedQuery(u, v), timeout=60)
            lines.append(
                f"live connected({u},{v}) = {bool(ans.value)} "
                f"[window {ans.window}, staleness {ans.staleness}]"
            )
        server.join(600)  # stream end: answers now staleness-0
        for u, v in queries:
            ans = server.ask(ConnectedQuery(u, v), timeout=60)
            size = server.ask(ComponentSizeQuery(u), timeout=60)
            lines.append(
                f"final connected({u},{v}) = {bool(ans.value)}, "
                f"|component({u})| = {int(size.value)} "
                f"[window {ans.window}]"
            )
        stats = server.stats.snapshot()
        for qcls, s in sorted(stats["queries"].items()):
            lines.append(
                f"{qcls}: n={s['count']} p50={s['p50_ms']:.2f}ms "
                f"p99={s['p99_ms']:.2f}ms "
                f"staleness_max={s['staleness_max']}"
            )
    return lines


def main(argv: List[str]) -> None:
    if argv:
        edge_path = argv[0]
        window = int(argv[1]) if len(argv) > 1 else 64
        queries = [
            tuple(int(x) for x in q.split(","))[:2] for q in argv[2:]
        ] or None
        edges = read_edges(edge_path)
    else:
        usage("ServingQueries", "[edge_file] [window_size] [u,v ...]")
        edges = default_chain_edges()
        window = 16
        queries = None
    for line in run(edges, window, queries):
        print(line)


if __name__ == "__main__":
    run_main(main)
