"""Iterative (label-emitting) CC CLI
(``example/IterativeConnectedComponents.java:52-63``). Output:
``(vertex,componentId)`` corrected-label lines."""

from __future__ import annotations

from typing import List, Optional

from ..core.stream import SimpleEdgeStream
from ..core.window import CountWindow
from ..library.iterative_cc import IterativeConnectedComponents
from .common import default_chain_edges, read_edges, run_main, usage, write_lines


def run(edges, window_size: int, output_path: Optional[str] = None):
    stream = SimpleEdgeStream(edges, window=CountWindow(window_size))
    icc = IterativeConnectedComponents()
    lines = []
    for changed in icc.run(stream):
        lines.extend(f"({v},{c})" for v, c in changed)
    write_lines(output_path, lines)
    return icc


def main(args: List[str]) -> None:
    if args:
        if len(args) not in (2, 3):
            print(
                "Usage: iterative_connected_components <input edges path> "
                "<window size (edges)> [output path]"
            )
            return
        edges = read_edges(args[0])
        run(edges, int(args[1]), args[2] if len(args) > 2 else None)
    else:
        usage(
            "iterative_connected_components",
            "<input edges path> <window size (edges)> [output path]",
        )
        run(default_chain_edges(), 10)


if __name__ == "__main__":
    run_main(main)
