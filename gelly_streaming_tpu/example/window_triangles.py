"""Window triangle count CLI (``example/WindowTriangles.java:40-160``).

Input lines: ``src trg timestamp`` (event time, like the reference's
``AscendingTimestampExtractor`` path); output lines ``(count,windowMaxTs)``
— the format ``WindowTrianglesITCase`` compares.
"""

from __future__ import annotations

from typing import List, Optional

from ..core.window import EventTimeWindow
from ..library.triangles import WindowTriangles
from .common import default_chain_edges, read_edges, run_main, usage, write_lines


def run(edges, window_time: float, output_path: Optional[str] = None):
    wt = WindowTriangles(EventTimeWindow(window_time, timestamp_fn=lambda e: e[2]))
    results = list(wt.run(edges))
    write_lines(output_path, [f"({c},{int(ts)})" for c, ts in results])
    return results


def main(args: List[str]) -> None:
    if args:
        if len(args) != 3:
            print(
                "Usage: window_triangles <input edges path> <output path> "
                "<window time>"
            )
            return
        edges = read_edges(args[0], n_fields=3)
        run(edges, float(args[2]), args[1])
    else:
        usage("window_triangles", "<input edges path> <output path> <window time>")
        run(default_chain_edges(), 300.0)


if __name__ == "__main__":
    run_main(main)
