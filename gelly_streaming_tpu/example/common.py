"""Shared CLI plumbing for the example programs (the L6 layer).

Mirrors the reference examples' conventions (e.g.
``example/ConnectedComponentsExample.java:81-102``): positional args, no
args -> built-in default data plus a usage message, results written to a
file when an output path is given, printed otherwise.
"""

from __future__ import annotations

import sys
from typing import Iterable, List, Optional, Tuple


def read_edges(path: str, n_fields: int = 2, val_fn=float) -> List[Tuple]:
    """Parse a whitespace-separated edge file (the reference's
    ``s.split("\\s")`` mappers). ``n_fields=3`` keeps a value/timestamp
    column parsed with ``val_fn``."""
    rows = []
    with open(path) as f:
        for line in f:
            parts = line.split()
            if not parts:
                continue
            if n_fields == 2:
                rows.append((int(parts[0]), int(parts[1]), 0.0))
            else:
                rows.append((int(parts[0]), int(parts[1]), val_fn(parts[2])))
    return rows


def write_lines(output_path: Optional[str], lines: Iterable[str]) -> None:
    """Write one result per line to the path, or print (reference
    ``writeAsText`` / ``print()`` split)."""
    if output_path is None:
        for line in lines:
            print(line)
    else:
        with open(output_path, "w") as f:
            for line in lines:
                f.write(line + "\n")


def usage(name: str, params: str) -> None:
    print(f"Executing {name} example with default parameters and built-in default data.")
    print("  Provide parameters to read input data from files.")
    print(f"  Usage: {name} {params}")


def default_chain_edges(n: int = 100) -> List[Tuple]:
    """The reference examples' built-in data: edges (k, k+2) for k=1..n
    (``ConnectedComponentsExample.java:120-130``) — two odd/even chains."""
    return [(k, k + 2, float(k * 100)) for k in range(1, n + 1)]


def parse_checkpoint_flags(args: List[str]):
    """Extract the shared fault-tolerance flags from an example CLI's
    argument list (the ISSUE 5 satellite surface — every example gets
    crash survival out of the box):

    ``--checkpoint <path>``      barrier file path (legacy spelling)
    ``--checkpoint-dir <dir>``   barriers under ``<dir>/<name>.ckpt``
    ``--every <n|auto>``         barrier cadence (``auto`` tunes from
                                 measured barrier cost, ≤5% of wall time)
    ``--resume``                 resume from an existing barrier — the
                                 DEFAULT (re-running the same command
                                 after a crash continues where it died);
                                 the flag exists to make scripts explicit
    ``--fresh``                  start over: discard any barrier already
                                 at the path instead of resuming it

    Returns ``(remaining_args, spec)`` where ``spec`` is None when no
    checkpoint flag was given, else a dict with ``path``/``every``/
    ``resume``; ``path`` is None for ``--checkpoint-dir`` until the
    caller names it via :func:`checkpoint_path_in`.
    """
    args = list(args)
    spec = {"path": None, "dir": None, "every": 64, "resume": True}
    seen = False
    for flag, key in (("--checkpoint", "path"), ("--checkpoint-dir", "dir")):
        if flag in args:
            i = args.index(flag)
            if i + 1 >= len(args):
                raise ValueError(f"{flag} requires a value")
            spec[key] = args[i + 1]
            del args[i:i + 2]
            seen = True
    modifier = None
    if "--every" in args:
        i = args.index("--every")
        if i + 1 >= len(args):
            raise ValueError("--every requires a value")
        val = args[i + 1]
        spec["every"] = "auto" if val == "auto" else int(val)
        del args[i:i + 2]
        modifier = "--every"
    if "--resume" in args:
        spec["resume"] = True
        args.remove("--resume")
        modifier = "--resume"
    if "--fresh" in args:
        spec["resume"] = False
        args.remove("--fresh")
        modifier = "--fresh"
    if modifier is not None and not seen:
        # consuming the modifier while dropping the spec would silently
        # run WITHOUT the fault tolerance the user asked to configure
        raise ValueError(
            f"{modifier} requires --checkpoint or --checkpoint-dir"
        )
    return args, (spec if seen else None)


def checkpoint_path_in(spec: dict, name: str) -> str:
    """Resolve the barrier path for one example from a parsed spec
    (``--checkpoint`` wins; ``--checkpoint-dir`` appends ``name``)."""
    if spec["path"] is not None:
        return spec["path"]
    import os

    os.makedirs(spec["dir"], exist_ok=True)
    return os.path.join(spec["dir"], name)


def supervised_emissions(path: str, every, make_stream, work,
                         resume: bool = True):
    """Run a checkpointed workload under the resilience layer's
    :class:`~gelly_streaming_tpu.resilience.Supervisor`: barriers every
    ``every`` windows (``"auto"`` tunes the cadence from measured
    barrier cost), transparent restore from the newest valid barrier,
    restart-with-backoff on transient faults, replayed windows deduped —
    the example survives a kill out of the box; re-running the same
    command finishes with identical output. Returns
    ``(emissions_iterator, checkpoint)``; ``checkpoint.restored_vdict``
    / ``restored_emission`` serve the resumed-past-the-end case.

    ``resume=False`` discards any barrier already at ``path`` (and its
    rotation slots) so a fresh run never silently continues a stale
    one."""
    import os

    from ..aggregate.autockpt import AutoCheckpoint
    from ..resilience import Supervisor

    parent = os.path.dirname(path)
    if parent:
        # a missing directory would otherwise surface as a confusing
        # poison-window loop (every barrier write fails identically)
        os.makedirs(parent, exist_ok=True)
    ac = AutoCheckpoint(path, every=every)
    if not resume:
        # the checkpoint owns its on-disk layout: discard() removes
        # ONLY this checkpoint's artifacts, never a sibling that merely
        # shares the path as a prefix
        ac.discard()
    sup = Supervisor(ac)
    return sup.run(make_stream, work), ac


def run_main(main_fn):
    """python -m entry point."""
    main_fn(sys.argv[1:])
