"""Shared CLI plumbing for the example programs (the L6 layer).

Mirrors the reference examples' conventions (e.g.
``example/ConnectedComponentsExample.java:81-102``): positional args, no
args -> built-in default data plus a usage message, results written to a
file when an output path is given, printed otherwise.
"""

from __future__ import annotations

import sys
from typing import Iterable, List, Optional, Sequence, Tuple


def read_edges(path: str, n_fields: int = 2, val_fn=float) -> List[Tuple]:
    """Parse a whitespace-separated edge file (the reference's
    ``s.split("\\s")`` mappers). ``n_fields=3`` keeps a value/timestamp
    column parsed with ``val_fn``."""
    rows = []
    with open(path) as f:
        for line in f:
            parts = line.split()
            if not parts:
                continue
            if n_fields == 2:
                rows.append((int(parts[0]), int(parts[1]), 0.0))
            else:
                rows.append((int(parts[0]), int(parts[1]), val_fn(parts[2])))
    return rows


def write_lines(output_path: Optional[str], lines: Iterable[str]) -> None:
    """Write one result per line to the path, or print (reference
    ``writeAsText`` / ``print()`` split)."""
    if output_path is None:
        for line in lines:
            print(line)
    else:
        with open(output_path, "w") as f:
            for line in lines:
                f.write(line + "\n")


def usage(name: str, params: str) -> None:
    print(f"Executing {name} example with default parameters and built-in default data.")
    print("  Provide parameters to read input data from files.")
    print(f"  Usage: {name} {params}")


def default_chain_edges(n: int = 100) -> List[Tuple]:
    """The reference examples' built-in data: edges (k, k+2) for k=1..n
    (``ConnectedComponentsExample.java:120-130``) — two odd/even chains."""
    return [(k, k + 2, float(k * 100)) for k in range(1, n + 1)]


def run_main(main_fn):
    """python -m entry point."""
    main_fn(sys.argv[1:])
