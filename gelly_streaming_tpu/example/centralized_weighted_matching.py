"""Centralized weighted matching CLI
(``example/CentralizedWeightedMatching.java:41-64``). Input lines:
``src trg weight``; output: ADD/REMOVE events then the final matching
weight (the reference prints events and runtime)."""

from __future__ import annotations

import time
from typing import List, Optional

from ..library.matching import CentralizedWeightedMatching
from .common import read_edges, run_main, usage, write_lines


def run(edges, output_path: Optional[str] = None):
    m = CentralizedWeightedMatching()
    t0 = time.perf_counter()
    lines = [
        f"({e.type.name},({e.edge.src},{e.edge.dst},{e.edge.val}))"
        for e in m.run(edges)
    ]
    runtime_ms = (time.perf_counter() - t0) * 1000
    lines.append(f"Matching weight: {m.total_weight()}")
    write_lines(output_path, lines)
    print(f"Runtime: {runtime_ms:.1f}")  # getNetRuntime analog (:62-64)
    return m


def main(args: List[str]) -> None:
    if args and args[0] == "--movielens":
        # the reference's dataset for this workload
        # (CentralizedWeightedMatching.java:41-44 reads movielens_10k_sorted):
        # real u.data under $GELLY_DATA/./data when present, else the
        # cached surrogate
        from .. import datasets

        path = args[1] if len(args) > 1 else datasets.ensure_corpus(
            "movielens-100k"
        )[0]
        u, i, r = datasets.load_movielens(path)
        run(zip(u.tolist(), i.tolist(), r.tolist()))
        return
    if args:
        if len(args) not in (1, 2):
            print(
                "Usage: centralized_weighted_matching "
                "[--movielens [u.data path] | <input edges path> "
                "[output path]]"
            )
            return
        edges = read_edges(args[0], n_fields=3)
        run(edges, args[1] if len(args) > 1 else None)
    else:
        usage(
            "centralized_weighted_matching",
            "[--movielens [u.data path] | <input edges path> [output path]]",
        )
        run([(1, 2, 10.0), (2, 3, 25.0), (3, 4, 15.0)])


if __name__ == "__main__":
    run_main(main)
