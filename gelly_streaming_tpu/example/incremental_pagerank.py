"""Incremental PageRank CLI (BASELINE config #4; no reference analog).
Output: final ``(vertex,rank)`` lines, 6 decimals."""

from __future__ import annotations

from typing import List, Optional

from ..core.stream import SimpleEdgeStream
from ..core.window import CountWindow
from ..library.pagerank import IncrementalPageRank
from .common import default_chain_edges, read_edges, run_main, usage, write_lines


def run(edges, window_size: int, output_path: Optional[str] = None):
    stream = SimpleEdgeStream(edges, window=CountWindow(window_size))
    pr = IncrementalPageRank()
    for emission in pr.run(stream):
        pass
    ranks = pr.ranks()
    write_lines(
        output_path, [f"({v},{r:.6f})" for v, r in sorted(ranks.items())]
    )
    return pr


def main(args: List[str]) -> None:
    if args:
        if len(args) not in (2, 3):
            print(
                "Usage: incremental_pagerank <input edges path> "
                "<window size (edges)> [output path]"
            )
            return
        edges = read_edges(args[0])
        run(edges, int(args[1]), args[2] if len(args) > 2 else None)
    else:
        usage(
            "incremental_pagerank",
            "<input edges path> <window size (edges)> [output path]",
        )
        run(default_chain_edges(), 25)


if __name__ == "__main__":
    run_main(main)
