"""Incremental PageRank CLI (BASELINE config #4; no reference analog).
Output: final ``(vertex,rank)`` lines, 6 decimals."""

from __future__ import annotations

from typing import List, Optional

from ..core.stream import SimpleEdgeStream
from ..core.window import CountWindow
from ..library.pagerank import IncrementalPageRank
from .common import default_chain_edges, read_edges, run_main, usage, write_lines


def run(edges, window_size: int, output_path: Optional[str] = None):
    stream = SimpleEdgeStream(edges, window=CountWindow(window_size))
    pr = IncrementalPageRank()
    for emission in pr.run(stream):
        pass
    ranks = pr.ranks()
    write_lines(
        output_path, [f"({v},{r:.6f})" for v, r in sorted(ranks.items())]
    )
    return pr


def run_corpus(name_or_path: str, window_size: int = 1 << 18):
    """Rank a BASELINE corpus (registry name or edge file) end to end."""
    import time

    from .. import datasets

    if name_or_path in datasets.CORPORA:
        path, is_real = datasets.ensure_corpus(name_or_path)
        print(f"corpus: {path} ({'real' if is_real else 'surrogate'})")
    else:
        path = name_or_path
    stream = datasets.stream_file(path, window=CountWindow(window_size))
    pr = IncrementalPageRank()
    t0 = time.perf_counter()
    for _ in pr.run(stream):
        pass
    ranks = pr.ranks()  # materializes (syncs) the final fixpoint
    print(f"Runtime: {(time.perf_counter() - t0) * 1000:.1f}")
    top = sorted(ranks.items(), key=lambda kv: -kv[1])[:10]
    for v, r in top:
        print(f"({v},{r:.6f})")
    return pr


def main(args: List[str]) -> None:
    if args and args[0] == "--corpus":
        rest = args[1:]
        name = rest[0] if rest else "livejournal"
        window = int(rest[1]) if len(rest) > 1 else 1 << 18
        run_corpus(name, window)
        return
    if args:
        if len(args) not in (2, 3):
            print(
                "Usage: incremental_pagerank [--corpus <name|path> "
                "[window]] | <input edges path> <window size (edges)> "
                "[output path]"
            )
            return
        edges = read_edges(args[0])
        run(edges, int(args[1]), args[2] if len(args) > 2 else None)
    else:
        usage(
            "incremental_pagerank",
            "[--corpus <name|path> [window]] | <input edges path> "
            "<window size (edges)> [output path]",
        )
        run(default_chain_edges(), 25)


if __name__ == "__main__":
    run_main(main)
