"""Per-window CSR construction and dense neighborhood materialization.

The reference gives ``applyOnNeighbors`` UDFs an ``Iterable`` over a vertex's
whole windowed neighborhood (``SnapshotStream.java:129-181``) — per-key
iteration that has no efficient TPU analog. The TPU-native form: sort the
window's edge block by vertex, derive ``row_ptr`` with ``searchsorted``
(CSR), and scatter neighbors into a padded ``[num_vertices, max_degree]``
matrix that a ``vmap``-ed UDF consumes with a validity mask.

``max_degree`` is static (host-bucketed) — the price of dense shapes; windows
with skewed degree distributions should prefer the segment-reduce paths
(``ops/segment.py``), which never materialize neighborhoods.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import jax
import jax.numpy as jnp

from .segment import segment_count, sort_by_segment


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class CSR:
    """Sorted-edge CSR view of one window's edge block.

    ``sorted_key``/``sorted_nbr``/``sorted_val``/``sorted_mask`` are the edge
    arrays stable-sorted by key vertex (padding last); ``row_ptr[v]`` is the
    first index of vertex ``v``'s run (length ``num_vertices+1``);
    ``degree[v]`` its run length.
    """

    sorted_key: jax.Array
    sorted_nbr: jax.Array
    sorted_val: Any
    sorted_mask: jax.Array
    row_ptr: jax.Array
    degree: jax.Array

    @property
    def num_vertices(self) -> int:
        return int(self.degree.shape[0])


def build_csr(
    key: jax.Array,
    nbr: jax.Array,
    val: Any,
    mask: jax.Array,
    num_vertices: int,
) -> CSR:
    """Sort one window's edges by key vertex and derive CSR offsets."""
    sorted_key, sorted_mask, sorted_nbr, sorted_val = sort_by_segment(key, mask, nbr, val)
    seg = jnp.arange(num_vertices + 1, dtype=sorted_key.dtype)
    row_ptr = jnp.searchsorted(sorted_key, seg, side="left")
    degree = segment_count(key, mask, num_vertices)
    return CSR(sorted_key, sorted_nbr, sorted_val, sorted_mask, row_ptr, degree)


def dense_neighbors(csr: CSR, max_degree: int) -> Tuple[jax.Array, Any, jax.Array]:
    """Materialize padded per-vertex neighbor rows from a CSR.

    Returns ``(nbr_mat[V, D], val_mat[V, D], valid[V, D])`` where D is the
    static ``max_degree`` bucket. Entries beyond a vertex's degree are
    masked False. Vertices with degree > D are truncated (callers bucket D
    from the true max degree, so this only happens when explicitly capped).
    """
    V = csr.num_vertices
    idx = csr.row_ptr[:V, None] + jnp.arange(max_degree)[None, :]
    valid = idx < csr.row_ptr[1 : V + 1, None]
    idx = jnp.clip(idx, 0, csr.sorted_key.shape[0] - 1)
    nbr_mat = csr.sorted_nbr[idx]
    val_mat = jax.tree.map(lambda a: a[idx], csr.sorted_val)
    return nbr_mat, val_mat, valid


def dense_neighbors_subset(
    csr: CSR, vids: jax.Array, max_degree: int
) -> Tuple[jax.Array, Any, jax.Array]:
    """Padded neighbor rows for SELECTED vertices only: ``[T, D]``.

    The degree-class path of ``apply_on_neighbors``: vertices are grouped
    by degree bucket and each class materializes rows only as wide as its
    own bucket, so one hub no longer sizes the whole window's dense rows
    (total work sum_v bucket(deg v) <= ~4E instead of V * max_degree).
    """
    starts = csr.row_ptr[vids]
    idx = starts[:, None] + jnp.arange(max_degree)[None, :]
    valid = idx < csr.row_ptr[vids + 1][:, None]
    idx = jnp.clip(idx, 0, csr.sorted_key.shape[0] - 1)
    nbr_mat = csr.sorted_nbr[idx]
    val_mat = jax.tree.map(lambda a: a[idx], csr.sorted_val)
    return nbr_mat, val_mat, valid


def sorted_neighbor_matrix(csr: CSR, max_degree: int) -> Tuple[jax.Array, jax.Array]:
    """Neighbor rows sorted ascending within each row (for intersections).

    Invalid slots are pushed to +INT_MAX so binary search never matches them.
    Used by the triangle-counting kernels (sorted-adjacency intersection, the
    formulation SURVEY.md §7 prefers over the reference's O(deg^2) wedge
    blowup in ``WindowTriangles.java:86-114``).
    """
    nbr_mat, _, valid = dense_neighbors(csr, max_degree)
    big = jnp.iinfo(jnp.int32).max
    rows = jnp.where(valid, nbr_mat, big)
    rows = jnp.sort(rows, axis=1)
    return rows, valid
