"""Pallas TPU kernels for the dense model path.

Where Pallas pays off here is the MXU-dense side of the framework: the
GraphSAGE layer computes ``act(h @ W_self + agg @ W_nbr + b)`` — two
matmuls whose [V, O] intermediates XLA materializes between fusions.
:func:`fused_sage_matmul` keeps one [TILE_V, TILE_O] accumulator in VMEM
across both contractions, writing each output tile once. Round-2
re-measurement on the chip ([65536, 256] x [256, 256] x 2, bf16):
0.024 ms fused vs 0.031 ms XLA dual-matmul — kept, opt-in.

The scatter/gather graph kernels (segment reductions, label propagation,
row intersection) deliberately stay on XLA. The two queued round-1
candidates were evaluated with measurements (round-2):

- **Sorted-run segmented reduction** — REJECTED. TPU Pallas has no
  arbitrary vector scatter, so the only hand-written shape is the
  scatter-free formulation (cumsum + run-boundary gather over pre-sorted
  keys). Measured on the chip at [1M edges -> 262k segments]:
  XLA scatter-add 12.7 ms vs cumsum+gather 93.7 ms — the f32 prefix scan
  over 1M elements costs far more than the scatter it removes. The XLA
  scatter path stays.
- **Double-buffered HBM->VMEM membership pass** (triangle row
  intersection) — REJECTED as not load-bearing: the XLA membership kernel
  already measures 10.5e9 edges/s at the 1M-edge window bench (BENCH
  detail), three orders of magnitude above the host-bound end-to-end
  rate; streaming row pairs by hand cannot move any system number.

All kernels run in ``interpret=True`` mode off-TPU, which is how the CPU
test suite covers them.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _pad_to(x: jax.Array, mult0: int, mult1: int) -> jax.Array:
    p0 = (-x.shape[0]) % mult0
    p1 = (-x.shape[1]) % mult1
    if p0 or p1:
        x = jnp.pad(x, ((0, p0), (0, p1)))
    return x


@functools.partial(
    jax.jit, static_argnames=("activation", "tile_v", "tile_o", "interpret")
)
def fused_sage_matmul(
    h: jax.Array,
    agg: jax.Array,
    w_self: jax.Array,
    w_nbr: jax.Array,
    b: jax.Array,
    activation: str = "relu",
    tile_v: int = 256,
    tile_o: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """``act(h @ w_self + agg @ w_nbr + b)`` as one Pallas kernel.

    ``h``/``agg``: [V, F]; weights [F, O]; bias [O]. Accumulation is f32
    regardless of input dtype (bf16 in, f32 accumulate, input-dtype out —
    the MXU-native recipe). Returns [V, O] in ``h.dtype``.
    """
    from jax.experimental import pallas as pl

    if activation not in ("relu", "none"):
        raise ValueError(
            f"fused_sage_matmul supports activation 'relu' or 'none', "
            f"got {activation!r}"
        )
    V, F = h.shape
    o_dim = w_self.shape[1]
    dtype = h.dtype
    hp = _pad_to(h, tile_v, 128)
    ap = _pad_to(agg, tile_v, 128)
    wsp = _pad_to(w_self, 128, tile_o)
    wnp = _pad_to(w_nbr, 128, tile_o)
    bp = jnp.pad(b, (0, wsp.shape[1] - o_dim))[None, :]
    Vp, Fp = hp.shape
    Op = wsp.shape[1]

    def kernel(h_ref, a_ref, ws_ref, wn_ref, b_ref, out_ref):
        acc = jnp.dot(
            h_ref[:], ws_ref[:], preferred_element_type=jnp.float32
        )
        acc += jnp.dot(
            a_ref[:], wn_ref[:], preferred_element_type=jnp.float32
        )
        acc += b_ref[:].astype(jnp.float32)
        if activation == "relu":
            acc = jnp.maximum(acc, 0.0)
        out_ref[:] = acc.astype(out_ref.dtype)

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((Vp, Op), dtype),
        grid=(Vp // tile_v, Op // tile_o),
        in_specs=[
            pl.BlockSpec((tile_v, Fp), lambda i, j: (i, 0)),
            pl.BlockSpec((tile_v, Fp), lambda i, j: (i, 0)),
            pl.BlockSpec((Fp, tile_o), lambda i, j: (0, j)),
            pl.BlockSpec((Fp, tile_o), lambda i, j: (0, j)),
            pl.BlockSpec((1, tile_o), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((tile_v, tile_o), lambda i, j: (i, j)),
        interpret=interpret,
    )(hp, ap, wsp, wnp, bp)
    return out[:V, :o_dim]


def pallas_available() -> bool:
    """True when a real TPU backend is present (interpret mode aside)."""
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False
