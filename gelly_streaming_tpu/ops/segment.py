"""Segment reductions: the TPU replacement for Flink's per-key window state.

Every neighborhood aggregation in the reference is a per-key stateful fold:
``keyBy(vertex)`` then fold/reduce/apply over the window's records
(``SnapshotStream.java:61-181``). On TPU the same computation is a *segment
reduction* over a sorted-or-scattered edge block: vertex id = segment id,
edge value = element. Three tiers, fastest first:

1. :func:`segment_reduce` — recognized monoids (sum/min/max/prod) lower to
   ``jax.ops.segment_*`` (XLA scatter-reduce; no sort needed).
2. :func:`segmented_reduce_generic` — arbitrary *associative* combine, via a
   segmented ``lax.associative_scan`` over edges sorted by segment (the
   classic (flag, value) trick). Parallel depth O(log E).
3. :func:`segmented_fold` — arbitrary (possibly non-associative) fold in
   arrival order, via ``lax.scan`` over the sorted edges. Sequential in E but
   fully compiled; mirrors the reference's per-record ``EdgesFold`` exactly
   (``EdgesFold.java:33-47``). Prefer tiers 1-2 for throughput.

All functions take padded blocks (mask-aware) and a static ``num_segments``.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax

_INT_MAX = jnp.iinfo(jnp.int32).max

_MONOIDS = {
    "sum": jax.ops.segment_sum,
    "min": jax.ops.segment_min,
    "max": jax.ops.segment_max,
    "prod": jax.ops.segment_prod,
}


def segment_reduce(
    values: jax.Array,
    segment_ids: jax.Array,
    mask: jax.Array,
    num_segments: int,
    op: str = "sum",
) -> jax.Array:
    """Masked monoid segment reduction (tier 1).

    Padding rows are routed to a sentinel segment (``num_segments``) so they
    never contribute. Empty segments hold whatever ``jax.ops.segment_*``
    produces for them — callers must gate on a count/nonempty mask.
    """
    ids = jnp.where(mask, segment_ids, num_segments)
    out = _MONOIDS[op](values, ids, num_segments=num_segments + 1)
    return out[:num_segments]


def segment_count(segment_ids: jax.Array, mask: jax.Array, num_segments: int) -> jax.Array:
    """Per-segment element count (degree computation)."""
    ones = mask.astype(jnp.int32)
    ids = jnp.where(mask, segment_ids, num_segments)
    return jax.ops.segment_sum(ones, ids, num_segments=num_segments + 1)[:num_segments]


# --------------------------------------------------------------------------- #
# Sorting edges by segment (shared by tiers 2-3 and CSR building)
# --------------------------------------------------------------------------- #
def sort_by_segment(
    segment_ids: jax.Array, mask: jax.Array, *arrays: jax.Array
) -> Tuple[jax.Array, ...]:
    """Stable-sort edge arrays by (masked) segment id.

    Padding gets the sentinel id ``INT_MAX`` so it sorts last; arrival order
    within a segment is preserved (stable), which is what makes tier-3 folds
    match the reference's per-record processing order.

    Returns ``(sorted_ids, sorted_mask, *sorted_arrays)``.
    """
    ids = jnp.where(mask, segment_ids, _INT_MAX)
    order = jnp.argsort(ids, stable=True)
    return (ids[order], mask[order]) + tuple(
        jax.tree.map(lambda a: a[order], arr) for arr in arrays
    )


def _segment_last_index(sorted_ids: jax.Array, num_segments: int) -> Tuple[jax.Array, jax.Array]:
    """For each segment: index of its last element, and whether it is nonempty."""
    seg = jnp.arange(num_segments, dtype=sorted_ids.dtype)
    right = jnp.searchsorted(sorted_ids, seg, side="right")
    left = jnp.searchsorted(sorted_ids, seg, side="left")
    nonempty = right > left
    last = jnp.clip(right - 1, 0, sorted_ids.shape[0] - 1)
    return last, nonempty


def segmented_reduce_generic(
    values: Any,
    segment_ids: jax.Array,
    mask: jax.Array,
    num_segments: int,
    combine: Callable[[Any, Any], Any],
) -> Tuple[Any, jax.Array]:
    """Arbitrary associative segmented reduction (tier 2).

    ``combine(a, b) -> c`` must be associative over the value pytree.
    Returns ``(per_segment_result, nonempty_mask)``; rows of empty segments
    are whatever the scan produced and must be gated by ``nonempty_mask``.

    Mechanism: sort by segment, then run the standard segmented-scan
    construction — carry (start_flag, value) pairs through
    ``lax.associative_scan`` where a start flag blocks combination across the
    boundary. This keeps arbitrary ``EdgesReduce`` UDFs
    (``EdgesReduce.java:31-44``) fully parallel on the VPU.
    """
    sorted_ids, sorted_mask, sorted_vals = sort_by_segment(segment_ids, mask, values)
    starts = jnp.concatenate(
        [jnp.ones(1, bool), sorted_ids[1:] != sorted_ids[:-1]]
    )

    def scan_op(a, b):
        fa, va = a
        fb, vb = b
        merged = combine(va, vb)
        v = jax.tree.map(
            lambda m, y: jnp.where(_bcast(fb, y), y, m), merged, vb
        )
        return fa | fb, v

    _, scanned = lax.associative_scan(scan_op, (starts, sorted_vals))
    last, nonempty = _segment_last_index(sorted_ids, num_segments)
    result = jax.tree.map(lambda a: a[last], scanned)
    return result, nonempty


def segmented_fold(
    init: Any,
    fold_fn: Callable[[Any, jax.Array, jax.Array, jax.Array], Any],
    segment_ids: jax.Array,
    neighbor_ids: jax.Array,
    values: Any,
    mask: jax.Array,
    num_segments: int,
    id_of_segment: jax.Array | None = None,
    id_of_neighbor: jax.Array | None = None,
) -> Tuple[Any, jax.Array]:
    """Arbitrary per-edge fold in arrival order (tier 3).

    ``fold_fn(accum, vertex_id, neighbor_id, edge_value) -> accum`` is the
    exact TPU analog of ``EdgesFold.foldEdges`` (``EdgesFold.java:33-47``).
    ``id_of_segment``/``id_of_neighbor`` optionally map compact indices back
    to raw vertex ids (int32 lookup tables) so UDFs observe the same ids the
    reference would.

    .. warning:: **Cost model — prefer tiers 1-2 at scale.** Arrival-order
       semantics with an arbitrary (possibly non-associative) ``fold_fn``
       force a SEQUENTIAL ``lax.scan`` over the whole window: per-window
       depth is the edge count, so throughput is per-edge scan-step rate
       (~1-5M eps, measured in ``BENCH_DETAIL.json: segmented_fold_eps``)
       regardless of window size — three orders below the scatter tiers.
       Use it only when the fold is genuinely order-dependent and
       non-associative, exactly like the reference's sequential
       ``EdgesFold``. Otherwise:

       * tier 1 — ``reduce_on_edges("sum"|"min"|"max")``: one XLA
         scatter-reduce, no sort;
       * tier 2 — ``reduce_on_edges(callable)``: any ASSOCIATIVE combine
         via segmented associative scan (log-depth);
       * order-dependent but associative-after-keying folds can usually
         be re-expressed as a tier-2 reduce over (timestamp, value)
         pairs.

    Returns ``(per_segment_accum, nonempty_mask)``.
    """
    sorted_ids, sorted_mask, sorted_nbr, sorted_vals = sort_by_segment(
        segment_ids, mask, neighbor_ids, values
    )
    starts = jnp.concatenate([jnp.ones(1, bool), sorted_ids[1:] != sorted_ids[:-1]])

    def step(carry, x):
        accum = carry
        sid, is_start, valid, nbr, val = x
        base = jax.tree.map(
            lambda i, a: jnp.where(_bcast(is_start, a), i, a), init, accum
        )
        vid = sid if id_of_segment is None else id_of_segment[jnp.clip(sid, 0, id_of_segment.shape[0] - 1)]
        nid = nbr if id_of_neighbor is None else id_of_neighbor[nbr]
        new = fold_fn(base, vid, nid, val)
        accum = jax.tree.map(
            lambda n, a: jnp.where(_bcast(valid, a), n, a), new, base
        )
        return accum, accum

    init_c = jax.tree.map(lambda i: jnp.asarray(i), init)
    _, outs = lax.scan(step, init_c, (sorted_ids, starts, sorted_mask, sorted_nbr, sorted_vals))
    last, nonempty = _segment_last_index(sorted_ids, num_segments)
    result = jax.tree.map(lambda a: a[last], outs)
    return result, nonempty


def _bcast(flag: jax.Array, like: jax.Array) -> jax.Array:
    """Broadcast a scalar/vector bool flag against a value of any rank."""
    extra = like.ndim - flag.ndim
    if extra > 0:
        flag = flag.reshape(flag.shape + (1,) * extra)
    return flag
