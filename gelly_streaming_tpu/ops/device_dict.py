"""Device-resident vertex dictionary: the keyBy ON the accelerator.

Reference analog: the raw-id keyed state behind every ``keyBy(vertex)``
(``SimpleEdgeStream.java:119,303,537``; ``summaries/DisjointSet.java:30``
keys HashMaps by raw ``Long`` directly). The TPU form needs dense compact
ids; this module produces them without host hashing.

The host ``VertexDict`` (C++ hash map) costs ~20 ns per id on the single
host core — at corpus scale that is the end-to-end ceiling (ROADMAP #1).
This module keeps the raw-id -> compact-id mapping AS DEVICE STATE and
encodes whole windows in one compiled step, so the host's only ingest work
is handing raw columns to the device (memmap slice + put on the binary
path).

Design — sort-based, not hash-probe-based: an open-addressing table needs
data-dependent probe ROUNDS (a ``while_loop`` whose trip count is the
longest chain — the tail serializes the whole batch), which measured ~100x
slower than the host dict. The TPU-native shape is static:

- State: ``keys[Kcap]`` sorted ascending (+INT32_MAX padding) with aligned
  ``idx[Kcap]``, reverse table ``rev[Kcap]``, and the assigned count.
- Per batch (one jitted dispatch): binary-search every id against the
  sorted table (known ids resolve immediately); sort the unknown ids with
  their arrival positions (two-key ``lax.sort``) so each novel key is one
  run whose head is its FIRST arrival; rank run heads by arrival
  (argsort + scatter) to assign ``count + rank`` — bit-identical to the
  sequential first-seen host dict; propagate ids down runs with
  ``cummax``; merge the novel keys into the table by concat + sort.
  Everything is fixed-shape vector work: O((K + B) log(K + B)) with no
  data-dependent control flow.
- Growth: padding a sorted table is appending +INT32_MAX — the host just
  re-pads to the next capacity bucket (no rehash at all).

Raw ids must be non-negative int32 below INT32_MAX (the framework-wide
raw-table contract; ``VertexDict`` remains the general path for 64-bit id
spaces).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.edgeblock import bucket_capacity

_BIG = jnp.iinfo(jnp.int32).max


def init_table(cap: int):
    """Fresh device dictionary state (``cap`` keys capacity).

    ``probe`` is the sticky overflow telltale read by the speculative
    growth-mode ingest: ``count`` while every batch so far fit the table,
    ``-(count)-1`` forever after the first one that did not (its
    ``state``/outputs are then poisoned and must be replayed). It lives
    INSIDE the state dict on purpose: emitting it as a separate executable
    output measured a ~17x whole-program slowdown on the remote-TPU
    runtime (round 3), while an extra scalar state field is free.
    """
    return {
        "keys": jnp.full(cap, _BIG, jnp.int32),  # sorted ascending
        "idx": jnp.zeros(cap, jnp.int32),
        "rev": jnp.full(cap, -1, jnp.int32),
        "count": jnp.int32(0),
        "probe": jnp.int32(0),
    }


@jax.jit
def encode_pair_batch(state, src, dst):
    """Edge-column encode as ONE executable: interleave, encode, split.

    The unfused form (host-side ``stack``/``reshape``/column slicing
    around :func:`encode_batch`) costs ~4 extra dispatches per window;
    through the remote-TPU tunnel each enqueue is milliseconds, so the
    fusion is worth ~2x end-to-end on the ingest path (round 3)."""
    n = src.shape[0]
    raw = jnp.stack([src, dst], axis=1).reshape(-1)
    state, out = encode_batch(state, raw)
    pair = out.reshape(n, 2)
    return state, pair[:, 0], pair[:, 1]


@jax.jit
def encode_batch(state, raw):
    """Map a batch of raw ids (arrival order) to compact ids, inserting
    novel ids first-seen-first. Returns ``(state, out_idx)``.

    The caller guarantees capacity: ``count + batch-unique-count`` must
    fit ``keys.shape[0]`` (the host grows by bucket beforehand).
    """
    keys, idxv, rev, count = (
        state["keys"], state["idx"], state["rev"], state["count"],
    )
    kcap = keys.shape[0]
    n = raw.shape[0]
    arange = jnp.arange(n, dtype=jnp.int32)

    # 1. resolve known ids by binary search
    pos = jnp.clip(jnp.searchsorted(keys, raw), 0, kcap - 1)
    found = keys[pos] == raw
    out = jnp.where(found, idxv[pos], -1)

    # 2. group unknown ids into runs ordered by (key, arrival)
    nr = jnp.where(found, _BIG, raw)
    sk, sa = jax.lax.sort((nr, arange), num_keys=2)
    real = sk != _BIG
    first = real & jnp.concatenate(
        [jnp.ones(1, bool), sk[1:] != sk[:-1]]
    )
    # 3. run heads get ids by global first-arrival order. Sort-based rank
    # (argsort of the argsort) instead of an inverse-permutation scatter:
    # this runtime degrades badly on large random scatters, while its sort
    # path measures at memory-bound rates (triangle kernels).
    head_arrival = jnp.where(first, sa, _BIG)
    order = jnp.argsort(head_arrival)
    rank = jnp.argsort(order).astype(jnp.int32)
    head_id = count + rank  # valid where `first`
    # 4. propagate each run's id to all members via the run-head POSITION
    # (cummax over positions is monotone, so it cannot leak across runs
    # the way cummax over ids would), then map back to arrival slots with
    # one more inverse-permutation argsort — again, no scatter.
    head_pos = jax.lax.cummax(jnp.where(first, arange, -1))
    ids_sorted = head_id[jnp.clip(head_pos, 0, n - 1)]
    inv_sa = jnp.argsort(sa)
    arrival_vals = jnp.where(real, ids_sorted, -1)[inv_sa]
    out = jnp.maximum(out, arrival_vals)
    n_new = first.sum().astype(jnp.int32)

    # 5. merge the novel (key, id) pairs into the sorted table
    nk = jnp.where(first, sk, _BIG)
    nv = jnp.where(first, ids_sorted, 0)
    mk, mv = jax.lax.sort(
        (jnp.concatenate([keys, nk]), jnp.concatenate([idxv, nv])),
        num_keys=1,
    )
    new_count = count + n_new
    still_ok = (state["probe"] >= 0) & (new_count <= kcap)
    new_state = {
        "keys": mk[:kcap],
        "idx": mv[:kcap],
        "rev": rev.at[jnp.where(first, head_id, kcap)].set(sk, mode="drop"),
        "count": new_count,
        "probe": jnp.where(still_ok, new_count, -new_count - 1),
    }
    return new_state, out


class DeviceVertexDict:
    """VertexDict-compatible facade over the device sorted table.

    ``encode_pair`` runs ON DEVICE and returns device index arrays (unlike
    the host dict's numpy): the device-encode ingest path feeds them
    straight into EdgeBlocks with zero host hash work. ``decode``/
    ``__len__`` sync lazily (emission-time only).
    """

    def __init__(self, min_capacity: int = 1 << 10, id_bound: int = 0):
        """``id_bound``: when the raw id space is known to be < bound, the
        table allocates for it once and NEVER grows or syncs — growth
        decisions otherwise need a pessimistic fill bound whose per-window
        count sync stalls the device pipeline (~100ms+ through a remote
        runtime)."""
        self.id_bound = int(id_bound)
        cap = bucket_capacity(max(min_capacity, self.id_bound, 16))
        self._state = init_table(cap)
        self._synced_count = 0  # host-known lower bound (lazy)
        self._pending = 0  # ids encoded since the last count sync

    # ------------------------------------------------------------------ #
    @property
    def capacity(self) -> int:
        return int(self._state["keys"].shape[0])

    def __len__(self) -> int:
        self._sync()
        return self._synced_count

    def _sync(self) -> None:
        probe = int(self._state["probe"])
        if probe < 0:
            raise RuntimeError(
                "device dictionary overflowed its table — the host-side "
                "novelty bound failed to grow it in time (bug); compact "
                "ids since the overflow are unreliable"
            )
        self._synced_count = probe
        self._pending = 0

    def _ensure(self, incoming: int) -> None:
        """Grow (by re-padding — the table is sorted, growth is appending
        +INT32_MAX) so the worst case ``count + incoming`` fits."""
        if self.id_bound:  # capacity covers the whole id space: no-op
            return
        ub = self._synced_count + self._pending + incoming
        cap = self.capacity
        if ub <= cap:
            return
        self._sync()  # one round trip, only near a growth boundary
        need = self._synced_count + incoming
        if need <= cap:
            return
        self._repad(bucket_capacity(need))

    def _repad(self, new_cap: int) -> None:
        """Growth is appending +INT32_MAX padding to the sorted table."""
        grow = new_cap - self.capacity
        if grow <= 0:
            return
        self._state = {
            "keys": jnp.concatenate(
                [self._state["keys"], jnp.full(grow, _BIG, jnp.int32)]
            ),
            "idx": jnp.concatenate(
                [self._state["idx"], jnp.zeros(grow, jnp.int32)]
            ),
            "rev": jnp.concatenate(
                [self._state["rev"], jnp.full(grow, -1, jnp.int32)]
            ),
            "count": self._state["count"],
            "probe": self._state["probe"],
        }

    # ------------------------------------------------------------------ #
    def _validate(self, *arrays) -> None:
        """With ``id_bound`` set, out-of-range raw ids would silently
        corrupt the fixed-capacity table (the merge truncates) — reject
        them like ``IdentityDict.encode`` does. Host arrays only; device
        arrays are produced by our own ingest paths from validated or
        host-checked sources."""
        if not self.id_bound:
            return
        for a in arrays:
            if isinstance(a, np.ndarray) and a.size and (
                int(a.min()) < 0 or int(a.max()) >= self.id_bound
            ):
                raise ValueError(
                    f"raw id outside [0, {self.id_bound}) — not a dense-id "
                    "corpus; drop id_bound (growth mode) or use VertexDict"
                )

    # ------------------------------------------------------------------ #
    # Growth-mode encode driven by host-side novelty tracking (round 3)
    # ------------------------------------------------------------------ #
    # The general arbitrary-id ingest keeps an EXACT host-side upper
    # bound on the table count (``native.NoveltyBitmap`` over the raw id
    # stream — first-seen distinctness is the same quantity the device
    # table counts) and calls :meth:`ensure_capacity_host` before each
    # window. Growth is pure padding, so the whole pipeline runs with
    # ZERO device->host reads; the sticky ``probe`` state field is a
    # defense-in-depth telltale asserted at the next natural sync.

    def ensure_capacity_host(self, count_bound: int) -> None:
        """Grow (no sync — pure padding) so ``count_bound`` entries fit."""
        if count_bound > self.capacity:
            self._repad(bucket_capacity(max(count_bound, 2 * self.capacity)))

    def encode_pair_spec(self, src, dst):
        """Growth-mode device encode: one dispatch, NO host sync, no
        validation. The caller guarantees capacity via
        :meth:`ensure_capacity_host` (host novelty tracking)."""
        src = jnp.asarray(src, jnp.int32)
        dst = jnp.asarray(dst, jnp.int32)
        self._state, si, di = encode_pair_batch(self._state, src, dst)
        self._pending += 2 * int(src.shape[0])
        return si, di

    def encode_pair(self, src, dst) -> Tuple[jax.Array, jax.Array]:
        """Device-encode edge columns in arrival order (src before dst per
        edge). Accepts numpy or device int32 arrays; returns device index
        columns."""
        self._validate(np.asarray(src) if isinstance(src, np.ndarray) else src,
                       np.asarray(dst) if isinstance(dst, np.ndarray) else dst)
        src = jnp.asarray(src, jnp.int32)
        dst = jnp.asarray(dst, jnp.int32)
        n = src.shape[0]
        self._ensure(2 * n)
        self._state, si, di = encode_pair_batch(self._state, src, dst)
        self._pending += 2 * n
        return si, di

    def encode(self, raw) -> np.ndarray:
        host = np.asarray(raw, np.int64).ravel()
        self._validate(host)
        arr = jnp.asarray(host, jnp.int32)
        self._ensure(int(arr.shape[0]))
        self._state, out = encode_batch(self._state, arr)
        self._pending += int(arr.shape[0])
        return np.asarray(out)

    def _rev_array(self) -> np.ndarray:
        """Host copy of the reverse table, cached by synced count (a full
        download per decode would move the whole table every emission)."""
        self._sync()
        cached = getattr(self, "_rev_cache", None)
        if cached is not None and cached[0] == self._synced_count:
            return cached[1]
        rev = np.asarray(self._state["rev"])
        self._rev_cache = (self._synced_count, rev)
        return rev

    def decode(self, idx) -> np.ndarray:
        return self._rev_array()[np.asarray(idx, np.int64)].astype(np.int64)

    def decode_one(self, idx: int) -> int:
        return int(self.decode(np.asarray([idx]))[0])

    def lookup(self, raw: int):
        """Query without inserting (host binary search — emission/API
        path, not the ingest hot path)."""
        keys = np.asarray(self._state["keys"])
        pos = int(np.searchsorted(keys, np.int32(raw)))
        if pos < keys.shape[0] and keys[pos] == int(raw):
            return int(np.asarray(self._state["idx"])[pos])
        return None

    def raw_ids(self) -> np.ndarray:
        n = len(self)
        return np.asarray(self._state["rev"][:n]).astype(np.int64)

    def raw_table(self) -> jax.Array:
        return jnp.where(self._state["rev"] == -1, 0, self._state["rev"])
