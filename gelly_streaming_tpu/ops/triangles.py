"""Triangle-counting kernels: sorted-adjacency intersection on dense rows.

TPU-native replacement for the reference's two triangle paths:

- ``example/WindowTriangles.java:86-139`` materializes O(Σdeg²) wedge
  candidates per window and joins them against real edges — a blowup
  SURVEY.md §7 explicitly avoids. Here a window's triangles are counted by
  intersecting the sorted neighbor rows of each edge's endpoints
  (:func:`window_triangle_count`): O(E·D·logD) dense vector work.
- ``example/ExactTriangleCount.java:74-116`` pairs per-edge neighborhood
  snapshots in keyed state so each triangle is counted exactly once, when its
  last edge arrives. The TPU form (:func:`ranked_triangle_update`) keeps an
  *arrival rank* per accumulated edge and counts, for each new edge, common
  neighbors whose two closing edges both have smaller rank — the same
  "closed by the final edge" semantics, batched per window.

All kernels take dense ``[V, D]`` neighbor matrices (see
``ops/csr.py:sorted_neighbor_matrix``); invalid slots hold +INT_MAX so
binary search never matches them.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from .csr import CSR, build_csr, dense_neighbors

_BIG = jnp.iinfo(jnp.int32).max


def canonicalize(src: jax.Array, dst: jax.Array, mask: jax.Array):
    """(min,max) edge ordering, self-loops masked off
    (``ExactTriangleCount.java:136-146`` ProjectCanonicalEdges)."""
    u = jnp.minimum(src, dst)
    v = jnp.maximum(src, dst)
    return u, v, mask & (u != v)


def dedup_canonical(u: jax.Array, v: jax.Array, mask: jax.Array, num_vertices: int):
    """Mask duplicate canonical edges within a block. Returns (u, v, mask)
    with duplicates masked off. Two-key ``lax.sort`` — no composite int64
    key, which would overflow with x64 disabled."""
    del num_vertices
    iota = jnp.arange(u.shape[0], dtype=jnp.int32)
    u_m = jnp.where(mask, u, _BIG)
    v_m = jnp.where(mask, v, _BIG)
    su, sv, si = jax.lax.sort((u_m, v_m, iota), num_keys=2)
    first = jnp.concatenate(
        [jnp.ones(1, bool), (su[1:] != su[:-1]) | (sv[1:] != sv[:-1])]
    )
    keep = jnp.zeros_like(mask).at[si].set(first)
    return u, v, mask & keep


def sorted_ranked_rows(
    u: jax.Array,
    v: jax.Array,
    rank: jax.Array,
    mask: jax.Array,
    num_vertices: int,
    max_degree: int,
) -> Tuple[jax.Array, jax.Array]:
    """Build ``(nbr_ids[V, D], nbr_ranks[V, D])`` rows sorted by neighbor id.

    Input is the *canonical* edge list; both directions are materialized so a
    vertex's row holds its full undirected neighborhood. Invalid slots hold
    +INT_MAX ids (rank irrelevant there).
    """
    key = jnp.concatenate([u, v])
    nbr = jnp.concatenate([v, u])
    rk = jnp.concatenate([rank, rank])
    m = jnp.concatenate([mask, mask])
    csr = build_csr(key, nbr, rk, m, num_vertices)
    nbr_mat, rank_mat, valid = dense_neighbors(csr, max_degree)
    ids = jnp.where(valid, nbr_mat, _BIG)
    order = jnp.argsort(ids, axis=1)
    ids = jnp.take_along_axis(ids, order, axis=1)
    ranks = jnp.take_along_axis(rank_mat, order, axis=1)
    return ids, ranks


def _row_membership(rows_a: jax.Array, rows_b: jax.Array):
    """For each element of rows_a[i], its position and presence in rows_b[i].

    Both inputs ``[E, D]`` with rows sorted ascending. Returns (pos, found);
    +INT_MAX sentinels never count as found.
    """

    def one(a, b):
        pos = jnp.searchsorted(b, a)
        pos_c = jnp.clip(pos, 0, b.shape[0] - 1)
        found = (b[pos_c] == a) & (a != _BIG)
        return pos_c, found

    return jax.vmap(one)(rows_a, rows_b)


def window_triangle_count(
    src: jax.Array,
    dst: jax.Array,
    mask: jax.Array,
    num_vertices: int,
    max_degree: int,
    edge_chunk: int = 1 << 16,
) -> Tuple[jax.Array, jax.Array]:
    """Exact triangle count of one window's edge block, degree-oriented.

    Edges are oriented from lexicographically-smaller ``(degree, id)`` to
    larger, and each edge intersects the *out*-neighbor rows of its
    endpoints — the standard forward-counting orientation. Two wins over
    intersecting full neighborhoods: each triangle is counted exactly once
    (no /3), and row width is bounded by the max out-degree, which is at
    most ~sqrt(2E) for ANY degree distribution — a Zipf hub no longer
    inflates the dense rows (the reference's wedge generation has the same
    O(Σdeg²) hub blowup this avoids, ``WindowTriangles.java:86-114``).

    ``max_degree`` must cover the max *oriented out-degree* (callers bucket
    it host-side). The [E, D] membership intermediates are processed in
    ``edge_chunk`` slices via ``lax.scan`` to bound peak memory.

    Returns ``(total, per_vertex[V])``; ``per_vertex[w]`` = number of window
    triangles containing ``w``.
    """
    u, v, m = canonicalize(src, dst, mask)
    u, v, m = dedup_canonical(u, v, m, num_vertices)
    mi = m.astype(jnp.int32)
    deg = jnp.zeros(num_vertices, jnp.int32).at[u].add(mi).at[v].add(mi)
    # orient a -> b where (deg, id) of a < of b
    du, dv = deg[u], deg[v]
    swap = (dv < du) | ((dv == du) & (v < u))
    a = jnp.where(swap, v, u)
    b = jnp.where(swap, u, v)
    # out-neighbor rows sorted by id (invalid slots +INT_MAX)
    zeros = jnp.zeros_like(a)
    csr = build_csr(a, b, zeros, m, num_vertices)
    nbr_mat, _, valid = dense_neighbors(csr, max_degree)
    ids = jnp.sort(jnp.where(valid, nbr_mat, _BIG), axis=1)

    E = a.shape[0]
    pad_to = -(-E // edge_chunk) * edge_chunk
    ap = jnp.concatenate([a, jnp.zeros(pad_to - E, a.dtype)])
    bp = jnp.concatenate([b, jnp.zeros(pad_to - E, b.dtype)])
    mp = jnp.concatenate([m, jnp.zeros(pad_to - E, bool)])
    n_chunks = pad_to // edge_chunk
    ac = ap.reshape(n_chunks, edge_chunk)
    bc = bp.reshape(n_chunks, edge_chunk)
    mc = mp.reshape(n_chunks, edge_chunk)

    def chunk_step(carry, x):
        counts, total = carry
        a_i, b_i, m_i = x
        rows_a = jnp.where(m_i[:, None], ids[a_i], _BIG)
        rows_b = ids[b_i]
        _, found = _row_membership(rows_a, rows_b)
        c = found.sum(axis=1).astype(jnp.int32)
        w_ids = jnp.where(found, rows_a, 0)
        counts = counts.at[w_ids.reshape(-1)].add(
            found.reshape(-1).astype(jnp.int32)
        )
        cm = jnp.where(m_i, c, 0)
        counts = counts.at[a_i].add(cm).at[b_i].add(cm)
        return (counts, total + cm.sum()), None

    (per_vertex, total), _ = jax.lax.scan(
        chunk_step,
        (jnp.zeros(num_vertices, jnp.int32), jnp.int32(0)),
        (ac, bc, mc),
    )
    return total, per_vertex


def ranked_triangle_update(
    nbr_ids: jax.Array,
    nbr_ranks: jax.Array,
    u: jax.Array,
    v: jax.Array,
    rank: jax.Array,
    mask: jax.Array,
    counts: jax.Array,
    edge_chunk: int = 1 << 16,
) -> Tuple[jax.Array, jax.Array]:
    """Count the triangles *closed by* a batch of new edges.

    ``nbr_ids``/``nbr_ranks`` describe the ACCUMULATED graph (new edges
    included); a new edge (u, v) of arrival rank r closes triangle
    (u, v, w) iff edges (u, w) and (v, w) both arrived strictly earlier.
    Updates the running per-vertex ``counts`` (each triangle vertex +1 —
    the ``(w,1)/(u,c)/(v,c)`` emissions of
    ``ExactTriangleCount.java:85-106``) and returns ``(counts, delta)``
    where delta is this batch's new-triangle total (the ``(-1, c)`` stream).

    The [E, D] membership intermediates are processed in ``edge_chunk``
    slices via ``lax.scan`` to bound peak HBM (same pattern as
    :func:`window_triangle_count`).
    """
    E = u.shape[0]
    pad_to = -(-E // edge_chunk) * edge_chunk

    def pad(a, fill=0):
        return jnp.concatenate(
            [a, jnp.full(pad_to - E, fill, a.dtype)]
        ) if pad_to != E else a

    uc = pad(u).reshape(-1, edge_chunk)
    vc = pad(v).reshape(-1, edge_chunk)
    rc = pad(rank).reshape(-1, edge_chunk)
    mc = pad(mask.astype(jnp.int32)).astype(bool).reshape(-1, edge_chunk)

    def chunk_step(carry, x):
        counts, total = carry
        u_i, v_i, r_i, m_i = x
        rows_u = jnp.where(m_i[:, None], nbr_ids[u_i], _BIG)
        ranks_u = nbr_ranks[u_i]
        rows_v = nbr_ids[v_i]
        ranks_v = nbr_ranks[v_i]
        pos, found = _row_membership(rows_u, rows_v)
        r = r_i[:, None]
        match = (
            found
            & (ranks_u < r)
            & (jnp.take_along_axis(ranks_v, pos, axis=1) < r)
        )
        c = match.sum(axis=1).astype(jnp.int32)
        w_ids = jnp.where(match, rows_u, 0)
        counts = counts.at[w_ids.reshape(-1)].add(
            match.reshape(-1).astype(jnp.int32)
        )
        cm = jnp.where(m_i, c, 0)
        counts = counts.at[u_i].add(cm).at[v_i].add(cm)
        return (counts, total + cm.sum().astype(jnp.int32)), None

    (counts, delta), _ = jax.lax.scan(
        chunk_step, (counts, jnp.int32(0)), (uc, vc, rc, mc)
    )
    return counts, delta
