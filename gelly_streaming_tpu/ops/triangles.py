"""Triangle-counting kernels: sorted-adjacency intersection on dense rows.

TPU-native replacement for the reference's two triangle paths:

- ``example/WindowTriangles.java:86-139`` materializes O(Σdeg²) wedge
  candidates per window and joins them against real edges — a blowup
  SURVEY.md §7 explicitly avoids. Here a window's triangles are counted by
  intersecting the sorted neighbor rows of each edge's endpoints
  (:func:`window_triangle_count`): O(E·D·logD) dense vector work.
- ``example/ExactTriangleCount.java:74-116`` pairs per-edge neighborhood
  snapshots in keyed state so each triangle is counted exactly once, when its
  last edge arrives. The TPU form (:func:`packed_triangle_update` over the
  :func:`merge_packed_adjacency`-carried sorted adjacency) keeps an
  *arrival rank* per accumulated edge and counts, for each new edge, common
  neighbors whose two closing edges both have smaller rank — the same
  "closed by the final edge" semantics, batched per window, with O(E)
  carried memory and per-query enumeration bounded by the min-degree
  endpoint's class.

The window kernel takes dense ``[V, D]`` neighbor matrices (see
``ops/csr.py``); the streaming kernels work on the packed sorted columns.
Invalid slots hold +INT_MAX everywhere so binary search never matches
them.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from .csr import build_csr, dense_neighbors

_BIG = jnp.iinfo(jnp.int32).max


def canonicalize(src: jax.Array, dst: jax.Array, mask: jax.Array):
    """(min,max) edge ordering, self-loops masked off
    (``ExactTriangleCount.java:136-146`` ProjectCanonicalEdges)."""
    u = jnp.minimum(src, dst)
    v = jnp.maximum(src, dst)
    return u, v, mask & (u != v)


def dedup_canonical(u: jax.Array, v: jax.Array, mask: jax.Array, num_vertices: int):
    """Mask duplicate canonical edges within a block. Returns (u, v, mask)
    with duplicates masked off. Two-key ``lax.sort`` — no composite int64
    key, which would overflow with x64 disabled."""
    del num_vertices
    iota = jnp.arange(u.shape[0], dtype=jnp.int32)
    u_m = jnp.where(mask, u, _BIG)
    v_m = jnp.where(mask, v, _BIG)
    su, sv, si = jax.lax.sort((u_m, v_m, iota), num_keys=2)
    first = jnp.concatenate(
        [jnp.ones(1, bool), (su[1:] != su[:-1]) | (sv[1:] != sv[:-1])]
    )
    keep = jnp.zeros_like(mask).at[si].set(first)
    return u, v, mask & keep


def _row_membership(rows_a: jax.Array, rows_b: jax.Array):
    """For each element of rows_a[i], its position and presence in rows_b[i].

    Both inputs ``[E, D]`` with rows sorted ascending. Returns (pos, found);
    +INT_MAX sentinels never count as found.
    """

    def one(a, b):
        pos = jnp.searchsorted(b, a)
        pos_c = jnp.clip(pos, 0, b.shape[0] - 1)
        found = (b[pos_c] == a) & (a != _BIG)
        return pos_c, found

    return jax.vmap(one)(rows_a, rows_b)


def window_triangle_count(
    src: jax.Array,
    dst: jax.Array,
    mask: jax.Array,
    num_vertices: int,
    max_degree: int,
    edge_chunk: int = 1 << 16,
) -> Tuple[jax.Array, jax.Array]:
    """Exact triangle count of one window's edge block, degree-oriented.

    Edges are oriented from lexicographically-smaller ``(degree, id)`` to
    larger, and each edge intersects the *out*-neighbor rows of its
    endpoints — the standard forward-counting orientation. Two wins over
    intersecting full neighborhoods: each triangle is counted exactly once
    (no /3), and row width is bounded by the max out-degree, which is at
    most ~sqrt(2E) for ANY degree distribution — a Zipf hub no longer
    inflates the dense rows (the reference's wedge generation has the same
    O(Σdeg²) hub blowup this avoids, ``WindowTriangles.java:86-114``).

    ``max_degree`` must cover the max *oriented out-degree* (callers bucket
    it host-side). The [E, D] membership intermediates are processed in
    ``edge_chunk`` slices via ``lax.scan`` to bound peak memory.

    Returns ``(total, per_vertex[V])``; ``per_vertex[w]`` = number of window
    triangles containing ``w``.
    """
    a, b, m, ids = _oriented_rows(src, dst, mask, num_vertices, max_degree)
    return _membership_pass(ids, a, b, m, num_vertices, edge_chunk)


def _oriented_rows(src, dst, mask, num_vertices: int, max_degree: int):
    """Shared prep of the window kernel: canonical dedup'd edges oriented
    low->high (degree, id) plus the sorted dense out-neighbor rows."""
    u, v, m = canonicalize(src, dst, mask)
    u, v, m = dedup_canonical(u, v, m, num_vertices)
    mi = m.astype(jnp.int32)
    deg = jnp.zeros(num_vertices, jnp.int32).at[u].add(mi).at[v].add(mi)
    # orient a -> b where (deg, id) of a < of b
    du, dv = deg[u], deg[v]
    swap = (dv < du) | ((dv == du) & (v < u))
    a = jnp.where(swap, v, u)
    b = jnp.where(swap, u, v)
    # out-neighbor rows sorted by id (invalid slots +INT_MAX)
    zeros = jnp.zeros_like(a)
    csr = build_csr(a, b, zeros, m, num_vertices)
    nbr_mat, _, valid = dense_neighbors(csr, max_degree)
    ids = jnp.sort(jnp.where(valid, nbr_mat, _BIG), axis=1)
    return a, b, m, ids


def _membership_pass(ids, a, b, m, num_vertices: int, edge_chunk: int):
    """Membership counting over (a, b) edge slices against the replicated
    ``ids`` rows; [E, D] intermediates bounded by ``edge_chunk`` scan."""
    E = a.shape[0]
    pad_to = -(-E // edge_chunk) * edge_chunk
    ap = jnp.concatenate([a, jnp.zeros(pad_to - E, a.dtype)])
    bp = jnp.concatenate([b, jnp.zeros(pad_to - E, b.dtype)])
    mp = jnp.concatenate([m, jnp.zeros(pad_to - E, bool)])
    n_chunks = pad_to // edge_chunk
    ac = ap.reshape(n_chunks, edge_chunk)
    bc = bp.reshape(n_chunks, edge_chunk)
    mc = mp.reshape(n_chunks, edge_chunk)

    def chunk_step(carry, x):
        counts, total = carry
        a_i, b_i, m_i = x
        rows_a = jnp.where(m_i[:, None], ids[a_i], _BIG)
        rows_b = ids[b_i]
        _, found = _row_membership(rows_a, rows_b)
        c = found.sum(axis=1).astype(jnp.int32)
        w_ids = jnp.where(found, rows_a, 0)
        counts = counts.at[w_ids.reshape(-1)].add(
            found.reshape(-1).astype(jnp.int32)
        )
        cm = jnp.where(m_i, c, 0)
        counts = counts.at[a_i].add(cm).at[b_i].add(cm)
        return (counts, total + cm.sum()), None

    init = (jnp.zeros(num_vertices, jnp.int32), jnp.int32(0))
    (per_vertex, total), _ = jax.lax.scan(chunk_step, init, (ac, bc, mc))
    return total, per_vertex


def window_triangle_count_sharded(
    src: jax.Array,
    dst: jax.Array,
    mask: jax.Array,
    num_vertices: int,
    max_degree: int,
    mesh,
    edge_chunk: int = 1 << 13,
) -> Tuple[jax.Array, jax.Array]:
    """Edge-sharded :func:`window_triangle_count` (SURVEY §2.5 P1 + P3).

    The prep (canonicalize/dedup/orient/row build) is replicated — it
    needs the whole window and is O(E log E) sort work; the membership
    pass (the O(E*D) dominant cost) splits over the mesh's ``"edges"``
    axis with the dense rows replicated, and the per-vertex counts and
    total ``psum`` back over ICI. Deterministic: per-shard counting is
    order-independent integer adds. The block capacity (a power of two)
    must divide by the edge-axis size.
    """
    from jax.sharding import PartitionSpec as P

    from ..parallel import comm
    from ..parallel.mesh import EDGE_AXIS

    a, b, m, ids = _oriented_rows(src, dst, mask, num_vertices, max_degree)

    def shard_fn(ids_r, a_s, b_s, m_s):
        total, counts = _membership_pass(
            ids_r, a_s, b_s, m_s, num_vertices, edge_chunk
        )
        return (
            jax.lax.psum(total, EDGE_AXIS),
            jax.lax.psum(counts, EDGE_AXIS),
        )

    return comm.shard_map(
        shard_fn,
        mesh,
        in_specs=(P(None, None), P(EDGE_AXIS), P(EDGE_AXIS), P(EDGE_AXIS)),
        out_specs=(P(), P()),
    )(ids, a, b, m)


def ranged_searchsorted(arr, lo, hi, x, *, side: str = "left", steps: int = 32):
    """Elementwise binary search of ``x`` within ``arr[lo:hi)`` (each
    element has its own range; ``arr`` ascending within every range).
    Returns the leftmost (``side='left'``) or rightmost insertion
    position. Fixed ``steps`` iterations (covers arrays up to 2^steps)."""
    right = side == "right"

    def body(_, c):
        lo, hi = c
        mid = (lo + hi) >> 1
        mid_c = jnp.clip(mid, 0, arr.shape[0] - 1)
        v = arr[mid_c]
        go_right = (v <= x) if right else (v < x)
        go_right = go_right & (lo < hi)
        return jnp.where(go_right, mid + 1, lo), jnp.where(
            lo < hi, jnp.where(go_right, hi, mid), hi
        )

    lo, hi = jax.lax.fori_loop(0, steps, body, (lo, hi))
    return lo


def _count_composite(sv, sn, v, n, side: str):
    """How many (sv, sn) pairs (sorted, sentinel-padded) compare
    less [or less-or-equal for side='right'] than each (v, n) query —
    the composite-key searchsorted, in pure int32."""
    lt = jnp.searchsorted(sv, v, side="left").astype(jnp.int32)
    hi = jnp.searchsorted(sv, v, side="right").astype(jnp.int32)
    within = ranged_searchsorted(sn, lt, hi, n, side=side)
    return within


def merge_packed_adjacency(pv, pn, pr, new_v, new_n, new_r, n_new):
    """Merge sorted new (vertex, nbr, rank) entries into the packed sorted
    adjacency — a composite-key merge path (two-level searchsorted +
    scatter), not a re-sort of the accumulated arrays; per-window work is
    O(total) data movement but only O(log) comparisons per element, all
    int32 (no 64-bit key packing).

    Both inputs sorted by (vertex, nbr) with +INT32_MAX sentinel padding
    in the vertex column; real keys must be disjoint (callers dedup).
    Output arrays keep the callers' pre-grown capacity = len(pv).
    """
    cap = pv.shape[0]
    ncap = new_v.shape[0]
    pos_old = jnp.arange(cap, dtype=jnp.int32) + _count_composite(
        new_v, new_n, pv, pn, side="left"
    )
    pos_new = jnp.arange(ncap, dtype=jnp.int32) + _count_composite(
        pv, pn, new_v, new_n, side="right"
    )
    pos_old = jnp.where(pv == _BIG, cap, pos_old)
    pos_new = jnp.where(jnp.arange(ncap) < n_new, pos_new, cap)
    out_v = jnp.full(cap, _BIG, jnp.int32)
    out_n = jnp.zeros(cap, jnp.int32)
    out_r = jnp.zeros(cap, jnp.int32)
    out_v = out_v.at[pos_old].set(pv, mode="drop").at[pos_new].set(new_v, mode="drop")
    out_n = out_n.at[pos_old].set(pn, mode="drop").at[pos_new].set(new_n, mode="drop")
    out_r = out_r.at[pos_old].set(pr, mode="drop").at[pos_new].set(new_r, mode="drop")
    return out_v, out_n, out_r


def prepare_packed_window(
    pv, pn, pr, src, dst, mask, rank0, num_vertices: int,
    search_steps: int = 32,
):
    """One-dispatch window prep for streaming exact triangles: canonicalize
    the window's raw edges, drop self-loops, dedup in-window, reject edges
    already present in the packed adjacency (ranged binary search), sort
    the survivors' two directed entries, merge them into the packed
    columns, and rebuild the row pointer — entirely on device.

    The previous design did the dedup (np.unique + hash set) and the
    entry sort (np.lexsort) on the host: ~220 ms per 256k-edge window,
    which WAS the system rate (round-3 profile). Returns
    ``(pv, pn, pr, row_ptr, qu, qv, qrank, qmask)`` where the q-arrays
    are the accepted query edges aligned with the input slots.
    """
    n = src.shape[0]
    u, v, m = canonicalize(src, dst, mask)
    u, v, m = dedup_canonical(u, v, m, num_vertices)
    # cross-window duplicates: is (u, v) already a packed row of u?
    row_ptr0 = jnp.searchsorted(
        pv, jnp.arange(num_vertices + 1, dtype=jnp.int32)
    ).astype(jnp.int32)
    uc = jnp.clip(u, 0, num_vertices - 1)
    lo = row_ptr0[uc]
    hi = row_ptr0[uc + 1]
    pos = ranged_searchsorted(pn, lo, hi, v, steps=search_steps)
    pos_c = jnp.clip(pos, 0, pn.shape[0] - 1)
    dup = (pos < hi) & (pn[pos_c] == v)
    m = m & ~dup
    qrank = rank0 + jnp.arange(n, dtype=jnp.int32)
    # both directed entries of every accepted edge; rejected slots become
    # +INT32_MAX sentinels and sort to the tail
    pv_new = jnp.concatenate([jnp.where(m, u, _BIG), jnp.where(m, v, _BIG)])
    pn_new = jnp.concatenate([jnp.where(m, v, 0), jnp.where(m, u, 0)])
    pr_new = jnp.concatenate([jnp.where(m, qrank, 0)] * 2)
    spv, spn, spr = jax.lax.sort((pv_new, pn_new, pr_new), num_keys=2)
    n_new = 2 * m.sum().astype(jnp.int32)
    pv2, pn2, pr2 = merge_packed_adjacency(pv, pn, pr, spv, spn, spr, n_new)
    row_ptr = jnp.searchsorted(
        pv2, jnp.arange(num_vertices + 1, dtype=jnp.int32)
    ).astype(jnp.int32)
    return pv2, pn2, pr2, row_ptr, u, v, qrank, m


# --------------------------------------------------------------------- #
# Shared packed-adjacency carry helpers (used by the streaming triangle
# pipeline AND the k=2 device spanner — one implementation of the growth,
# host-side build, class-binning, and recompile-avoidance policies).
# --------------------------------------------------------------------- #

def grow_packed_columns(pv, pn, pr, need: int, minimum: int = 8):
    """Grow (or create) packed (vertex, nbr, rank) columns to a pow2
    bucket covering ``need`` entries — appending +INT32_MAX vertex
    sentinels keeps the sort order."""
    from ..core.edgeblock import bucket_capacity

    cap = bucket_capacity(max(need, minimum))
    if pv is None:
        return (
            jnp.full(cap, _BIG, jnp.int32),
            jnp.zeros(cap, jnp.int32),
            jnp.zeros(cap, jnp.int32),
        )
    old = pv.shape[0]
    if cap <= old:
        return pv, pn, pr
    return (
        jnp.concatenate([pv, jnp.full(cap - old, _BIG, jnp.int32)]),
        jnp.concatenate([pn, jnp.zeros(cap - old, jnp.int32)]),
        jnp.concatenate([pr, jnp.zeros(cap - old, jnp.int32)]),
    )


def build_sorted_directed(u, v, ranks=None, cap=None):
    """Host-side build of both directed entries of canonical edges,
    (vertex, nbr)-lexsorted and sentinel-padded: the merge input format
    of :func:`merge_packed_adjacency`. Returns numpy
    ``(pv, pn, pr, n_new)``."""
    import numpy as _np

    from ..core.edgeblock import bucket_capacity

    pv_new = _np.concatenate([u, v])
    pn_new = _np.concatenate([v, u])
    if ranks is None:
        pr_new = _np.zeros(len(pv_new), _np.int32)
    else:
        pr_new = _np.concatenate([ranks, ranks])
    order = _np.lexsort((pn_new, pv_new))
    n_new = len(pv_new)
    ncap = cap if cap is not None else bucket_capacity(n_new, minimum=16)
    pvp = _np.full(ncap, _np.iinfo(_np.int32).max, _np.int32)
    pnp = _np.zeros(ncap, _np.int32)
    prp = _np.zeros(ncap, _np.int32)
    pvp[:n_new] = pv_new[order]
    pnp[:n_new] = pn_new[order]
    prp[:n_new] = pr_new[order]
    return pvp, pnp, prp, n_new


#: min-degree classes coarsen by powers of this factor: a handful of
#: dispatches per window (each enqueue is milliseconds through the remote
#: tunnel) for at most CLASS_FACTOR x enumeration-width waste in a class
CLASS_FACTOR = 4

#: [chunk, width] int32 entries budget for dense enumeration blocks
ENUM_BUDGET = 1 << 24  # 64 MB


def degree_class_plan(mindeg, class_factor: int = CLASS_FACTOR,
                      enum_budget: int = ENUM_BUDGET):
    """Group query indices into coarse min-degree classes.

    Yields ``(width, sel, tcap, chunk)`` per class: ``sel`` the query
    indices (numpy int32), ``tcap`` their pow2 padding, ``chunk`` the
    scan slice keeping [chunk, width] within ``enum_budget``.
    """
    import numpy as _np

    from ..core.edgeblock import bucket_capacity

    fbits = int(class_factor).bit_length() - 1
    exp = _np.ceil(
        _np.log2(_np.maximum(_np.maximum(mindeg, 16), 1)) / fbits
    ).astype(_np.int64)
    classes = _np.int64(1) << (exp * fbits)
    for c in _np.unique(classes):
        sel = _np.nonzero(classes == c)[0].astype(_np.int32)
        tcap = bucket_capacity(len(sel), minimum=16)
        chunk = min(tcap, bucket_capacity(max(enum_budget // int(c), 16)))
        yield int(c), sel, tcap, int(chunk)


def chunked_class_scan(body_fn, carry, sel, chunk: int):
    """Scan one degree class's padded selection (``-1`` padding) in
    ``chunk`` slices: ``body_fn(carry, sel_slice) -> carry``. The shared
    scaffold of the per-class query kernels (triangle counting, spanner
    common-neighbor tests) — bounds the [chunk, width] enumeration block
    instead of materializing the whole class at once. ``sel`` length and
    ``chunk`` are both powers of two, so the reshape is exact."""
    sel_r = sel.reshape(sel.shape[0] // chunk, chunk)
    out, _ = jax.lax.scan(lambda c, s: (body_fn(c, s), None), carry, sel_r)
    return out


def sticky_search_steps(current: int, max_degree: int) -> int:
    """Monotone, 8-quantized binary-search step count covering the
    longest adjacency row: at most a few distinct jit signatures over a
    stream's lifetime (each recompile costs ~20-40 s through the remote
    compiler), instead of churning every time the max degree crosses a
    pow2 bucket."""
    from ..core.edgeblock import bucket_capacity

    needed = max(4, int(bucket_capacity(max(int(max_degree), 1))).bit_length())
    return max(current, ((needed + 7) // 8) * 8)


def packed_common_neighbor_exists(
    pn, row_ptr, qu, qv, qmask, enum_width: int, search_steps: int = 32,
):
    """For each query pair (qu, qv): do their packed-adjacency rows share
    a neighbor? The k=2 reachability primitive of the device spanner —
    common-neighbor existence over the same packed sorted adjacency the
    triangle pipeline carries, with per-class dense enumeration rows (the
    caller groups queries by min-degree class). No [B, V] frontier."""
    d_u = row_ptr[qu + 1] - row_ptr[qu]
    d_v = row_ptr[qv + 1] - row_ptr[qv]
    take_u = d_u <= d_v
    small = jnp.where(take_u, qu, qv)
    big = jnp.where(take_u, qv, qu)
    idx = row_ptr[small][:, None] + jnp.arange(enum_width)[None, :]
    valid = (
        qmask[:, None]
        & (jnp.arange(enum_width)[None, :] < jnp.minimum(d_u, d_v)[:, None])
    )
    idx = jnp.clip(idx, 0, pn.shape[0] - 1)
    w = pn[idx]
    lo = jnp.broadcast_to(row_ptr[big][:, None], w.shape)
    hi = jnp.broadcast_to(row_ptr[big + 1][:, None], w.shape)
    pos = ranged_searchsorted(pn, lo, hi, w, steps=search_steps)
    pos_c = jnp.clip(pos, 0, pn.shape[0] - 1)
    found = valid & (pos < hi) & (pn[pos_c] == w)
    return found.any(axis=1)


def packed_triangle_update(
    pn, pr, row_ptr,
    qu, qv, qrank, qmask,
    counts,
    enum_width: int,
    search_steps: int = 32,
):
    """Count triangles closed by query edges against a PACKED adjacency.

    ``pn``/``pr``: neighbor/rank columns of the packed (vertex, nbr)-sorted
    adjacency; ``row_ptr[v]`` the start of v's run. Each query edge
    enumerates the neighborhood of its SMALLER-degree endpoint (the caller
    groups queries into ``enum_width`` degree classes, so dense enumeration
    rows are only as wide as each class — no hub sizes anyone else's rows;
    memory is O(E) total) and checks each candidate w against the larger
    endpoint's run with a ranged binary search, under the closed-by-last-
    edge rank rule: both closing edges strictly earlier than the query.
    Returns ``(counts, delta)``.
    """
    d_u = row_ptr[qu + 1] - row_ptr[qu]
    d_v = row_ptr[qv + 1] - row_ptr[qv]
    take_u = d_u <= d_v
    small = jnp.where(take_u, qu, qv)
    big = jnp.where(take_u, qv, qu)
    idx = row_ptr[small][:, None] + jnp.arange(enum_width)[None, :]
    valid = (
        qmask[:, None]
        & (jnp.arange(enum_width)[None, :] < jnp.minimum(d_u, d_v)[:, None])
    )
    idx = jnp.clip(idx, 0, pn.shape[0] - 1)
    w = pn[idx]
    wr = pr[idx]
    lo = jnp.broadcast_to(row_ptr[big][:, None], w.shape)
    hi = jnp.broadcast_to(row_ptr[big + 1][:, None], w.shape)
    pos = ranged_searchsorted(pn, lo, hi, w, steps=search_steps)
    pos_c = jnp.clip(pos, 0, pn.shape[0] - 1)
    found = (pos < hi) & (pn[pos_c] == w)
    r = qrank[:, None]
    match = valid & found & (wr < r) & (pr[pos_c] < r)
    c = match.sum(axis=1).astype(jnp.int32)
    w_ids = jnp.where(match, w, 0)
    counts = counts.at[w_ids.reshape(-1)].add(match.reshape(-1).astype(jnp.int32))
    cm = jnp.where(qmask, c, 0)
    counts = counts.at[qu].add(cm).at[qv].add(cm)
    return counts, cm.sum().astype(jnp.int32)


