from .segment import (
    segment_reduce,
    segment_count,
    segmented_fold,
    segmented_reduce_generic,
    sort_by_segment,
)
from .csr import CSR, build_csr, dense_neighbors, sorted_neighbor_matrix
