"""gelly_streaming_tpu: TPU-native single-pass streaming graph analytics.

A from-scratch re-design of the capabilities of ``gelly-streaming`` (Flink's
experimental graph-streaming API) for JAX/XLA on TPU. See SURVEY.md at the
repo root for the structural analysis of the reference this build follows.

Quick tour::

    from gelly_streaming_tpu import SimpleEdgeStream, CountWindow, EdgeDirection

    stream = SimpleEdgeStream(edges, window=CountWindow(1_000_000))
    for vertex, degree in stream.get_degrees():
        ...  # continuously-improving degree stream (per-window change-only)
    snap = stream.slice(direction=EdgeDirection.ALL)
    for vertex, total in snap.reduce_on_edges("sum"):
        ...  # per-window neighborhood aggregate
"""

from .core.types import Edge, EdgeDirection, EventType, Vertex
from .core.edgeblock import EdgeBlock, bucket_capacity, concat_blocks
from .core.vertexdict import VertexDict
from .core.window import (
    CountWindow,
    EventTimeWindow,
    ProcessingTimeWindow,
    ScheduledCountWindow,
    Windower,
    blocks_from_edges,
)
from .core.stream import GraphStream, SimpleEdgeStream, StreamContext
from .core.snapshot import SnapshotStream
from .core.sources import GeneratorSource, SocketEdgeSource
from .aggregate.autockpt import AutoCheckpoint
from .resilience import FaultPlan, RetryPolicy, Supervisor

__version__ = "0.1.0"

__all__ = [
    "Edge",
    "EdgeDirection",
    "EventType",
    "Vertex",
    "EdgeBlock",
    "bucket_capacity",
    "concat_blocks",
    "VertexDict",
    "CountWindow",
    "EventTimeWindow",
    "ProcessingTimeWindow",
    "ScheduledCountWindow",
    "Windower",
    "blocks_from_edges",
    "GraphStream",
    "SimpleEdgeStream",
    "StreamContext",
    "SnapshotStream",
    "SocketEdgeSource",
    "GeneratorSource",
    "AutoCheckpoint",
    "FaultPlan",
    "RetryPolicy",
    "Supervisor",
]
