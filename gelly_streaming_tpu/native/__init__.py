"""Native host-runtime components (C++ via ctypes, no pybind11).

The compute path is JAX/XLA; the runtime AROUND it is native where it
matters. Today that is file ingest (``ingest.cpp``): parsing large edge
lists in Python is ~50x slower than the device consumes them. Reference
analog: Flink's parallel text sources + per-line split mappers
(``env.readTextFile``, ``ConnectedComponentsExample.java:106-118``) — the
reference itself is 100% Java with no native code (SURVEY.md §2), so this
layer replaces the JVM runtime, not a C++ one.

The shared library builds lazily on first use with ``g++ -O3`` and is
cached next to the source; every entry point has a pure-numpy fallback so
the package works without a toolchain.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Iterator, Optional, Tuple

import numpy as np

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "ingest.cpp")
_SO = os.path.join(_HERE, "_ingest.so")
_lock = threading.Lock()
_lib = None
_lib_failed = False


def _host_isa() -> str:
    """Fingerprint of the host ISA the cached .so must match.

    The build uses ``-march=native``, so a cached binary is only valid on
    a host with the same CPU feature set — reusing an AVX-512-specialized
    .so on a host without AVX-512 dies with SIGILL, which no exception
    handler can catch. A checkout can move between machines (NFS, docker
    bake), so the sidecar carries this fingerprint too."""
    import platform

    flags = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    flags = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        pass
    return hashlib.sha256(
        (platform.machine() + "|" + flags).encode()
    ).hexdigest()[:16]


def _stale(digest: str) -> bool:
    """The build is stale unless the .so's hash sidecar matches the source
    AND the host ISA.

    Content hash, not mtime: a checkout or copy can leave any mtime order,
    and a binary silently out of sync with its source is worse than a
    rebuild."""
    if not os.path.exists(_SO):
        return True
    try:
        with open(_SO + ".hash") as f:
            return f.read().strip() != digest + ":" + _host_isa()
    except OSError:
        return True


def _load() -> Optional[ctypes.CDLL]:
    """Compile (once) and load the ingest library; None if unavailable."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            # graftlint: disable=GL009 (one-time double-checked compile-and-load; a thread that needs the library MUST wait for the build — the lock exists to make everyone wait exactly once)
            with open(_SRC, "rb") as f:
                digest = hashlib.sha256(f.read()).hexdigest()
            # graftlint: disable=GL009 (one-time double-checked compile-and-load; a thread that needs the library MUST wait for the build — the lock exists to make everyone wait exactly once)
            if _stale(digest):
                # -march=native unlocks the AVX-512 line scanner where the
                # host supports it; fall back to a generic build elsewhere
                # (the source guards all intrinsics with __AVX512BW__)
                base = ["g++", "-O3", "-shared", "-fPIC", "-pthread",
                        "-o", _SO + ".tmp", _SRC]
                native_try = base[:1] + ["-march=native"] + base[1:]
                r = subprocess.run(native_try, capture_output=True)
                if r.returncode != 0:
                    subprocess.run(base, check=True, capture_output=True)
                os.replace(_SO + ".tmp", _SO)
                # graftlint: disable=GL009 (one-time double-checked compile-and-load; a thread that needs the library MUST wait for the build — the lock exists to make everyone wait exactly once)
                with open(_SO + ".hash", "w") as f:
                    # graftlint: disable=GL009 (one-time double-checked compile-and-load; a thread that needs the library MUST wait for the build — the lock exists to make everyone wait exactly once)
                    f.write(digest + ":" + _host_isa())
            lib = ctypes.CDLL(_SO)
            i64 = ctypes.c_int64
            p64 = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
            pf64 = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
            pi32 = ctypes.POINTER(ctypes.c_int32)
            lib.write_edge_file.restype = i64
            lib.write_edge_file.argtypes = [
                ctypes.c_char_p, p64, p64, i64, ctypes.c_int32,
                ctypes.c_int32,
            ]
            lib.cc_baseline_run.restype = i64
            lib.cc_baseline_run.argtypes = [
                p64, p64, i64, i64, ctypes.c_int32, ctypes.POINTER(i64),
            ]
            lib.flink_proxy_run.restype = i64
            lib.flink_proxy_run.argtypes = [
                p64, p64, i64, i64, ctypes.c_int32, ctypes.POINTER(i64),
            ]
            pi32a = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
            lib.encoder_create.restype = ctypes.c_void_p
            lib.encoder_destroy.argtypes = [ctypes.c_void_p]
            lib.encoder_encode.restype = i64
            lib.encoder_encode.argtypes = [ctypes.c_void_p, p64, i64, pi32a, p64]
            lib.encoder_encode2.restype = i64
            lib.encoder_encode2.argtypes = [
                ctypes.c_void_p, p64, p64, i64, pi32a, pi32a, p64,
            ]
            lib.reader_open.restype = ctypes.c_void_p
            lib.reader_open.argtypes = [ctypes.c_char_p, i64]
            lib.reader_close.argtypes = [ctypes.c_void_p]
            lib.reader_offset.restype = i64
            lib.reader_offset.argtypes = [ctypes.c_void_p]
            lib.reader_next_span.restype = i64
            lib.reader_next_span.argtypes = [
                ctypes.c_void_p, p64, p64, pf64, i64, pi32, pi32,
                ctypes.c_int32,
            ]
            lib.reader_next_encoded.restype = i64
            lib.reader_next_encoded.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, pi32a, pi32a, pf64, i64,
                p64, ctypes.POINTER(i64), pi32, pi32,
            ]
            lib.reader_next_span_i32.restype = i64
            lib.reader_next_span_i32.argtypes = [
                ctypes.c_void_p, pi32a, pi32a, pf64, i64, i64, pi32, pi32,
                ctypes.POINTER(i64),
            ]
            lib.encoder_lookup.restype = ctypes.c_int32
            lib.encoder_lookup.argtypes = [ctypes.c_void_p, i64]
            lib.encoder_lookup_batch.restype = None
            lib.encoder_lookup_batch.argtypes = [
                ctypes.c_void_p, p64, i64, pi32a,
            ]
            lib.encoder_size.restype = i64
            lib.encoder_size.argtypes = [ctypes.c_void_p]
            lib.vbitmap_create.restype = ctypes.c_void_p
            lib.vbitmap_destroy.argtypes = [ctypes.c_void_p]
            lib.vbitmap_novel2.restype = i64
            lib.vbitmap_novel2.argtypes = [
                ctypes.c_void_p, pi32a, pi32a, i64,
            ]
            lib.cuf_create.restype = ctypes.c_void_p
            lib.cuf_destroy.argtypes = [ctypes.c_void_p]
            lib.cuf_fold_window.restype = i64
            lib.cuf_fold_window.argtypes = [
                ctypes.c_void_p, pi32a, pi32a, i64, i64,
                pi32a, pi32a, pi32a, pi32a, ctypes.POINTER(i64),
            ]
            lib.cuf_fold_group.restype = i64
            lib.cuf_fold_group.argtypes = [
                ctypes.c_void_p, pi32a, pi32a, p64, i64, i64,
                pi32a, pi32a, pi32a, pi32a, p64, p64, pi32a, pi32a,
                p64, ctypes.POINTER(i64),
            ]
            lib.cuf_flatten.argtypes = [ctypes.c_void_p, pi32a, i64]
            lib.cuf_load.restype = i64
            lib.cuf_load.argtypes = [ctypes.c_void_p, pi32a, i64]
            lib.wprep_create.restype = ctypes.c_void_p
            lib.wprep_destroy.argtypes = [ctypes.c_void_p]
            lib.wprep_run.restype = i64
            lib.wprep_run.argtypes = [
                ctypes.c_void_p, pi32a, pi32a, i64, i64, pi32a, pi32a, pi32a,
            ]
            lib.decode_edge_frame.restype = i64
            lib.decode_edge_frame.argtypes = [
                ctypes.c_char_p, i64, i64, ctypes.c_int32, ctypes.c_int32,
                p64, p64, pf64,
            ]
            lib.parse_edge_lines.restype = i64
            lib.parse_edge_lines.argtypes = [
                ctypes.c_char_p, i64, p64, p64, pf64, i64, pi32,
                ctypes.POINTER(i64),
            ]
            _lib = lib
        except Exception:
            _lib_failed = True
    return _lib


def native_available() -> bool:
    return _load() is not None


def parse_edge_file(path: str) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]:
    """Parse a whole edge-list file into (src, dst, val|None) columns.

    Third column (value/timestamp/±flag as ±1.0) is returned when present.
    One span-parse pass (no separate counting pass): chunks concatenate.
    """
    lib = _load()
    if lib is None:
        return _parse_python(path)
    srcs, dsts, vals = [], [], []
    any_val = False
    for s, d, v in iter_edge_chunks(path, chunk_edges=1 << 22):
        srcs.append(s)
        dsts.append(d)
        vals.append(v)
        any_val = any_val or v is not None
    if not srcs:
        return np.zeros(0, np.int64), np.zeros(0, np.int64), None
    src = np.concatenate(srcs)
    dst = np.concatenate(dsts)
    if not any_val:
        return src, dst, None
    val = np.concatenate(
        [np.zeros(len(s), np.float64) if v is None else v
         for s, v in zip(srcs, vals)]
    )
    return src, dst, val


def iter_edge_chunks(
    path: str, chunk_edges: int = 1 << 20, threads: Optional[int] = None
) -> Iterator[Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]]:
    """Stream (src, dst, val|None) column chunks from a file — the bounded-
    memory ingest path for streams larger than RAM.

    Chunk boundaries are byte-budgeted (``chunk_edges`` times an average
    line-length estimate), so yields carry *approximately* ``chunk_edges``
    edges; exact window discretization is the Windower's job downstream.
    Each span is parsed by ``threads`` workers (default: all cores).
    """
    lib = _load()
    if lib is None:
        src, dst, val = _parse_python(path)
        for a in range(0, len(src), chunk_edges):
            b = a + chunk_edges
            yield src[a:b], dst[a:b], None if val is None else val[a:b]
        return
    if threads is None:
        threads = os.cpu_count() or 1
    budget = min(max(chunk_edges * 20, 4096), 1 << 28)
    cap = budget // 4 + 64
    handle = lib.reader_open(path.encode(), budget)
    if not handle:
        raise IOError(f"cannot read {path}")
    try:
        src = np.empty(cap, np.int64)
        dst = np.empty(cap, np.int64)
        val = np.empty(cap, np.float64)
        has_val = ctypes.c_int32(0)
        at_eof = ctypes.c_int32(0)
        while True:
            prev = lib.reader_offset(handle)
            got = lib.reader_next_span(
                handle, src, dst, val, cap,
                ctypes.byref(has_val), ctypes.byref(at_eof), threads,
            )
            if got < 0:
                raise IOError(f"cannot read {path}")
            if got:
                yield (
                    src[:got].copy(),
                    dst[:got].copy(),
                    val[:got].copy() if has_val.value else None,
                )
            if at_eof.value:
                return
            # got == 0 with more file left is fine as long as the offset
            # moved (a span of comments/blanks); no progress means a single
            # line larger than the byte budget — error, don't drop the rest.
            if got == 0 and lib.reader_offset(handle) == prev:
                raise IOError(
                    f"{path}: line at byte {prev} exceeds the span read "
                    "budget"
                )
    finally:
        lib.reader_close(handle)


def iter_edge_chunks_i32(
    path: str, chunk_edges: int = 1 << 20, id_bound: int = 0
) -> Iterator[Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]]:
    """Like :func:`iter_edge_chunks` but yields int32 endpoint columns
    directly (dense-id corpora: half the column traffic, no convert or
    validation pass downstream). Raises when any id falls outside
    ``[0, id_bound)`` (or outside int32 when ``id_bound`` is 0)."""
    lib = _load()
    if lib is None:
        for s, d, v in iter_edge_chunks(path, chunk_edges):
            hi = id_bound if id_bound else np.iinfo(np.int32).max
            if len(s) and (
                int(s.min()) < 0 or int(s.max()) >= hi
                or int(d.min()) < 0 or int(d.max()) >= hi
            ):
                raise ValueError(
                    f"{path}: raw id outside [0, {hi}) — not a dense-id "
                    "corpus"
                )
            yield s.astype(np.int32), d.astype(np.int32), v
        return
    budget = min(max(chunk_edges * 20, 4096), 1 << 28)
    cap = budget // 4 + 64
    handle = lib.reader_open(path.encode(), budget)
    if not handle:
        raise IOError(f"cannot read {path}")
    try:
        src = np.empty(cap, np.int32)
        dst = np.empty(cap, np.int32)
        val = np.empty(cap, np.float64)
        has_val = ctypes.c_int32(0)
        at_eof = ctypes.c_int32(0)
        oob = ctypes.c_int64(0)
        while True:
            prev = lib.reader_offset(handle)
            got = lib.reader_next_span_i32(
                handle, src, dst, val, cap, id_bound,
                ctypes.byref(has_val), ctypes.byref(at_eof),
                ctypes.byref(oob),
            )
            if got < 0:
                raise IOError(f"cannot read {path}")
            if oob.value:
                hi = id_bound if id_bound else np.iinfo(np.int32).max
                raise ValueError(
                    f"{path}: {oob.value} ids outside [0, {hi}) — not a "
                    "dense-id corpus"
                )
            if got:
                yield (
                    src[:got].copy(),
                    dst[:got].copy(),
                    val[:got].copy() if has_val.value else None,
                )
            if at_eof.value:
                return
            if got == 0 and lib.reader_offset(handle) == prev:
                raise IOError(
                    f"{path}: line at byte {prev} exceeds the span read "
                    "budget"
                )
    finally:
        lib.reader_close(handle)


def write_edge_file(
    path: str,
    src: np.ndarray,
    dst: np.ndarray,
    append: bool = False,
    threads: Optional[int] = None,
) -> None:
    """Write a tab-separated edge list (corpus synthesis at scale).

    ~100x ``np.savetxt``: per-thread integer formatting into string
    buffers, written sequentially. Non-negative ids only (the formats of
    the BASELINE corpora)."""
    src = np.ascontiguousarray(src, np.int64)
    dst = np.ascontiguousarray(dst, np.int64)
    lib = _load()
    if lib is None:
        with open(path, "a" if append else "w") as f:
            for s, d in zip(src.tolist(), dst.tolist()):
                f.write(f"{s}\t{d}\n")
        return
    if threads is None:
        threads = os.cpu_count() or 1
    rc = lib.write_edge_file(
        path.encode(), src, dst, src.size, 1 if append else 0, threads
    )
    if rc != 0:
        raise IOError(f"cannot write {path}")


def cc_baseline(
    src: np.ndarray,
    dst: np.ndarray,
    window: int,
    partitions: Optional[int] = None,
) -> Tuple[float, int]:
    """Run the compiled streaming-CC baseline (the reference's execution
    model — per-partition window folds into hash-map union-find +
    sequential merge — compiled to native code). Returns (seconds,
    component_count). Raises when the native library is unavailable: a
    Python fallback would not be a meaningful baseline."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native toolchain unavailable for the baseline")
    src = np.ascontiguousarray(src, np.int64)
    dst = np.ascontiguousarray(dst, np.int64)
    if partitions is None:
        partitions = min(8, os.cpu_count() or 1)
    comps = ctypes.c_int64(0)
    ns = lib.cc_baseline_run(
        src, dst, src.size, window, partitions, ctypes.byref(comps)
    )
    return ns / 1e9, int(comps.value)


def flink_proxy(
    src: np.ndarray,
    dst: np.ndarray,
    window: int,
    partitions: Optional[int] = None,
) -> Tuple[float, int]:
    """Run the Flink-representative streaming-CC proxy: the reference's
    job graph with per-record serialized shuffles and a serialized
    partial-merge boundary (``ingest.cpp:flink_proxy_run``). An UPPER
    bound on real single-host Flink throughput for this job — no JVM,
    no netty, no GC — so ratios against it are conservative. Returns
    (seconds, component_count)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native toolchain unavailable for the proxy")
    src = np.ascontiguousarray(src, np.int64)
    dst = np.ascontiguousarray(dst, np.int64)
    if partitions is None:
        partitions = min(8, os.cpu_count() or 1)
    comps = ctypes.c_int64(0)
    ns = lib.flink_proxy_run(
        src, dst, src.size, window, partitions, ctypes.byref(comps)
    )
    return ns / 1e9, int(comps.value)


_I64_MAX = 2**63 - 1


def _saturate_i64(token: str) -> int:
    """Signed decimal with C-parser saturation: |value| clamps to
    INT64_MAX before the sign is applied."""
    neg = token.startswith("-")
    mag = min(int(token.lstrip("+-")), _I64_MAX)
    return -mag if neg else mag


_LINE_RE = None


def _parse_text_lines(lines):
    """The shared python-fallback line grammar (mirrors the C parser
    char-for-char — see :func:`_parse_python`). Consumes an iterable of
    text lines; returns ``(srcs, dsts, vals, any_val, malformed)`` with
    ``malformed`` counting non-blank, non-comment lines the grammar
    rejected (the file path ignores the count; the socket path reports
    it)."""
    global _LINE_RE
    import re

    if _LINE_RE is None:
        _LINE_RE = (
            re.compile(r"^[ \t,\r]*([+-]?\d+)[ \t,\r]+([+-]?\d+)(.*)$"),
            re.compile(r"^[+-]?(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?"),
        )
    line_re, float_re = _LINE_RE
    srcs, dsts, vals = [], [], []
    any_val = False
    malformed = 0
    for line in lines:
        stripped = line.lstrip(" \t,\r")
        if not stripped or stripped[0] in "#%\n":
            continue
        m = line_re.match(line.rstrip("\n"))
        if not m:
            malformed += 1
            continue
        # ids beyond int64 saturate (sign applied after), matching the
        # C parser's digit-counted saturation — so oob/id-bound checks
        # fire identically on both paths instead of OverflowError here
        # vs a silent wrap there (round-2 advisor finding)
        srcs.append(_saturate_i64(m.group(1)))
        dsts.append(_saturate_i64(m.group(2)))
        rest = m.group(3).lstrip(" \t,\r")
        v = 0.0
        if rest:
            c0 = rest[0]
            follows = rest[1:2]
            if c0 == "+" and follows in ("", " ", "\t", "\r"):
                v = 1.0
                any_val = True
            elif c0 == "-" and follows in ("", " ", "\t", "\r"):
                v = -1.0
                any_val = True
            else:
                fm = float_re.match(rest)
                if fm:
                    v = float(fm.group(0))
                    any_val = True
        vals.append(v)
    return srcs, dsts, vals, any_val, malformed


def _parse_python(path: str):
    """Numpy fallback when no C++ toolchain is available.

    Mirrors the C grammar char-for-char (prefix number parsing, not token
    splitting): two integers separated by space/tab/comma runs, trailing
    junk after a number tolerated, an unparseable THIRD column leaves the
    edge valid with value 0 (the strtod-failure behavior). Never raises
    on noise — the fuzz suite holds the two parsers byte-equivalent."""
    with open(path) as f:
        srcs, dsts, vals, any_val, _malformed = _parse_text_lines(f)
    src = np.asarray(srcs, np.int64)
    dst = np.asarray(dsts, np.int64)
    return src, dst, (np.asarray(vals, np.float64) if any_val else None)


def parse_edge_lines(
    data: bytes,
) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray], int]:
    """Parse a buffer of complete text edge lines into ``(src, dst,
    val|None, malformed)`` columns — the socket text hot path's
    one-call-per-recv chunk parse (ISSUE 11 satellite), replacing
    per-line Python ``split()``/``int()``.

    The accepted grammar is the FILE parser's (native fast parser, or
    the byte-equivalent regex fallback without the toolchain), so a
    socket stream and the same bytes on disk parse identically.
    ``malformed`` counts non-blank, non-comment lines the grammar
    rejected; the caller owns the counter semantics
    (``source.malformed_lines``). ``data`` need not end with a newline
    (a terminator is supplied), but must contain only COMPLETE lines —
    the caller keeps any partial trailing line in its recv buffer."""
    lib = _load()
    if lib is None:
        srcs, dsts, vals, any_val, malformed = _parse_text_lines(
            data.decode("latin-1").split("\n")
        )
        return (
            np.asarray(srcs, np.int64),
            np.asarray(dsts, np.int64),
            np.asarray(vals, np.float64) if any_val else None,
            malformed,
        )
    cap = data.count(b"\n") + 2
    src = np.empty(cap, np.int64)
    dst = np.empty(cap, np.int64)
    val = np.empty(cap, np.float64)
    has_val = ctypes.c_int32(0)
    malformed = ctypes.c_int64(0)
    # newline-terminate the final line + READ_PAD zeros for SWAR loads
    buf = data + b"\n" + bytes(64)
    got = lib.parse_edge_lines(
        buf, len(data) + 1, src, dst, val, cap,
        ctypes.byref(has_val), ctypes.byref(malformed),
    )
    return (
        src[:got].copy(),
        dst[:got].copy(),
        val[:got].copy() if has_val.value else None,
        int(malformed.value),
    )


def decode_edge_frame(
    payload: bytes, n_edges: int, wide: bool, has_val: bool
) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]:
    """Decode one GSEW binary frame payload (``core/ingest.py``) into
    engine-ready columns ``(src i64, dst i64, val f64|None)`` — ONE
    native call per frame (geometry check + int32 widen + copy into
    fresh buffers), replacing the text path's per-line integer parsing
    entirely. Numpy ``frombuffer`` fallback without the toolchain.
    Raises ``ValueError`` when the payload size disagrees with the
    header-declared geometry (the caller counts a malformed frame)."""
    n = int(n_edges)
    isz = 8 if wide else 4
    want = n * isz * 2 + (8 * n if has_val else 0)
    lib = _load()
    if lib is None or n == 0:
        if len(payload) != want:
            raise ValueError(
                f"frame payload is {len(payload)} bytes; declared "
                f"geometry (n={n}, wide={bool(wide)}, "
                f"val={bool(has_val)}) wants {want}"
            )
        dt = np.int64 if wide else np.int32
        src = np.frombuffer(payload, dt, n, 0).astype(np.int64)
        dst = np.frombuffer(payload, dt, n, n * isz).astype(np.int64)
        val = (
            np.frombuffer(payload, np.float64, n, 2 * n * isz).copy()
            if has_val else None
        )
        return src, dst, val
    src = np.empty(n, np.int64)
    dst = np.empty(n, np.int64)
    val = np.empty(n if has_val else 0, np.float64)
    rc = lib.decode_edge_frame(
        payload, len(payload), n, 1 if wide else 0, 1 if has_val else 0,
        src, dst, val,
    )
    if rc != 0:
        raise ValueError(
            f"frame payload is {len(payload)} bytes; declared geometry "
            f"(n={n}, wide={bool(wide)}, val={bool(has_val)}) wants {want}"
        )
    return src, dst, (val if has_val else None)


class NoveltyBitmap:
    """First-seen counter over the non-negative int32 id space.

    ``novel2(src, dst)`` records both endpoint columns (interleaved
    arrival order) and returns how many ids were never seen before —
    EXACT distinctness, which lets the device-encode ingest grow its
    on-device dictionary proactively from host knowledge alone instead of
    reading a count back through the tunnel (~0.5-3 s per scalar fetch,
    round 3). Native: a lazily-committed 2^31-bit anonymous mmap.
    Fallback: a numpy byte map grown to the observed id range.
    """

    def __init__(self):
        self._lib = _load()
        self._h = self._lib.vbitmap_create() if self._lib is not None else None
        if self._lib is not None and not self._h:
            self._lib = None  # mmap failed: numpy fallback
        self._bits: Optional[np.ndarray] = None  # fallback storage

    def novel2(self, src: np.ndarray, dst: np.ndarray) -> int:
        src = np.ascontiguousarray(src, np.int32)
        dst = np.ascontiguousarray(dst, np.int32)
        if self._lib is not None:
            return int(self._lib.vbitmap_novel2(self._h, src, dst, src.size))
        ids = np.stack([src, dst], axis=1).ravel()
        ids = ids[ids >= 0]
        if ids.size == 0:
            return 0
        uniq = np.unique(ids).astype(np.int64)
        # bit-packed like the native mmap (max 256 MB at the int32
        # extreme, not 2 GB byte-per-id)
        hi = (int(uniq[-1]) >> 3) + 1
        if self._bits is None or self._bits.size < hi:
            grown = np.zeros(max(hi, 1024), np.uint8)
            if self._bits is not None:
                grown[: self._bits.size] = self._bits
            self._bits = grown
        cell = uniq >> 3
        mask = np.uint8(1) << (uniq & 7).astype(np.uint8)
        fresh = (self._bits[cell] & mask) == 0
        np.bitwise_or.at(self._bits, cell[fresh], mask[fresh])
        return int(fresh.sum())

    def __del__(self):
        lib = getattr(self, "_lib", None)
        h = getattr(self, "_h", None)
        if lib is not None and h:
            lib.vbitmap_destroy(h)


class CompactUnionFind:
    """Incremental union-find over compact int32 ids — the host CC carry
    (``ingest.cpp: cuf_*``; placement rationale in
    ``library/connected_components.py``).

    ``fold(src, dst, vcap)`` unions one window and returns
    ``(touched, roots, changed, changed_roots)``: the window's distinct
    endpoints with their post-window roots, plus every root demoted by
    this window with its post-window root — exactly the scatter a device
    pointer-forest mirror needs to stay resolvable.

    Raises ``RuntimeError`` at construction when the native toolchain is
    unavailable; callers fall back to the device forest carry.
    """

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError("native toolchain unavailable")
        self._lib = lib
        self._h = lib.cuf_create()
        if not self._h:
            raise RuntimeError("cuf_create failed")
        self._tbuf = np.zeros(1024, np.int32)
        self._rbuf = np.zeros(1024, np.int32)
        self._cbuf = np.zeros(1024, np.int32)
        self._crbuf = np.zeros(1024, np.int32)

    def fold(self, src: np.ndarray, dst: np.ndarray, vcap: int):
        src = np.ascontiguousarray(src, np.int32)
        dst = np.ascontiguousarray(dst, np.int32)
        n = src.size
        if self._tbuf.size < 2 * n:
            self._tbuf = np.zeros(2 * n, np.int32)
            self._rbuf = np.zeros(2 * n, np.int32)
        if self._cbuf.size < max(n, 1):
            self._cbuf = np.zeros(n, np.int32)
            self._crbuf = np.zeros(n, np.int32)
        nc = ctypes.c_int64(0)
        nt = self._lib.cuf_fold_window(
            self._h, src, dst, n, int(vcap),
            self._tbuf, self._rbuf, self._cbuf, self._crbuf,
            ctypes.byref(nc),
        )
        if nt < 0:
            raise ValueError("edge ids out of range for vcap")
        nc = nc.value
        return (
            self._tbuf[:nt].copy(), self._rbuf[:nt].copy(),
            self._cbuf[:nc].copy(), self._crbuf[:nc].copy(),
        )

    def fold_group(self, cols, vcap: int):
        """Union K windows in ONE native call (``cuf_fold_group``) — the
        host-carry superbatch path. ``cols`` is a list of per-window
        column tuples ``(src, dst, ...)``; per-window python/ctypes
        overhead measured ~0.3 ms via :meth:`fold`, which dominates
        sub-8k windows.

        Returns ``(windows, group_ids, group_roots, gt_counts)``:
        ``windows`` holds per-window ``(touched, roots, changed,
        changed_roots)`` views into freshly-allocated group buffers
        (safe to keep — nothing is reused across calls);
        ``group_ids``/``group_roots`` is the C-deduped union of every id
        the group re-rooted with its POST-GROUP root — the single masked
        scatter a device mirror needs per group — ordered group-unique
        touched ids FIRST (window first-seen order, per-window counts in
        ``gt_counts``, so a first-seen emission log can batch on the
        prefix) with the demoted-roots remainder after."""
        k = len(cols)
        offsets = np.zeros(k + 1, np.int64)
        for i, c in enumerate(cols):
            offsets[i + 1] = offsets[i] + len(c[0])
        n = int(offsets[-1])
        src = np.empty(n, np.int32)
        dst = np.empty(n, np.int32)
        for i, c in enumerate(cols):
            src[offsets[i]:offsets[i + 1]] = c[0]
            dst[offsets[i]:offsets[i + 1]] = c[1]
        tbuf = np.empty(2 * n, np.int32)
        rbuf = np.empty(2 * n, np.int32)
        cbuf = np.empty(max(n, 1), np.int32)
        crbuf = np.empty(max(n, 1), np.int32)
        gid = np.empty(max(3 * n, 1), np.int32)
        grt = np.empty(max(3 * n, 1), np.int32)
        tcnt = np.zeros(k, np.int64)
        ccnt = np.zeros(k, np.int64)
        gtcnt = np.zeros(k, np.int64)
        ngrp = ctypes.c_int64(0)
        tt = self._lib.cuf_fold_group(
            self._h, src, dst, offsets, k, int(vcap),
            tbuf, rbuf, cbuf, crbuf, tcnt, ccnt, gid, grt, gtcnt,
            ctypes.byref(ngrp),
        )
        if tt < 0:
            raise ValueError("edge ids out of range for vcap")
        wins = []
        t0 = c0 = 0
        for w in range(k):
            t1 = t0 + int(tcnt[w])
            c1 = c0 + int(ccnt[w])
            wins.append((tbuf[t0:t1], rbuf[t0:t1], cbuf[c0:c1], crbuf[c0:c1]))
            t0, c0 = t1, c1
        ng = ngrp.value
        return wins, gid[:ng], grt[:ng], gtcnt

    def flatten(self, vcap: int) -> np.ndarray:
        out = np.zeros(vcap, np.int32)
        self._lib.cuf_flatten(self._h, out, vcap)
        return out

    def load(self, labels: np.ndarray) -> None:
        labels = np.ascontiguousarray(labels, np.int32)
        if self._lib.cuf_load(self._h, labels, labels.size) != 0:
            raise ValueError("labels are not a min-rooted forest")

    def __del__(self):
        lib = getattr(self, "_lib", None)
        h = getattr(self, "_h", None)
        if lib is not None and h:
            lib.cuf_destroy(h)


class NativeWindowPrep:
    """Single-pass touched-set + local-renumbering for the forest CC
    carry (``ingest.cpp: wprep_*``): epoch-stamped, no clearing, cost
    scales with the window alone. ``run(src, dst, vcap)`` returns
    ``(tids, lu, lv)`` with touched ids in ARRIVAL order. Raises
    ``RuntimeError`` at construction when the toolchain is unavailable
    (callers keep the numpy bitmap+LUT path)."""

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError("native toolchain unavailable")
        self._lib = lib
        self._h = lib.wprep_create()
        if not self._h:
            raise RuntimeError("wprep_create failed")
        self._tbuf = np.zeros(1024, np.int32)
        self._lu = np.zeros(512, np.int32)
        self._lv = np.zeros(512, np.int32)

    def run(self, src: np.ndarray, dst: np.ndarray, vcap: int):
        src = np.ascontiguousarray(src, np.int32)
        dst = np.ascontiguousarray(dst, np.int32)
        n = src.size
        if self._tbuf.size < 2 * n:
            self._tbuf = np.zeros(max(2 * n, 1024), np.int32)
        if self._lu.size < max(n, 1):
            self._lu = np.zeros(n, np.int32)
            self._lv = np.zeros(n, np.int32)
        t = self._lib.wprep_run(
            self._h, src, dst, n, int(vcap),
            self._tbuf, self._lu, self._lv,
        )
        if t < 0:
            raise ValueError("edge ids out of range for vcap")
        return self._tbuf[:t].copy(), self._lu[:n].copy(), self._lv[:n].copy()

    def __del__(self):
        lib = getattr(self, "_lib", None)
        h = getattr(self, "_h", None)
        if lib is not None and h:
            lib.wprep_destroy(h)


class NativeEncoder:
    """C++ first-seen id compactor (the ``VertexDict.encode`` hot path).

    ``encode(raw)`` returns ``(idx[i32], novel_raw[i64])`` — compact ids
    for every input and the never-seen-before raw ids in first-appearance
    order. Falls back is handled by the caller (``VertexDict`` keeps its
    numpy path when the toolchain is absent).
    """

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError("native toolchain unavailable")
        self._lib = lib
        self._h = lib.encoder_create()
        # ctypes calls release the GIL; without this lock a prefetch-thread
        # encode's rehash could free buffers mid-lookup (use-after-free)
        self._mu = threading.Lock()

    def encode(self, raw: np.ndarray):
        raw = np.ascontiguousarray(raw, np.int64)
        idx = np.empty(raw.size, np.int32)
        novel = np.empty(raw.size, np.int64)
        with self._mu:
            n_novel = self._lib.encoder_encode(
                self._h, raw, raw.size, idx, novel
            )
        return idx, novel[:n_novel]

    def encode_pair(self, a: np.ndarray, b: np.ndarray):
        """Encode edge columns as the interleaved a0,b0,a1,b1,... sequence
        (first-seen order by edge arrival) without the interleaved copy."""
        a = np.ascontiguousarray(a, np.int64)
        b = np.ascontiguousarray(b, np.int64)
        ia = np.empty(a.size, np.int32)
        ib = np.empty(b.size, np.int32)
        novel = np.empty(a.size + b.size, np.int64)
        with self._mu:
            n_novel = self._lib.encoder_encode2(
                self._h, a, b, a.size, ia, ib, novel
            )
        return ia, ib, novel[:n_novel]

    def parse_encode_chunks(self, path: str, chunk_edges: int = 1 << 20):
        """Fused file ingest: yield (src_idx, dst_idx, val|None, novel_raw)
        chunks with endpoints already compact-encoded — the file bytes are
        parsed and hashed in one C pass, no int64 columns round trip."""
        budget = min(max(chunk_edges * 20, 4096), 1 << 28)
        cap = budget // 4 + 64
        lib = self._lib
        handle = lib.reader_open(path.encode(), budget)
        if not handle:
            raise IOError(f"cannot read {path}")
        try:
            src = np.empty(cap, np.int32)
            dst = np.empty(cap, np.int32)
            val = np.empty(cap, np.float64)
            novel = np.empty(2 * cap, np.int64)
            n_novel = ctypes.c_int64(0)
            has_val = ctypes.c_int32(0)
            at_eof = ctypes.c_int32(0)
            while True:
                prev = lib.reader_offset(handle)
                with self._mu:
                    got = lib.reader_next_encoded(
                        handle, self._h, src, dst, val, cap, novel,
                        ctypes.byref(n_novel), ctypes.byref(has_val),
                        ctypes.byref(at_eof),
                    )
                if got < 0:
                    raise IOError(f"cannot read {path}")
                if got:
                    yield (
                        src[:got].copy(),
                        dst[:got].copy(),
                        val[:got].copy() if has_val.value else None,
                        novel[: n_novel.value].copy(),
                    )
                if at_eof.value:
                    return
                if got == 0 and lib.reader_offset(handle) == prev:
                    raise IOError(
                        f"{path}: line at byte {prev} exceeds the span "
                        "read budget"
                    )
        finally:
            lib.reader_close(handle)

    def lookup(self, k: int):
        with self._mu:
            v = self._lib.encoder_lookup(self._h, int(k))
        return None if v < 0 else int(v)

    def lookup_batch(self, ks: np.ndarray) -> np.ndarray:
        """Batched query-without-insert: int32 compact ids, -1 for
        unseen. ONE C call (and one mutex acquisition) for the whole
        batch — the serving read path must not pay a ctypes round trip
        per id."""
        ks = np.ascontiguousarray(ks, np.int64)
        out = np.empty(ks.size, np.int32)
        with self._mu:
            self._lib.encoder_lookup_batch(self._h, ks, ks.size, out)
        return out

    def __len__(self) -> int:
        return int(self._lib.encoder_size(self._h))

    def __del__(self):
        lib = getattr(self, "_lib", None)
        h = getattr(self, "_h", None)
        if lib is not None and h:
            lib.encoder_destroy(h)
