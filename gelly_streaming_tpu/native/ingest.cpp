// Fast edge-file ingest for the host layer.
//
// The reference delegates file ingest to Flink's JVM text sources
// (env.readTextFile + per-line split mappers, e.g.
// ConnectedComponentsExample.java:106-118). Here the host layer owns
// ingestion (SURVEY.md §7), and for file-backed streams the Python-side
// line parsing is the bottleneck long before the device is busy — this
// translation unit parses whitespace-separated edge lists straight into
// caller-provided numpy buffers at C speed.
//
// Exposed via ctypes (extern "C"), no pybind11 dependency:
//   count_edges(path)                         -> number of data lines
//   parse_edge_file(path, src, dst, val, cap, has_val) -> n parsed
//   parse_edge_chunk(path, offset, src, dst, val, cap, ...)
//     -> n parsed, *next_offset updated (chunked/streaming reads)
//
// Format per line: "src dst [third]" where third may be a value,
// timestamp, or +/- event flag (returned as +1/-1). '#'/'%' lines and
// blanks are skipped. Separators: spaces, tabs, commas.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace {

inline const char* skip_sep(const char* p, const char* end) {
    while (p < end && (*p == ' ' || *p == '\t' || *p == ',' || *p == '\r')) ++p;
    return p;
}

inline const char* skip_line(const char* p, const char* end) {
    while (p < end && *p != '\n') ++p;
    return p < end ? p + 1 : end;
}

// Parse one line into (s, d, v, has_third). Returns false for
// blank/comment/malformed lines.
inline bool parse_line(const char*& p, const char* end, int64_t* s, int64_t* d,
                       double* v, bool* has_third) {
    p = skip_sep(p, end);
    if (p >= end) return false;
    if (*p == '#' || *p == '%' || *p == '\n') {
        p = skip_line(p, end);
        return false;
    }
    char* q;
    long long a = strtoll(p, &q, 10);
    if (q == p) { p = skip_line(p, end); return false; }
    p = skip_sep(q, end);
    long long b = strtoll(p, &q, 10);
    if (q == p) { p = skip_line(p, end); return false; }
    p = skip_sep(q, end);
    *has_third = false;
    *v = 0.0;
    if (p < end && *p != '\n') {
        if (*p == '+') { *v = 1.0; *has_third = true; p = skip_line(p, end); }
        else if (*p == '-' && (p + 1 >= end || *(p + 1) == '\n' || *(p + 1) == ' ' || *(p + 1) == '\r')) {
            *v = -1.0; *has_third = true; p = skip_line(p, end);
        } else {
            double x = strtod(p, &q);
            if (q != p) { *v = x; *has_third = true; }
            p = skip_line(q, end);
        }
    } else {
        p = skip_line(p, end);
    }
    *s = (int64_t)a;
    *d = (int64_t)b;
    return true;
}

// Read [offset, offset+len) of the file into a malloc'd buffer.
// *at_eof is set when the span reaches the end of the file.
char* read_span(const char* path, int64_t offset, int64_t* len, bool* at_eof) {
    FILE* f = fopen(path, "rb");
    if (!f) { *len = -1; return nullptr; }  // signal IO error to callers
    if (fseek(f, 0, SEEK_END) != 0) { fclose(f); *len = -1; return nullptr; }
    int64_t size = ftell(f);
    if (offset >= size) { fclose(f); *len = 0; *at_eof = true; return nullptr; }
    int64_t want = (*len <= 0 || offset + *len > size) ? size - offset : *len;
    *at_eof = (offset + want) >= size;
    char* buf = (char*)malloc(want);
    if (!buf) { fclose(f); return nullptr; }
    fseek(f, offset, SEEK_SET);
    int64_t got = (int64_t)fread(buf, 1, want, f);
    fclose(f);
    *len = got;
    return buf;
}

}  // namespace

extern "C" {

// Number of parseable edge lines in the file (-1 on IO error).
int64_t count_edges(const char* path) {
    int64_t len = 0;
    bool eof = false;
    char* buf = read_span(path, 0, &len, &eof);
    if (!buf) return len == 0 ? 0 : -1;
    const char* p = buf;
    const char* end = buf + len;
    int64_t n = 0;
    int64_t s, d; double v; bool h;
    while (p < end) {
        if (parse_line(p, end, &s, &d, &v, &h)) ++n;
    }
    free(buf);
    return n;
}

// Parse up to cap edges from the whole file into the caller's buffers.
// Returns edges parsed; *has_val set to 1 if any line had a third column.
int64_t parse_edge_file(const char* path, int64_t* src, int64_t* dst,
                        double* val, int64_t cap, int32_t* has_val) {
    int64_t len = 0;
    bool eof = false;
    char* buf = read_span(path, 0, &len, &eof);
    if (!buf) return len == 0 ? 0 : -1;
    const char* p = buf;
    const char* end = buf + len;
    int64_t n = 0;
    int64_t s, d; double v; bool h;
    *has_val = 0;
    while (p < end && n < cap) {
        if (parse_line(p, end, &s, &d, &v, &h)) {
            src[n] = s; dst[n] = d; val[n] = v;
            if (h) *has_val = 1;
            ++n;
        }
    }
    free(buf);
    return n;
}

// Chunked parse: read from byte *offset, stop after cap edges or EOF;
// *offset is advanced to the first unconsumed byte (always at a line
// boundary). Returns edges parsed (-1 on IO error). *at_eof_out is set to
// 1 only when this call consumed through the last byte of the file — a
// return of 0 with *at_eof_out == 0 means "no edges in this span, keep
// going" (comment/blank run) or, if *offset did not advance, a line larger
// than the read buffer (caller's error to surface).
int64_t parse_edge_chunk(const char* path, int64_t* offset, int64_t* src,
                         int64_t* dst, double* val, int64_t cap,
                         int32_t* has_val, int32_t* at_eof_out) {
    // Over-read enough bytes for cap edges (64 bytes/line upper bound),
    // then re-scan; the last (possibly partial) line is not consumed.
    int64_t len = cap * 64 + 4096;
    bool at_eof = false;
    *at_eof_out = 0;
    char* buf = read_span(path, *offset, &len, &at_eof);
    if (!buf) {
        if (len == 0) { *at_eof_out = 1; return 0; }
        return -1;
    }
    const char* p = buf;
    const char* end = buf + len;
    int64_t n = 0;
    int64_t s, d; double v; bool h;
    *has_val = 0;
    const char* consumed = p;
    while (p < end && n < cap) {
        const char* line_start = p;
        // a line touching the buffer end may be truncated — only take it
        // if terminated inside the buffer (or the file itself ends here)
        const char* probe = line_start;
        while (probe < end && *probe != '\n') ++probe;
        if (probe >= end && !at_eof) break;  // partial tail: next chunk
        if (parse_line(p, end, &s, &d, &v, &h)) {
            src[n] = s; dst[n] = d; val[n] = v;
            if (h) *has_val = 1;
            ++n;
        }
        consumed = p;
    }
    *offset += consumed - buf;
    if (at_eof && consumed == end) *at_eof_out = 1;
    free(buf);
    return n;
}

}  // extern "C"

// --------------------------------------------------------------------- //
// First-seen vertex compaction (the VertexDict.encode hot path).
//
// Open-addressing int64 -> int32 hash map with linear probing; the
// Python VertexDict keeps the reverse (idx -> raw) table and hands the
// encoder only the forward mapping. ~10x the numpy sorted-merge path.
// --------------------------------------------------------------------- //

namespace {

struct Encoder {
    int64_t* keys;    // EMPTY_KEY = sentinel
    int32_t* vals;
    int64_t cap;      // power of two
    int64_t size;
    int32_t min_idx;  // slot for the raw id == EMPTY_KEY itself (-1 = unseen)
};

constexpr int64_t EMPTY_KEY = INT64_MIN;

inline uint64_t mix_hash(uint64_t x) {
    x ^= x >> 33; x *= 0xff51afd7ed558ccdULL;
    x ^= x >> 33; x *= 0xc4ceb9fe1a85ec53ULL;
    x ^= x >> 33; return x;
}

void encoder_rehash(Encoder* e, int64_t new_cap) {
    int64_t* nk = (int64_t*)malloc(new_cap * sizeof(int64_t));
    int32_t* nv = (int32_t*)malloc(new_cap * sizeof(int32_t));
    for (int64_t i = 0; i < new_cap; ++i) nk[i] = EMPTY_KEY;
    for (int64_t i = 0; i < e->cap; ++i) {
        if (e->keys[i] == EMPTY_KEY) continue;
        uint64_t h = mix_hash((uint64_t)e->keys[i]) & (new_cap - 1);
        while (nk[h] != EMPTY_KEY) h = (h + 1) & (new_cap - 1);
        nk[h] = e->keys[i];
        nv[h] = e->vals[i];
    }
    free(e->keys); free(e->vals);
    e->keys = nk; e->vals = nv; e->cap = new_cap;
}

}  // namespace

extern "C" {

void* encoder_create() {
    Encoder* e = (Encoder*)malloc(sizeof(Encoder));
    e->cap = 1024; e->size = 0; e->min_idx = -1;
    e->keys = (int64_t*)malloc(e->cap * sizeof(int64_t));
    e->vals = (int32_t*)malloc(e->cap * sizeof(int32_t));
    for (int64_t i = 0; i < e->cap; ++i) e->keys[i] = EMPTY_KEY;
    return e;
}

void encoder_destroy(void* ptr) {
    Encoder* e = (Encoder*)ptr;
    free(e->keys); free(e->vals); free(e);
}

// Encode n raw ids to compact indices (first-seen-first). Novel raw ids,
// in first-appearance order, are appended to novel_out (caller-sized >= n).
// Returns the number of novel ids.
int64_t encoder_encode(void* ptr, const int64_t* raw, int64_t n,
                       int32_t* idx_out, int64_t* novel_out) {
    Encoder* e = (Encoder*)ptr;
    int64_t n_novel = 0;
    for (int64_t i = 0; i < n; ++i) {
        if ((e->size + 1) * 10 >= e->cap * 7) encoder_rehash(e, e->cap * 2);
        int64_t k = raw[i];
        if (k == EMPTY_KEY) {  // the sentinel value is a legal raw id
            if (e->min_idx < 0) {
                e->min_idx = (int32_t)e->size;
                novel_out[n_novel++] = k;
                e->size++;
            }
            idx_out[i] = e->min_idx;
            continue;
        }
        uint64_t h = mix_hash((uint64_t)k) & (e->cap - 1);
        while (true) {
            if (e->keys[h] == k) { idx_out[i] = e->vals[h]; break; }
            if (e->keys[h] == EMPTY_KEY) {
                e->keys[h] = k;
                e->vals[h] = (int32_t)e->size;
                idx_out[i] = (int32_t)e->size;
                novel_out[n_novel++] = k;
                e->size++;
                break;
            }
            h = (h + 1) & (e->cap - 1);
        }
    }
    return n_novel;
}

// Lookup without insert; returns -1 when unseen.
int32_t encoder_lookup(void* ptr, int64_t k) {
    Encoder* e = (Encoder*)ptr;
    if (k == EMPTY_KEY) return e->min_idx;
    uint64_t h = mix_hash((uint64_t)k) & (e->cap - 1);
    while (true) {
        if (e->keys[h] == k) return e->vals[h];
        if (e->keys[h] == EMPTY_KEY) return -1;
        h = (h + 1) & (e->cap - 1);
    }
}

int64_t encoder_size(void* ptr) { return ((Encoder*)ptr)->size; }

}  // extern "C"
