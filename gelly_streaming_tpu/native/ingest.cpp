// Fast edge-file ingest for the host layer.
//
// The reference delegates file ingest to Flink's JVM text sources
// (env.readTextFile + per-line split mappers, e.g.
// ConnectedComponentsExample.java:106-118). Here the host layer owns
// ingestion (SURVEY.md §7), and for file-backed streams the Python-side
// line parsing is the bottleneck long before the device is busy — this
// translation unit parses whitespace-separated edge lists straight into
// caller-provided numpy buffers at C speed.
//
// Exposed via ctypes (extern "C"), no pybind11 dependency:
//   reader_open/next_span/next_encoded/close  -> chunked streaming reads
//   encoder_*                                 -> first-seen id compaction
//   write_edge_file                           -> fast corpus writer
//   cc_baseline_run                           -> compiled CC baseline
//   decode_edge_frame                         -> GSEW binary wire decode
//   parse_edge_lines                          -> socket text chunk parse
//
// Format per line: "src dst [third]" where third may be a value,
// timestamp, or +/- event flag (returned as +1/-1). '#'/'%' lines and
// blanks are skipped. Separators: spaces, tabs, commas.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include <atomic>
#include <ctime>
#include <string>
#include <thread>
#include <vector>

#include <sys/mman.h>

#if defined(__AVX512BW__)
#include <immintrin.h>
#endif

// Read buffers are over-allocated and zero-padded by PAD bytes so the
// SWAR parsers can load 8 bytes and the AVX-512 newline scanner 64 bytes
// at any position < len without reading out of bounds.
#define READ_PAD 64

namespace {

inline const char* skip_sep(const char* p, const char* end) {
    while (p < end && (*p == ' ' || *p == '\t' || *p == ',' || *p == '\r')) ++p;
    return p;
}

inline const char* skip_line(const char* p, const char* end) {
    const char* nl =
        (const char*)memchr(p, '\n', (size_t)(end - p));
    return nl ? nl + 1 : end;
}

// Parse one line into (s, d, v, has_third). Returns false for
// blank/comment/malformed lines.
inline bool parse_line(const char*& p, const char* end, int64_t* s, int64_t* d,
                       double* v, bool* has_third) {
    p = skip_sep(p, end);
    if (p >= end) return false;
    if (*p == '#' || *p == '%' || *p == '\n') {
        p = skip_line(p, end);
        return false;
    }
    char* q;
    long long a = strtoll(p, &q, 10);
    if (q == p) { p = skip_line(p, end); return false; }
    p = skip_sep(q, end);
    long long b = strtoll(p, &q, 10);
    if (q == p) { p = skip_line(p, end); return false; }
    p = skip_sep(q, end);
    *has_third = false;
    *v = 0.0;
    if (p < end && *p != '\n') {
        if (*p == '+') { *v = 1.0; *has_third = true; p = skip_line(p, end); }
        else if (*p == '-' && (p + 1 >= end || *(p + 1) == '\n' || *(p + 1) == ' ' || *(p + 1) == '\r')) {
            *v = -1.0; *has_third = true; p = skip_line(p, end);
        } else {
            double x = strtod(p, &q);
            if (q != p) { *v = x; *has_third = true; }
            p = skip_line(q, end);
        }
    } else {
        p = skip_line(p, end);
    }
    *s = (int64_t)a;
    *d = (int64_t)b;
    return true;
}

// Read [offset, offset+len) of the file into a malloc'd buffer.
// *at_eof is set when the span reaches the end of the file.
// The buffer is over-allocated by READ_PAD zero bytes (see above).
char* read_span(const char* path, int64_t offset, int64_t* len, bool* at_eof) {
    FILE* f = fopen(path, "rb");
    if (!f) { *len = -1; return nullptr; }  // signal IO error to callers
    if (fseek(f, 0, SEEK_END) != 0) { fclose(f); *len = -1; return nullptr; }
    int64_t size = ftell(f);
    if (offset >= size) { fclose(f); *len = 0; *at_eof = true; return nullptr; }
    int64_t want = (*len <= 0 || offset + *len > size) ? size - offset : *len;
    *at_eof = (offset + want) >= size;
    char* buf = (char*)malloc(want + READ_PAD);
    if (!buf) { fclose(f); return nullptr; }
    memset(buf + want, 0, READ_PAD);
    fseek(f, offset, SEEK_SET);
    int64_t got = (int64_t)fread(buf, 1, want, f);
    fclose(f);
    *len = got;
    return buf;
}

// ----- SWAR digit parsing (safe: read_span pads 8 bytes past len) ----- //

inline uint32_t parse_eight(uint64_t w) {
    w = (w & 0x0F0F0F0F0F0F0F0FULL) * 2561 >> 8;
    w = (w & 0x00FF00FF00FF00FFULL) * 6553601 >> 16;
    return (uint32_t)((w & 0x0000FFFF0000FFFFULL) * 42949672960001ULL >> 32);
}

// Parse an unsigned decimal run at p (8 bytes at a time); advances p past
// the digits. Returns false when *p is not a digit. Runs whose value
// exceeds INT64_MAX saturate to INT64_MAX (digit count tracked, plus an
// exact check for 19-digit runs) so downstream id-bound/oob checks fire —
// a silent uint64 wrap would let corrupted edges into validated ingest
// paths, and the Python fallback must agree byte-for-byte.
inline bool parse_uint_swar(const char*& p, uint64_t* out) {
    uint64_t w;
    memcpy(&w, p, 8);
    uint64_t nd_mask = ((w - 0x3030303030303030ULL) |
                        (w + 0x4646464646464646ULL)) &
                       0x8080808080808080ULL;
    if (nd_mask == 0) {  // >= 8 digits: full block, then continue
        uint64_t v = parse_eight(w);
        int64_t digits = 8;
        p += 8;
        while (true) {
            memcpy(&w, p, 8);
            nd_mask = ((w - 0x3030303030303030ULL) |
                       (w + 0x4646464646464646ULL)) &
                      0x8080808080808080ULL;
            if (nd_mask == 0) {
                v = v * 100000000ULL + parse_eight(w);
                digits += 8;
                p += 8;
                continue;
            }
            int nd = __builtin_ctzll(nd_mask) >> 3;
            if (nd) {
                // left-align the nd digits behind '0' padding
                uint64_t w2 = (w << ((8 - nd) * 8)) |
                              (0x3030303030303030ULL >> (nd * 8));
                static const uint64_t pow10[8] = {1, 10, 100, 1000, 10000,
                                                  100000, 1000000, 10000000};
                v = v * pow10[nd] + parse_eight(w2);
                digits += nd;
                p += nd;
            }
            // 20+ digits always exceed INT64_MAX; 19 digits fit uint64
            // exactly, so the comparison below is wrap-free
            if (digits > 19 || (digits == 19 && v > (uint64_t)INT64_MAX))
                v = (uint64_t)INT64_MAX;
            *out = v;
            return true;
        }
    }
    int nd = __builtin_ctzll(nd_mask) >> 3;
    if (nd == 0) return false;
    uint64_t w2 = (w << ((8 - nd) * 8)) | (0x3030303030303030ULL >> (nd * 8));
    *out = parse_eight(w2);
    p += nd;
    return true;
}

}  // namespace

// --------------------------------------------------------------------- //
// Fast span parser: hand-rolled digit scanning + thread-parallel spans.
//
// strtoll tops out around 35 MB/s on edge lists; the inline parser below
// runs ~10x that per core and sub-spans parse independently (each thread
// starts at the first line boundary past its slice start), so a single
// read_span turns into all-core parsing. This is the host half of the
// "host feeds the device" contract (SURVEY.md §7 hard part #6); the
// reference's equivalent stage is Flink's parallel text source +
// per-line split mappers (ConnectedComponentsExample.java:106-118).
// --------------------------------------------------------------------- //

namespace {

// Parse one line fast. Same accepted grammar as parse_line above:
// "src dst [third]" with space/tab/comma separators, '#'/'%' comments,
// third column as number or +/- event flag. Returns false for non-edge
// lines; p always advances past the line.
inline bool parse_line_fast(const char*& p, const char* end, int64_t* s,
                            int64_t* d, double* v, bool* has_third) {
    p = skip_sep(p, end);
    if (p >= end) return false;
    char c = *p;
    if (c == '#' || c == '%' || c == '\n') { p = skip_line(p, end); return false; }
    // first integer (SWAR digit runs; sign prefixes handled here)
    bool neg = false;
    if (c == '-' || c == '+') { neg = (c == '-'); ++p; }
    uint64_t a;
    if (p >= end || !parse_uint_swar(p, &a)) {
        p = skip_line(p, end);
        return false;
    }
    int64_t sa = neg ? -(int64_t)a : (int64_t)a;
    p = skip_sep(p, end);
    // second integer
    if (p >= end) return false;
    c = *p; neg = false;
    if (c == '-' || c == '+') { neg = (c == '-'); ++p; }
    uint64_t b;
    if (p >= end || !parse_uint_swar(p, &b)) {
        p = skip_line(p, end);
        return false;
    }
    int64_t sb = neg ? -(int64_t)b : (int64_t)b;
    p = skip_sep(p, end);
    *has_third = false;
    *v = 0.0;
    if (p < end && *p != '\n') {
        c = *p;
        if (c == '+' && (p + 1 >= end || *(p + 1) == '\n' || *(p + 1) == ' ' ||
                         *(p + 1) == '\r' || *(p + 1) == '\t')) {
            *v = 1.0; *has_third = true; p = skip_line(p, end);
        } else if (c == '-' && (p + 1 >= end || *(p + 1) == '\n' ||
                                *(p + 1) == ' ' || *(p + 1) == '\r' ||
                                *(p + 1) == '\t')) {
            *v = -1.0; *has_third = true; p = skip_line(p, end);
        } else {
            // integer fast path; anything else falls back to strtod
            bool vneg = false; const char* q0 = p;
            if (c == '-' || c == '+') { vneg = (c == '-'); ++p; }
            uint64_t iv = 0; const char* digs = p;
            while (p < end && *p >= '0' && *p <= '9') iv = iv * 10 + (*p++ - '0');
            if (p > digs && (p >= end || *p == '\n' || *p == ' ' ||
                             *p == '\t' || *p == ',' || *p == '\r')) {
                *v = vneg ? -(double)iv : (double)iv;
                *has_third = true;
                p = skip_line(p, end);
            } else {
                char* qe;
                double x = strtod(q0, &qe);
                if (qe != q0) { *v = x; *has_third = true; }
                p = skip_line(qe > q0 ? qe : q0, end);
            }
        }
    } else {
        p = skip_line(p, end);
    }
    *s = sa;
    *d = sb;
    return true;
}

// Fast path for the dominant unweighted line shape "digits SEP digits\n"
// (measured ~1.8x the general parser): advances p and returns true on an
// exact match; leaves p untouched otherwise so the caller falls back to
// the general parser — accepted grammar is unchanged. Caller guarantees
// p < end (the 8-byte pad covers SWAR loads).
inline bool parse_two_col_fast(const char*& p, int64_t* a_out,
                               int64_t* b_out) {
    if ((uint8_t)(*p - '0') > 9) return false;
    const char* save = p;
    uint64_t a, b;
    if (parse_uint_swar(p, &a)) {
        char sep = *p;
        if ((sep == ' ' || sep == '\t' || sep == ',') &&
            (uint8_t)(p[1] - '0') <= 9) {
            ++p;
            if (parse_uint_swar(p, &b) && *p == '\n') {
                ++p;
                *a_out = (int64_t)a;
                *b_out = (int64_t)b;
                return true;
            }
        }
    }
    p = save;
    return false;
}

// Parse one already-delimited line [s, nl) of the dominant unweighted
// shape "digits SEP digits [\r]" with both ids <= 8 digits (so they fit
// int32 by construction: max 99,999,999 < 2^31). Returns false — without
// consuming anything — for any other shape; the caller falls back to the
// general grammar parser for that line. Two 8-byte SWAR loads, no scan
// loop: the line boundaries come from the caller's newline mask.
inline bool parse_line_i32_quick(const char* s, const char* nl, int32_t* a_out,
                                 int32_t* b_out) {
    uint64_t w;
    memcpy(&w, s, 8);
    uint64_t ndm = ((w - 0x3030303030303030ULL) |
                    (w + 0x4646464646464646ULL)) &
                   0x8080808080808080ULL;
    int nd1 = ndm ? (__builtin_ctzll(ndm) >> 3) : 8;
    if (nd1 == 0) return false;
    uint64_t v1 = parse_eight(
        nd1 == 8 ? w
                 : ((w << ((8 - nd1) * 8)) |
                    (0x3030303030303030ULL >> (nd1 * 8))));
    const char* q = s + nd1;
    if (q >= nl) return false;
    char sep = *q;
    if (sep != '\t' && sep != ' ' && sep != ',') return false;  // 9+ digits land here
    ++q;
    memcpy(&w, q, 8);
    ndm = ((w - 0x3030303030303030ULL) |
           (w + 0x4646464646464646ULL)) &
          0x8080808080808080ULL;
    int nd2 = ndm ? (__builtin_ctzll(ndm) >> 3) : 8;
    if (nd2 == 0) return false;
    const char* e2 = q + nd2;
    if (e2 != nl && !(e2 + 1 == nl && *e2 == '\r')) return false;
    uint64_t v2 = parse_eight(
        nd2 == 8 ? w
                 : ((w << ((8 - nd2) * 8)) |
                    (0x3030303030303030ULL >> (nd2 * 8))));
    *a_out = (int32_t)v1;
    *b_out = (int32_t)v2;
    return true;
}

#if defined(__AVX512BW__)
// Newline-driven int32 region parse: one AVX-512 compare finds the
// newlines of 64 input bytes (~4-5 lines) at once, and each line is then
// parsed branch-lean by parse_line_i32_quick — the per-line separator
// scanning, comment tests, and third-column probing of the scalar loop
// vanish from the hot path. Lines that are not simple two-column edges
// fall back to parse_line_fast one line at a time (accepted grammar is
// identical). ~3x the scalar loop on SNAP-shaped corpora (measured round
// 3: 26.6M -> ~80M edges/s single core).
//
// [buf, end) must end at a line boundary or EOF (reader_fill contract)
// and carry READ_PAD zero bytes past `end`. Returns edges written;
// *consumed gets the byte count consumed (always the full span unless
// `cap` fills).
int64_t parse_region_i32_simd(const char* buf, const char* end, int32_t* src,
                              int32_t* dst, double* val, int64_t cap,
                              int64_t bound, int64_t* oob_out, bool* any_val,
                              int64_t* consumed) {
    int64_t n = 0, oob = 0;
    bool av = false;
    const char* line = buf;  // start of the current (unconsumed) line
    const char* p = buf;     // 64-byte scan cursor
    const __m512i NL = _mm512_set1_epi8('\n');
    while (p < end && n < cap) {
        __m512i v = _mm512_loadu_si512((const void*)p);
        uint64_t m = _mm512_cmpeq_epi8_mask(v, NL);
        if (end - p < 64) m &= (((uint64_t)1) << (end - p)) - 1;
        while (m) {
            if (n >= cap) goto done;
            const char* nl = p + __builtin_ctzll(m);
            m &= m - 1;
            if (nl == line) { ++line; continue; }  // blank line
            int32_t a, b;
            if (parse_line_i32_quick(line, nl, &a, &b)) {
                oob += (a >= bound) | (b >= bound);
                src[n] = a;
                dst[n] = b;
                val[n] = 0.0;
                ++n;
            } else {
                const char* q = line;
                int64_t s, d;
                double w;
                bool h;
                if (parse_line_fast(q, nl + 1, &s, &d, &w, &h)) {
                    oob += (s < 0) | (s >= bound) | (d < 0) | (d >= bound);
                    src[n] = (int32_t)s;
                    dst[n] = (int32_t)d;
                    val[n] = w;
                    av |= h;
                    ++n;
                }
            }
            line = nl + 1;
        }
        p += 64;
    }
    // ragged tail (EOF without a trailing newline)
    while (line < end && n < cap) {
        const char* q = line;
        int64_t s, d;
        double w;
        bool h;
        if (parse_line_fast(q, end, &s, &d, &w, &h)) {
            oob += (s < 0) | (s >= bound) | (d < 0) | (d >= bound);
            src[n] = (int32_t)s;
            dst[n] = (int32_t)d;
            val[n] = w;
            av |= h;
            ++n;
        }
        line = q;
    }
done:
    *oob_out = oob;
    *any_val = av;
    *consumed = line - buf;
    return n;
}
#endif  // __AVX512BW__

// Parse every complete line of [p, end) into the output slices.
int64_t parse_region(const char* p, const char* end, int64_t* src,
                     int64_t* dst, double* val, int64_t cap, bool* any_val) {
    int64_t n = 0;
    int64_t s, d; double v; bool h;
    bool av = false;
    while (p < end && n < cap) {
        if (parse_line_fast(p, end, &s, &d, &v, &h)) {
            src[n] = s; dst[n] = d; val[n] = v;
            av |= h;
            ++n;
        }
    }
    *any_val = av;
    return n;
}

}  // namespace

extern "C" {

// --------------------------------------------------------------------- //
// First-seen bitmap over the non-negative int32 id space.
//
// The general (arbitrary-id) device-encode ingest needs to know, per
// chunk, how many ids the device dictionary has never seen — growing the
// device table proactively keeps the whole pipeline free of
// device->host reads (a single scalar fetch measures ~0.5-3 s through
// the remote-TPU tunnel; round 3). A 2^31-bit anonymous mmap commits
// lazily page by page, so clustered real-world id spaces stay a few
// hundred KB resident and the test-and-set rides the L2 cache.
// --------------------------------------------------------------------- //

#define VBITMAP_BYTES (((size_t)1 << 31) / 8)  // 256 MB virtual

void* vbitmap_create() {
    void* bits = mmap(nullptr, VBITMAP_BYTES, PROT_READ | PROT_WRITE,
                      MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
    return bits == MAP_FAILED ? nullptr : bits;
}

void vbitmap_destroy(void* ptr) {
    if (ptr) munmap(ptr, VBITMAP_BYTES);
}

// Count and record first-seen ids among (a[i], b[i]) in interleaved
// arrival order; ids outside [0, 2^31) are ignored (the caller's oob
// check rejects those edges anyway).
int64_t vbitmap_novel2(void* bitmap, const int32_t* a, const int32_t* b,
                       int64_t n) {
    uint8_t* bits = (uint8_t*)bitmap;
    int64_t novel = 0;
    for (int64_t i = 0; i < n; ++i) {
        uint32_t x = (uint32_t)a[i];
        if (a[i] >= 0) {
            uint8_t m = (uint8_t)(1u << (x & 7));
            uint8_t& cell = bits[x >> 3];
            novel += !(cell & m);
            cell |= m;
        }
        uint32_t y = (uint32_t)b[i];
        if (b[i] >= 0) {
            uint8_t m = (uint8_t)(1u << (y & 7));
            uint8_t& cell = bits[y >> 3];
            novel += !(cell & m);
            cell |= m;
        }
    }
    return novel;
}

// Persistent reader session: reuses one file handle and one read buffer
// across span calls. A fresh 40MB malloc per chunk costs ~8-10ns/edge in
// soft page faults alone (measured); the session touches its pages once.
struct SpanReader {
    FILE* f;
    char* buf;
    int64_t buf_cap;
    int64_t size;    // file size
    int64_t offset;  // next unread byte
};

void* reader_open(const char* path, int64_t budget) {
    FILE* f = fopen(path, "rb");
    if (!f) return nullptr;
    if (fseek(f, 0, SEEK_END) != 0) { fclose(f); return nullptr; }
    int64_t size = ftell(f);
    fseek(f, 0, SEEK_SET);
    char* buf = (char*)malloc(budget + READ_PAD);
    if (!buf) { fclose(f); return nullptr; }
    SpanReader* r = (SpanReader*)malloc(sizeof(SpanReader));
    r->f = f; r->buf = buf; r->buf_cap = budget; r->size = size;
    r->offset = 0;
    return r;
}

void reader_close(void* ptr) {
    SpanReader* r = (SpanReader*)ptr;
    if (!r) return;
    fclose(r->f);
    free(r->buf);
    free(r);
}

int64_t reader_offset(void* ptr) { return ((SpanReader*)ptr)->offset; }

namespace {

// Fill the session buffer with the next complete-line span.
// Returns span length (0 at EOF or when one line exceeds the buffer;
// distinguish via *at_eof), -1 on IO error. The span always ends at a
// line boundary unless it reaches EOF.
int64_t reader_fill(SpanReader* r, const char** span_end, bool* at_eof) {
    if (r->offset >= r->size) { *at_eof = true; return 0; }
    int64_t want = r->size - r->offset;
    if (want > r->buf_cap) want = r->buf_cap;
    *at_eof = (r->offset + want) >= r->size;
    if (fseek(r->f, r->offset, SEEK_SET) != 0) return -1;
    int64_t got = (int64_t)fread(r->buf, 1, want, r->f);
    if (got <= 0) return -1;
    memset(r->buf + got, 0, READ_PAD);
    const char* end = r->buf + got;
    if (!*at_eof) {
        while (end > r->buf && *(end - 1) != '\n') --end;
        if (end == r->buf) return 0;  // one line > buffer
    }
    *span_end = end;
    return end - r->buf;
}

}  // namespace

// Session-based span parse (same output contract as parse_edge_span).
int64_t reader_next_span(void* ptr, int64_t* src, int64_t* dst, double* val,
                         int64_t cap, int32_t* has_val, int32_t* at_eof_out,
                         int32_t n_threads) {
    SpanReader* r = (SpanReader*)ptr;
    bool at_eof = false;
    *at_eof_out = 0;
    *has_val = 0;
    const char* end = nullptr;
    int64_t span = reader_fill(r, &end, &at_eof);
    if (span < 0) return -1;
    if (span == 0) {
        if (at_eof) *at_eof_out = 1;
        return 0;
    }
    char* buf = r->buf;
    int64_t t = n_threads < 1 ? 1 : n_threads;
    if (t > span / (1 << 16)) t = span / (1 << 16) ? span / (1 << 16) : 1;
    std::vector<const char*> starts(t + 1);
    starts[0] = buf;
    starts[t] = end;
    for (int64_t i = 1; i < t; ++i) {
        const char* p = buf + (span * i) / t;
        while (p < end && *p != '\n') ++p;
        starts[i] = p < end ? p + 1 : end;
    }
    std::vector<int64_t> counts(t, 0);
    std::vector<int64_t> offs(t + 1);
    for (int64_t i = 0; i < t; ++i) offs[i] = (starts[i] - buf) >> 2;
    offs[t] = cap;
    std::vector<char> anyv(t, 0);
    std::vector<std::thread> workers;
    for (int64_t i = 0; i < t; ++i) {
        workers.emplace_back([&, i] {
            bool av = false;
            counts[i] = parse_region(starts[i], starts[i + 1],
                                     src + offs[i], dst + offs[i],
                                     val + offs[i], offs[i + 1] - offs[i],
                                     &av);
            anyv[i] = av;
        });
    }
    for (auto& w : workers) w.join();
    int64_t n = counts[0];
    for (int64_t i = 1; i < t; ++i) {
        if (counts[i] && n != offs[i]) {
            memmove(src + n, src + offs[i], counts[i] * sizeof(int64_t));
            memmove(dst + n, dst + offs[i], counts[i] * sizeof(int64_t));
            memmove(val + n, val + offs[i], counts[i] * sizeof(double));
        }
        n += counts[i];
    }
    for (int64_t i = 0; i < t; ++i)
        if (anyv[i]) *has_val = 1;
    r->offset += end - buf;
    if (at_eof && r->offset >= r->size) *at_eof_out = 1;
    return n;
}

// Session-based fused parse+encode (contract of parse_encode_span).
int64_t reader_next_encoded(void* ptr, void* enc_ptr, int32_t* src32,
                            int32_t* dst32, double* val, int64_t cap,
                            int64_t* novel_out, int64_t* n_novel_out,
                            int32_t* has_val, int32_t* at_eof_out);

// int32-direct span parse for dense-id corpora: writes int32 columns
// (half the memory traffic of the int64 path, no convert pass) and counts
// ids outside [0, id_bound) (bound 0 = only require int32 range) so the
// caller can reject bad corpora instead of truncating silently.
int64_t reader_next_span_i32(void* ptr, int32_t* src, int32_t* dst,
                             double* val, int64_t cap, int64_t id_bound,
                             int32_t* has_val, int32_t* at_eof_out,
                             int64_t* oob_out) {
    SpanReader* r = (SpanReader*)ptr;
    bool at_eof = false;
    *at_eof_out = 0;
    *has_val = 0;
    *oob_out = 0;
    const char* end = nullptr;
    int64_t span = reader_fill(r, &end, &at_eof);
    if (span < 0) return -1;
    if (span == 0) {
        if (at_eof) *at_eof_out = 1;
        return 0;
    }
    int64_t bound = id_bound > 0 ? id_bound : (int64_t)1 << 31;
    int64_t n, oob = 0;
    bool any_val = false;
#if defined(__AVX512BW__)
    int64_t used = 0;
    n = parse_region_i32_simd(r->buf, end, src, dst, val, cap, bound, &oob,
                              &any_val, &used);
    r->offset += used;
#else
    const char* p = r->buf;
    n = 0;
    int64_t s, d; double v; bool h;
    while (p < end && n < cap) {
        if (parse_two_col_fast(p, &s, &d)) {
            oob += (s >= bound) | (d >= bound);
            src[n] = (int32_t)s;
            dst[n] = (int32_t)d;
            val[n] = 0.0;
            ++n;
            continue;
        }
        if (parse_line_fast(p, end, &s, &d, &v, &h)) {
            oob += (s < 0) | (s >= bound) | (d < 0) | (d >= bound);
            src[n] = (int32_t)s;
            dst[n] = (int32_t)d;
            val[n] = v;
            any_val |= h;
            ++n;
        }
    }
    r->offset += p - r->buf;
#endif
    if (at_eof && r->offset >= r->size) *at_eof_out = 1;
    *has_val = any_val ? 1 : 0;
    *oob_out = oob;
    return n;
}

// Fast tab-separated edge-file writer (for corpus synthesis at scale —
// np.savetxt measures ~0.5M edges/s; this runs ~100x that across cores).
// Appends when append != 0. Returns 0, or -1 on IO error.
int64_t write_edge_file(const char* path, const int64_t* src,
                        const int64_t* dst, int64_t n, int32_t append,
                        int32_t n_threads) {
    int64_t t = n_threads < 1 ? 1 : n_threads;
    if (t > n / (1 << 16)) t = n / (1 << 16) ? n / (1 << 16) : 1;
    // format each slice into its own buffer, then write sequentially
    std::vector<std::string> bufs((size_t)t);
    std::vector<std::thread> workers;
    for (int64_t i = 0; i < t; ++i) {
        workers.emplace_back([&, i] {
            int64_t a = (n * i) / t, b = (n * (i + 1)) / t;
            std::string& out = bufs[(size_t)i];
            out.reserve((size_t)(b - a) * 16);
            char tmp[48];
            for (int64_t j = a; j < b; ++j) {
                char* p = tmp + sizeof(tmp);
                *--p = '\n';
                uint64_t y = (uint64_t)dst[j];
                do { *--p = '0' + (char)(y % 10); y /= 10; } while (y);
                *--p = '\t';
                uint64_t x = (uint64_t)src[j];
                do { *--p = '0' + (char)(x % 10); x /= 10; } while (x);
                out.append(p, (size_t)(tmp + sizeof(tmp) - p));
            }
        });
    }
    for (auto& w : workers) w.join();
    FILE* f = fopen(path, append ? "ab" : "wb");
    if (!f) return -1;
    for (auto& b : bufs) {
        if (b.size() && fwrite(b.data(), 1, b.size(), f) != b.size()) {
            fclose(f);
            return -1;
        }
    }
    fclose(f);
    return 0;
}

// Binary wire-frame column decode (the GSEW ingest wire format,
// core/ingest.py). One call replaces the per-line strtoll/int() work of
// the text path entirely: the payload already IS little-endian columns,
// so decoding is a geometry check plus a widen/copy into the caller's
// int64/double buffers. Layout: src column, then dst column (int32 when
// wide == 0, int64 otherwise), then an optional float64 value column.
// Returns 0, or -1 when the payload size disagrees with (n, wide,
// has_val) — the caller counts that as a malformed frame.
int64_t decode_edge_frame(const char* payload, int64_t nbytes, int64_t n,
                          int32_t wide, int32_t has_val, int64_t* src,
                          int64_t* dst, double* val) {
    if (n < 0) return -1;
    int64_t isz = wide ? 8 : 4;
    int64_t want = n * isz * 2 + (has_val ? n * 8 : 0);
    if (nbytes != want) return -1;
    if (wide) {
        memcpy(src, payload, (size_t)(n * 8));
        memcpy(dst, payload + n * 8, (size_t)(n * 8));
    } else {
        // widen int32 -> int64 (the engine's raw-id dtype) in one pass
        int32_t s32, d32;
        const char* ps = payload;
        const char* pd = payload + n * 4;
        for (int64_t i = 0; i < n; ++i) {
            memcpy(&s32, ps + i * 4, 4);
            memcpy(&d32, pd + i * 4, 4);
            src[i] = s32;
            dst[i] = d32;
        }
    }
    if (has_val) memcpy(val, payload + n * isz * 2, (size_t)(n * 8));
    return 0;
}

// Parse a memory buffer of complete text edge lines (the socket text hot
// path, core/sources.py): same accepted grammar as the file reader
// (parse_line_fast), one call per recv batch instead of per-line Python
// split()/int(). Unlike the file path, MALFORMED lines are counted —
// a live socket's noise is data the operator should know about — where
// malformed means a non-blank, non-comment line the grammar rejects.
// [buf, buf+len) must carry READ_PAD zero bytes past len (SWAR loads).
// Returns edges written (never exceeds cap; the caller sizes cap at the
// line count), with *malformed_out the rejected-line count.
int64_t parse_edge_lines(const char* buf, int64_t len, int64_t* src,
                         int64_t* dst, double* val, int64_t cap,
                         int32_t* has_val, int64_t* malformed_out) {
    const char* p = buf;
    const char* end = buf + len;
    int64_t n = 0, malformed = 0;
    bool av = false;
    int64_t s, d;
    double v;
    bool h;
    while (p < end && n < cap) {
        const char* q = skip_sep(p, end);
        if (q >= end) break;
        if (*q == '#' || *q == '%' || *q == '\n') {
            p = skip_line(q, end);
            continue;
        }
        if (parse_line_fast(p, end, &s, &d, &v, &h)) {
            src[n] = s;
            dst[n] = d;
            val[n] = v;
            av |= h;
            ++n;
        } else {
            ++malformed;  // non-blank, non-comment, rejected: counted
        }
    }
    *has_val = av ? 1 : 0;
    *malformed_out = malformed;
    return n;
}

}  // extern "C"

// --------------------------------------------------------------------- //
// First-seen vertex compaction (the VertexDict.encode hot path).
//
// Open-addressing int64 -> int32 hash map with linear probing; the
// Python VertexDict keeps the reverse (idx -> raw) table and hands the
// encoder only the forward mapping. ~10x the numpy sorted-merge path.
// --------------------------------------------------------------------- //

namespace {

struct Encoder {
    int64_t* keys;    // EMPTY_KEY = sentinel
    int32_t* vals;
    int64_t cap;      // power of two
    int64_t size;
    int32_t min_idx;  // slot for the raw id == EMPTY_KEY itself (-1 = unseen)
};

constexpr int64_t EMPTY_KEY = INT64_MIN;

inline uint64_t mix_hash(uint64_t x) {
    x ^= x >> 33; x *= 0xff51afd7ed558ccdULL;
    x ^= x >> 33; x *= 0xc4ceb9fe1a85ec53ULL;
    x ^= x >> 33; return x;
}

void encoder_rehash(Encoder* e, int64_t new_cap) {
    int64_t* nk = (int64_t*)malloc(new_cap * sizeof(int64_t));
    int32_t* nv = (int32_t*)malloc(new_cap * sizeof(int32_t));
    for (int64_t i = 0; i < new_cap; ++i) nk[i] = EMPTY_KEY;
    for (int64_t i = 0; i < e->cap; ++i) {
        if (e->keys[i] == EMPTY_KEY) continue;
        uint64_t h = mix_hash((uint64_t)e->keys[i]) & (new_cap - 1);
        while (nk[h] != EMPTY_KEY) h = (h + 1) & (new_cap - 1);
        nk[h] = e->keys[i];
        nv[h] = e->vals[i];
    }
    free(e->keys); free(e->vals);
    e->keys = nk; e->vals = nv; e->cap = new_cap;
}

}  // namespace

extern "C" {

void* encoder_create() {
    Encoder* e = (Encoder*)malloc(sizeof(Encoder));
    e->cap = 1024; e->size = 0; e->min_idx = -1;
    e->keys = (int64_t*)malloc(e->cap * sizeof(int64_t));
    e->vals = (int32_t*)malloc(e->cap * sizeof(int32_t));
    for (int64_t i = 0; i < e->cap; ++i) e->keys[i] = EMPTY_KEY;
    return e;
}

void encoder_destroy(void* ptr) {
    Encoder* e = (Encoder*)ptr;
    free(e->keys); free(e->vals); free(e);
}

namespace {

inline int32_t encode_one(Encoder* e, int64_t k, int64_t* novel_out,
                          int64_t* n_novel) {
    if ((e->size + 1) * 10 >= e->cap * 7) encoder_rehash(e, e->cap * 2);
    if (k == EMPTY_KEY) {  // the sentinel value is a legal raw id
        if (e->min_idx < 0) {
            e->min_idx = (int32_t)e->size;
            novel_out[(*n_novel)++] = k;
            e->size++;
        }
        return e->min_idx;
    }
    uint64_t h = mix_hash((uint64_t)k) & (e->cap - 1);
    while (true) {
        if (e->keys[h] == k) return e->vals[h];
        if (e->keys[h] == EMPTY_KEY) {
            e->keys[h] = k;
            e->vals[h] = (int32_t)e->size;
            novel_out[(*n_novel)++] = k;
            return (int32_t)e->size++;
        }
        h = (h + 1) & (e->cap - 1);
    }
}

inline void prefetch_slot(const Encoder* e, int64_t k) {
    uint64_t hp = mix_hash((uint64_t)k) & (e->cap - 1);
    __builtin_prefetch(&e->keys[hp]);
    __builtin_prefetch(&e->vals[hp]);
}

}  // namespace

// Encode n raw ids to compact indices (first-seen-first). Novel raw ids,
// in first-appearance order, are appended to novel_out (caller-sized >= n).
// Returns the number of novel ids.
int64_t encoder_encode(void* ptr, const int64_t* raw, int64_t n,
                       int32_t* idx_out, int64_t* novel_out) {
    Encoder* e = (Encoder*)ptr;
    int64_t n_novel = 0;
    // Random probes into a table larger than L2 are memory-latency bound
    // (~20M ids/s); issuing the hash-slot prefetch a few elements ahead
    // overlaps the misses and roughly triples throughput.
    constexpr int64_t PD = 16;
    for (int64_t i = 0; i < n; ++i) {
        if (i + PD < n) prefetch_slot(e, raw[i + PD]);
        idx_out[i] = encode_one(e, raw[i], novel_out, &n_novel);
    }
    return n_novel;
}

// Paired encode for edge columns: equivalent to encoding the interleaved
// sequence a0,b0,a1,b1,... (first-seen order follows edge arrival, matching
// the reference's per-record processing) without the caller materializing
// the interleaved copy.
int64_t encoder_encode2(void* ptr, const int64_t* a, const int64_t* b,
                        int64_t n, int32_t* ia, int32_t* ib,
                        int64_t* novel_out) {
    Encoder* e = (Encoder*)ptr;
    int64_t n_novel = 0;
    constexpr int64_t PD = 8;
    for (int64_t i = 0; i < n; ++i) {
        if (i + PD < n) {
            prefetch_slot(e, a[i + PD]);
            prefetch_slot(e, b[i + PD]);
        }
        ia[i] = encode_one(e, a[i], novel_out, &n_novel);
        ib[i] = encode_one(e, b[i], novel_out, &n_novel);
    }
    return n_novel;
}

// Session-based fused parse+encode (same loop as parse_encode_span over
// the persistent reader buffer — no per-chunk allocation or page faults).
int64_t reader_next_encoded(void* ptr, void* enc_ptr, int32_t* src32,
                            int32_t* dst32, double* val, int64_t cap,
                            int64_t* novel_out, int64_t* n_novel_out,
                            int32_t* has_val, int32_t* at_eof_out) {
    SpanReader* r = (SpanReader*)ptr;
    bool at_eof = false;
    *at_eof_out = 0;
    *has_val = 0;
    *n_novel_out = 0;
    const char* end = nullptr;
    int64_t span = reader_fill(r, &end, &at_eof);
    if (span < 0) return -1;
    if (span == 0) {
        if (at_eof) *at_eof_out = 1;
        return 0;
    }
    Encoder* e = (Encoder*)enc_ptr;
    const char* p = r->buf;
    int64_t n = 0, n_novel = 0;
    bool any_val = false;
    constexpr int B = 128;
    int64_t ss[2][B], dd[2][B];
    double vv[2][B];
    int m[2] = {0, 0};
    auto parse_batch = [&](int which) {
        int k = 0;
        int64_t s, d; double v; bool h;
        while (k < B && p < end && n + m[which ^ 1] + k < cap) {
            if (parse_two_col_fast(p, &s, &d)) {
                ss[which][k] = s; dd[which][k] = d; vv[which][k] = 0.0;
                ++k;
                continue;
            }
            if (parse_line_fast(p, end, &s, &d, &v, &h)) {
                ss[which][k] = s; dd[which][k] = d; vv[which][k] = v;
                any_val |= h;
                ++k;
            }
        }
        m[which] = k;
        for (int i = 0; i < k; ++i) {
            prefetch_slot(e, ss[which][i]);
            prefetch_slot(e, dd[which][i]);
        }
    };
    parse_batch(0);
    int cur = 0;
    while (m[cur]) {
        parse_batch(cur ^ 1);
        for (int i = 0; i < m[cur]; ++i) {
            src32[n] = encode_one(e, ss[cur][i], novel_out, &n_novel);
            dst32[n] = encode_one(e, dd[cur][i], novel_out, &n_novel);
            val[n] = vv[cur][i];
            ++n;
        }
        cur ^= 1;
    }
    r->offset += p - r->buf;
    if (at_eof && r->offset >= r->size) *at_eof_out = 1;
    *has_val = any_val ? 1 : 0;
    *n_novel_out = n_novel;
    return n;
}

// Lookup without insert; returns -1 when unseen.
int32_t encoder_lookup(void* ptr, int64_t k) {
    Encoder* e = (Encoder*)ptr;
    if (k == EMPTY_KEY) return e->min_idx;
    uint64_t h = mix_hash((uint64_t)k) & (e->cap - 1);
    while (true) {
        if (e->keys[h] == k) return e->vals[h];
        if (e->keys[h] == EMPTY_KEY) return -1;
        h = (h + 1) & (e->cap - 1);
    }
}

// Batched lookup without insert (the serving read path): out[i] = compact
// id or -1. One C call per query batch — a Python-side loop over
// encoder_lookup costs a GIL/ctypes round trip per id, which is exactly
// the per-query host loop the query engine forbids.
void encoder_lookup_batch(void* ptr, const int64_t* ks, int64_t n,
                          int32_t* out) {
    Encoder* e = (Encoder*)ptr;
    for (int64_t i = 0; i < n; ++i) {
        if (i + 8 < n) prefetch_slot(e, ks[i + 8]);
        int64_t k = ks[i];
        if (k == EMPTY_KEY) { out[i] = e->min_idx; continue; }
        uint64_t h = mix_hash((uint64_t)k) & (e->cap - 1);
        while (true) {
            if (e->keys[h] == k) { out[i] = e->vals[h]; break; }
            if (e->keys[h] == EMPTY_KEY) { out[i] = -1; break; }
            h = (h + 1) & (e->cap - 1);
        }
    }
}

int64_t encoder_size(void* ptr) { return ((Encoder*)ptr)->size; }

}  // extern "C"

// --------------------------------------------------------------------- //
// Compiled streaming-CC baseline (the honest comparator for bench.py).
//
// This is the reference's execution model compiled to native code: edges
// round-robin across P partitions (PartitionMapper stamping subtask
// indices, SummaryBulkAggregation.java:93-106), each partition folds its
// window slice into its own union-find keyed by RAW vertex id — hash-map
// state, exactly the shape of the reference's DisjointSet-over-HashMaps
// (summaries/DisjointSet.java:30-154) — and at window end the partials
// merge pairwise into a running global summary on one thread (the
// parallelism-1 Merger, SummaryAggregation.java:107-119). It is strictly
// faster than the JVM original (no Flink runtime, no serialization, no
// network) — beating it by 10x is therefore a conservative proof of the
// north-star target.
// --------------------------------------------------------------------- //

namespace {

// Open-addressing union-find over raw int64 ids: map id -> slot, with
// parent/rank arrays indexed by slot (path halving).
struct UnionFind {
    std::vector<int64_t> keys;   // EMPTY_KEY = empty
    std::vector<int32_t> slot;   // key -> dense slot
    std::vector<int32_t> parent;
    std::vector<uint8_t> rnk;
    int64_t mask;

    explicit UnionFind(int64_t cap_hint = 1024) {
        int64_t cap = 1024;
        while (cap < cap_hint * 2) cap <<= 1;
        keys.assign(cap, EMPTY_KEY);
        slot.assign(cap, -1);
        mask = cap - 1;
    }
    void maybe_grow() {
        if ((int64_t)parent.size() * 10 < (mask + 1) * 7) return;
        int64_t ncap = (mask + 1) << 1;
        std::vector<int64_t> nk(ncap, EMPTY_KEY);
        std::vector<int32_t> ns(ncap, -1);
        for (int64_t i = 0; i <= mask; ++i) {
            if (keys[i] == EMPTY_KEY) continue;
            uint64_t h = mix_hash((uint64_t)keys[i]) & (ncap - 1);
            while (nk[h] != EMPTY_KEY) h = (h + 1) & (ncap - 1);
            nk[h] = keys[i];
            ns[h] = slot[i];
        }
        keys.swap(nk);
        slot.swap(ns);
        mask = ncap - 1;
    }
    int32_t lookup_or_insert(int64_t k) {
        maybe_grow();
        uint64_t h = mix_hash((uint64_t)k) & mask;
        while (true) {
            if (keys[h] == k) return slot[h];
            if (keys[h] == EMPTY_KEY) {
                int32_t s = (int32_t)parent.size();
                keys[h] = k;
                slot[h] = s;
                parent.push_back(s);
                rnk.push_back(0);
                return s;
            }
            h = (h + 1) & mask;
        }
    }
    int32_t find(int32_t x) {
        while (parent[x] != x) {
            parent[x] = parent[parent[x]];  // path halving
            x = parent[x];
        }
        return x;
    }
    void union_ids(int64_t a, int64_t b) {
        int32_t ra = find(lookup_or_insert(a));
        int32_t rb = find(lookup_or_insert(b));
        if (ra == rb) return;
        if (rnk[ra] < rnk[rb]) { int32_t t = ra; ra = rb; rb = t; }
        parent[rb] = ra;
        if (rnk[ra] == rnk[rb]) ++rnk[ra];
    }
    // DisjointSet.merge analog: fold every (element, root) pair of one
    // structure into the other (ConnectedComponents.java:116-125).
    void merge_from(UnionFind& o) {
        std::vector<int64_t> slot_to_key(o.parent.size(), EMPTY_KEY);
        for (int64_t i = 0; i <= o.mask; ++i)
            if (o.keys[i] != EMPTY_KEY) slot_to_key[o.slot[i]] = o.keys[i];
        for (int64_t i = 0; i <= o.mask; ++i) {
            if (o.keys[i] == EMPTY_KEY) continue;
            union_ids(o.keys[i], slot_to_key[o.find(o.slot[i])]);
        }
    }
};

}  // namespace

extern "C" {

// Streaming-model CC over a parsed edge array: `partitions` parallel
// window folds + sequential merge per window, `window` edges per window.
// Returns elapsed nanoseconds; *components_out gets the final component
// count (for correctness cross-checks against the device path).
int64_t cc_baseline_run(const int64_t* src, const int64_t* dst, int64_t n,
                        int64_t window, int32_t partitions,
                        int64_t* components_out) {
    struct timespec t0, t1;
    clock_gettime(CLOCK_MONOTONIC, &t0);
    int64_t p = partitions < 1 ? 1 : partitions;
    UnionFind global(1024);
    for (int64_t w0 = 0; w0 < n; w0 += window) {
        int64_t w1 = w0 + window < n ? w0 + window : n;
        std::vector<UnionFind> parts;
        parts.reserve((size_t)p);
        for (int64_t i = 0; i < p; ++i) parts.emplace_back(256);
        std::vector<std::thread> workers;
        for (int64_t i = 0; i < p; ++i) {
            workers.emplace_back([&, i] {
                UnionFind& uf = parts[(size_t)i];
                // round-robin partition stamping, as PartitionMapper does
                for (int64_t j = w0 + i; j < w1; j += p)
                    uf.union_ids(src[j], dst[j]);
            });
        }
        for (auto& w : workers) w.join();
        for (auto& part : parts) global.merge_from(part);
    }
    // component count = number of root slots
    int64_t comps = 0;
    for (size_t s = 0; s < global.parent.size(); ++s)
        if (global.find((int32_t)s) == (int32_t)s) ++comps;
    *components_out = comps;
    clock_gettime(CLOCK_MONOTONIC, &t1);
    return (t1.tv_sec - t0.tv_sec) * 1000000000LL + (t1.tv_nsec - t0.tv_nsec);
}

// Flink-representative proxy (round-3 verdict #4): the same job graph as
// the reference's streaming-CC plan, with the runtime costs Flink adds on
// top of the bare algorithm made explicit — every record crosses the
// partitioner as SERIALIZED bytes (Flink's network shuffle: a
// StreamRecord tag byte + two big-endian longs, the Tuple2<Long,Long>
// wire shape of DataOutputView), and each window's partials cross a
// second serialized boundary to the parallelism-1 Merger (the DisjointSet
// serializer writes (element, parent) pairs; SummaryAggregation.java
// routes partials through a keyed shuffle to the single Merger subtask).
// Deliberately NOT modeled: JVM object churn/GC, Flink's actual netty
// stack, credit-based flow control, task-thread handover — all of which
// only slow the real system further. This proxy is therefore an UPPER
// bound on real single-host Flink throughput for this job, so
// headline/proxy is a conservative lower bound on the true advantage;
// it must land between the interpreted-Python union-find tier and the
// zero-overhead compiled baseline above to be credible (bench.py asserts
// exactly that bracket).
int64_t flink_proxy_run(const int64_t* src, const int64_t* dst, int64_t n,
                        int64_t window, int32_t partitions,
                        int64_t* components_out) {
    struct timespec t0, t1;
    clock_gettime(CLOCK_MONOTONIC, &t0);
    int64_t p = partitions < 1 ? 1 : partitions;
    UnionFind global(1024);
    std::vector<std::vector<uint8_t>> queues((size_t)p);
    for (int64_t w0 = 0; w0 < n; w0 += window) {
        int64_t w1 = w0 + window < n ? w0 + window : n;
        // --- shuffle boundary 1: source -> window fold -------------------
        // round-robin partition stamping (PartitionMapper), then each
        // record is serialized onto its partition's in-flight buffer.
        for (auto& q : queues) q.clear();
        for (int64_t j = w0; j < w1; ++j) {
            std::vector<uint8_t>& q = queues[(size_t)((j - w0) % p)];
            size_t off = q.size();
            q.resize(off + 17);
            q[off] = 0;  // StreamRecord tag (element, no timestamp)
            uint64_t a = __builtin_bswap64((uint64_t)src[j]);
            uint64_t b = __builtin_bswap64((uint64_t)dst[j]);
            memcpy(q.data() + off + 1, &a, 8);
            memcpy(q.data() + off + 9, &b, 8);
        }
        // --- per-partition window folds (deserialize + union) -----------
        std::vector<UnionFind> parts;
        parts.reserve((size_t)p);
        for (int64_t i = 0; i < p; ++i) parts.emplace_back(256);
        std::vector<std::thread> workers;
        for (int64_t i = 0; i < p; ++i) {
            workers.emplace_back([&, i] {
                UnionFind& uf = parts[(size_t)i];
                const std::vector<uint8_t>& q = queues[(size_t)i];
                for (size_t off = 0; off + 17 <= q.size(); off += 17) {
                    uint64_t a, b;
                    memcpy(&a, q.data() + off + 1, 8);
                    memcpy(&b, q.data() + off + 9, 8);
                    uf.union_ids((int64_t)__builtin_bswap64(a),
                                 (int64_t)__builtin_bswap64(b));
                }
            });
        }
        for (auto& w : workers) w.join();
        // --- shuffle boundary 2: partials -> parallelism-1 Merger --------
        // each partial DisjointSet serializes as (element, root) pairs and
        // the Merger deserializes and re-unions them.
        for (auto& part : parts) {
            std::vector<int64_t> slot_to_key(part.parent.size(), EMPTY_KEY);
            for (int64_t i = 0; i <= part.mask; ++i)
                if (part.keys[i] != EMPTY_KEY)
                    slot_to_key[part.slot[i]] = part.keys[i];
            std::vector<uint8_t> wire;
            wire.reserve(part.parent.size() * 16);
            for (int64_t i = 0; i <= part.mask; ++i) {
                if (part.keys[i] == EMPTY_KEY) continue;
                uint64_t e = __builtin_bswap64((uint64_t)part.keys[i]);
                uint64_t r = __builtin_bswap64(
                    (uint64_t)slot_to_key[part.find(part.slot[i])]);
                size_t off = wire.size();
                wire.resize(off + 16);
                memcpy(wire.data() + off, &e, 8);
                memcpy(wire.data() + off + 8, &r, 8);
            }
            for (size_t off = 0; off + 16 <= wire.size(); off += 16) {
                uint64_t e, r;
                memcpy(&e, wire.data() + off, 8);
                memcpy(&r, wire.data() + off + 8, 8);
                global.union_ids((int64_t)__builtin_bswap64(e),
                                 (int64_t)__builtin_bswap64(r));
            }
        }
    }
    int64_t comps = 0;
    for (size_t s = 0; s < global.parent.size(); ++s)
        if (global.find((int32_t)s) == (int32_t)s) ++comps;
    *components_out = comps;
    clock_gettime(CLOCK_MONOTONIC, &t1);
    return (t1.tv_sec - t0.tv_sec) * 1000000000LL + (t1.tv_nsec - t0.tv_nsec);
}

}  // extern "C"

// ===========================================================================
// Compact-id incremental union-find: the host CC carry (round 5).
//
// The streaming-CC merge is control-flow-heavy pointer chasing — the one
// graph kernel that maps better onto a scalar core beside the parser than
// onto dense vector passes (the reference's own fold is a CPU hashmap,
// library/ConnectedComponents.java:83-126). This carry runs union-find
// with path-halving over COMPACT int32 ids (the vertex dictionary already
// made the id space dense, so no hash keys are needed — cf. the keyed
// UnionFind above used by the baselines), and per window reports exactly
// what the device mirror needs to stay a resolvable pointer forest:
//
//   * the window's touched ids + their post-window roots (epoch-stamped
//     first-touch detection, no per-window clears), and
//   * every root DEMOTED this window + its post-window root — a vertex
//     never touched again still resolves on the device mirror because
//     each pointer target was once a root and every demotion is mirrored.
//
// Union is by MIN ROOT (parent[max_root] = min_root), preserving the
// invariant the device carries share: a component's canonical root is its
// minimum compact id.
// ===========================================================================

struct CompactUF {
    std::vector<int32_t> parent;
    std::vector<uint32_t> stamp;   // epoch of last touch
    uint32_t epoch = 0;

    void ensure(int64_t vcap) {
        int64_t old = (int64_t)parent.size();
        if (vcap <= old) return;
        parent.resize((size_t)vcap);
        stamp.resize((size_t)vcap, 0);
        for (int64_t v = old; v < vcap; ++v) parent[(size_t)v] = (int32_t)v;
    }

    int32_t find(int32_t x) {
        while (parent[(size_t)x] != x) {
            int32_t p = parent[(size_t)x];
            int32_t g = parent[(size_t)p];
            parent[(size_t)x] = g;  // path halving
            x = g;
        }
        return x;
    }
};

extern "C" {

void* cuf_create() { return new (std::nothrow) CompactUF(); }

void cuf_destroy(void* h) { delete (CompactUF*)h; }

// Fold one window of compact edges. touched_out/roots_out need capacity
// 2n; changed_out/changed_roots_out need capacity n. Returns the touched
// count (>= 0) and writes the demoted-root count to *n_changed_out.
// Ids are validated in a PREPASS before any union is applied: a mid-loop
// bail-out would leave the union-find partially mutated with the applied
// unions' touched/changed outputs discarded, permanently desyncing a
// device pointer-forest mirror from this state for callers that catch
// the error and keep streaming. A -1 return therefore guarantees the
// carry is untouched (the wprep epoch scheme self-heals on the next
// window; a union does not).
int64_t cuf_fold_window(void* h, const int32_t* src, const int32_t* dst,
                        int64_t n, int64_t vcap,
                        int32_t* touched_out, int32_t* roots_out,
                        int32_t* changed_out, int32_t* changed_roots_out,
                        int64_t* n_changed_out) {
    CompactUF& uf = *(CompactUF*)h;
    for (int64_t i = 0; i < n; ++i) {
        int32_t a = src[i], b = dst[i];
        if (a < 0 || b < 0 || a >= vcap || b >= vcap) return -1;
    }
    uf.ensure(vcap);
    if (++uf.epoch == 0) {  // uint32 wrap: see wprep_run
        std::fill(uf.stamp.begin(), uf.stamp.end(), 0u);
        uf.epoch = 1;
    }
    int64_t nt = 0, nc = 0;
    for (int64_t i = 0; i < n; ++i) {
        int32_t a = src[i], b = dst[i];
        if (uf.stamp[(size_t)a] != uf.epoch) {
            uf.stamp[(size_t)a] = uf.epoch;
            touched_out[nt++] = a;
        }
        if (uf.stamp[(size_t)b] != uf.epoch) {
            uf.stamp[(size_t)b] = uf.epoch;
            touched_out[nt++] = b;
        }
        int32_t ra = uf.find(a), rb = uf.find(b);
        if (ra == rb) continue;
        int32_t lo = ra < rb ? ra : rb;
        int32_t hi = ra < rb ? rb : ra;
        uf.parent[(size_t)hi] = lo;   // union by min root
        changed_out[nc++] = hi;       // hi was a root until now: unique
    }
    for (int64_t i = 0; i < nt; ++i)
        roots_out[i] = uf.find(touched_out[i]);
    for (int64_t i = 0; i < nc; ++i)
        changed_roots_out[i] = uf.find(changed_out[i]);
    *n_changed_out = nc;
    return nt;
}

// Fold K windows in ONE call (the superbatch host-carry path): columns
// are concatenated with offsets[w]..offsets[w+1] delimiting window w
// (offsets has k+1 entries). Per-window outputs land back to back in
// the shared buffers with lengths in t_counts/c_counts (same capacity
// contract as k cuf_fold_window calls: touched/roots 2n total,
// changed/changed_roots n total, n = offsets[k]). Additionally emits
// the GROUP-deduped commit delta — the union of every touched or
// demoted id with its POST-GROUP root — into group_ids/group_roots
// (capacity 3n; count to *n_group_out): exactly the single masked
// scatter a device mirror needs per group, deduped here because a
// python-side unique() measured 26 ms per 64-window group. Ids are
// validated across the WHOLE group before any union (same no-partial-
// mutation guarantee as cuf_fold_window, extended to the group).
int64_t cuf_fold_group(void* h, const int32_t* src, const int32_t* dst,
                       const int64_t* offsets, int64_t k, int64_t vcap,
                       int32_t* touched_out, int32_t* roots_out,
                       int32_t* changed_out, int32_t* changed_roots_out,
                       int64_t* t_counts, int64_t* c_counts,
                       int32_t* group_ids, int32_t* group_roots,
                       int64_t* gt_counts, int64_t* n_group_out) {
    CompactUF& uf = *(CompactUF*)h;
    const int64_t n = offsets[k];
    for (int64_t i = 0; i < n; ++i) {
        int32_t a = src[i], b = dst[i];
        if (a < 0 || b < 0 || a >= vcap || b >= vcap) return -1;
    }
    int64_t tt = 0, tc = 0;
    for (int64_t w = 0; w < k; ++w) {
        const int64_t a = offsets[w];
        int64_t nc = 0;
        int64_t nt = cuf_fold_window(
            h, src + a, dst + a, offsets[w + 1] - a, vcap,
            touched_out + tt, roots_out + tt,
            changed_out + tc, changed_roots_out + tc, &nc);
        if (nt < 0) return -1;  // unreachable: ids validated above
        t_counts[w] = nt;
        c_counts[w] = nc;
        tt += nt;
        tc += nc;
    }
    // group dedup pass: group-unique TOUCHED ids first, in window order
    // (first-seen) with per-window counts in gt_counts — the caller's
    // first-seen emission log batches on this — then any demoted roots
    // not already present complete the commit delta.
    if (++uf.epoch == 0) {
        std::fill(uf.stamp.begin(), uf.stamp.end(), 0u);
        uf.epoch = 1;
    }
    int64_t ng = 0, toff = 0;
    for (int64_t w = 0; w < k; ++w) {
        const int64_t start = ng;
        for (int64_t i = toff; i < toff + t_counts[w]; ++i) {
            int32_t v = touched_out[i];
            if (uf.stamp[(size_t)v] != uf.epoch) {
                uf.stamp[(size_t)v] = uf.epoch;
                group_ids[ng++] = v;
            }
        }
        toff += t_counts[w];
        gt_counts[w] = ng - start;
    }
    for (int64_t i = 0; i < tc; ++i) {
        int32_t v = changed_out[i];
        if (uf.stamp[(size_t)v] != uf.epoch) {
            uf.stamp[(size_t)v] = uf.epoch;
            group_ids[ng++] = v;
        }
    }
    for (int64_t i = 0; i < ng; ++i)
        group_roots[i] = uf.find(group_ids[i]);
    *n_group_out = ng;
    return tt;
}

// Canonical flat labels for [0, vcap) (checkpoint sync point).
void cuf_flatten(void* h, int32_t* out, int64_t vcap) {
    CompactUF& uf = *(CompactUF*)h;
    uf.ensure(vcap);
    for (int64_t v = 0; v < vcap; ++v)
        out[v] = uf.find((int32_t)v);
}

// Restore from flat labels (a valid forest; roots must be component
// minima, which cuf_flatten and the device carries both guarantee).
int64_t cuf_load(void* h, const int32_t* labels, int64_t vcap) {
    CompactUF& uf = *(CompactUF*)h;
    uf.parent.assign((size_t)vcap, 0);
    uf.stamp.assign((size_t)vcap, 0);
    uf.epoch = 0;
    for (int64_t v = 0; v < vcap; ++v) {
        int32_t l = labels[v];
        if (l < 0 || l > v) return -1;  // not a min-rooted forest
        uf.parent[(size_t)v] = l;
    }
    return 0;
}

}  // extern "C"

// ===========================================================================
// Window prep for the forest CC carry (round 5): touched set + local
// renumbering in ONE pass. The numpy bitmap+LUT version costs ~50 ms per
// 1M-edge window (three passes + an O(V) nonzero scan); this epoch-
// stamped single pass touches each edge once and never clears state, so
// the cost scales with the window alone (~10-15 ms at 1M edges on one
// core). Touched ids come out in ARRIVAL order — the device kernels
// index by position, not value, so any consistent order works.
// ===========================================================================

struct WindowPrep {
    // stamp+code interleaved in one 8-byte entry: each endpoint costs a
    // single random cache-line touch instead of two (the pass is
    // memory-latency bound; measured 36 -> ~25 ms per 1M-edge window)
    struct Entry { uint32_t stamp; int32_t code; };
    std::vector<Entry> tab;
    uint32_t epoch = 0;

    void ensure(int64_t vcap) {
        if ((int64_t)tab.size() < vcap) tab.resize((size_t)vcap, Entry{0, 0});
    }
};

extern "C" {

void* wprep_create() { return new (std::nothrow) WindowPrep(); }

void wprep_destroy(void* h) { delete (WindowPrep*)h; }

// tids_out needs capacity 2n; lu_out/lv_out capacity n. Returns the
// touched count, or -1 on out-of-range ids.
int64_t wprep_run(void* h, const int32_t* src, const int32_t* dst,
                  int64_t n, int64_t vcap,
                  int32_t* tids_out, int32_t* lu_out, int32_t* lv_out) {
    WindowPrep& w = *(WindowPrep*)h;
    w.ensure(vcap);
    if (++w.epoch == 0) {
        // uint32 epoch wrapped (one in 2^32 windows): stale stamps from
        // 4.3e9 windows ago would read as current — reset and burn
        // epoch 0 (the default stamp value)
        std::fill(w.tab.begin(), w.tab.end(), WindowPrep::Entry{0, 0});
        w.epoch = 1;
    }
    int32_t t = 0;
    const int64_t PF = 16;  // unlike the union-find's dependent chains,
                            // these table accesses are independent
                            // across edges, so prefetch hides the misses
    WindowPrep::Entry* tab = w.tab.data();
    for (int64_t i = 0; i < n; ++i) {
        if (i + PF < n) {
            // ids at the prefetch distance are NOT yet validated: clamp
            // before forming the address (an out-of-range vector index
            // is UB even for a prefetch)
            size_t pa = (size_t)(uint32_t)src[i + PF];
            size_t pb = (size_t)(uint32_t)dst[i + PF];
            if (pa < (size_t)vcap) __builtin_prefetch(tab + pa, 1, 1);
            if (pb < (size_t)vcap) __builtin_prefetch(tab + pb, 1, 1);
        }
        int32_t a = src[i], b = dst[i];
        if (a < 0 || b < 0 || a >= vcap || b >= vcap) return -1;
        WindowPrep::Entry& ea = w.tab[(size_t)a];
        if (ea.stamp != w.epoch) {
            ea.stamp = w.epoch;
            ea.code = t;
            tids_out[t++] = a;
        }
        lu_out[i] = ea.code;
        WindowPrep::Entry& eb = w.tab[(size_t)b];
        if (eb.stamp != w.epoch) {
            eb.stamp = w.epoch;
            eb.code = t;
            tids_out[t++] = b;
        }
        lv_out[i] = eb.code;
    }
    return t;
}

}  // extern "C"
