"""Cluster transport fabric: one exchange interface, three backends.

See :mod:`~gelly_streaming_tpu.fabric.base` for the contract. The
public surface:

- :class:`Transport` / :class:`TagStat` / :class:`TransportUnsupported`
  — the interface;
- :class:`SharedDirTransport` — tag = file under a shared directory
  (today's semantics, byte-identical layouts);
- :class:`SocketTransport` / :class:`ExchangeDaemon` — GSRP frames
  against a tiny stdlib exchange daemon;
- :class:`CollectiveTransport` — XLA collectives over a live
  ``jax.distributed`` runtime (group primitives only);
- :class:`ElectedK` — the cadence-agreement adapter riding
  ``Transport.elect``;
- :func:`as_transport` — the string-coercion seam: every consumer that
  historically took a directory path keeps its signature, a bare
  string becoming a shared-dir transport.

``python -m gelly_streaming_tpu.fabric --smoke`` runs the 2-process
smoke over the locally-runnable backends; ``--daemon`` runs the
exchange daemon in the foreground.
"""

from __future__ import annotations

from .agreement import ElectedK
from .base import TagStat, Transport, TransportUnsupported
from .collective import CollectiveTransport
from .exchange import ExchangeDaemon, SocketTransport
from .shared_dir import SharedDirTransport

__all__ = [
    "CollectiveTransport",
    "ElectedK",
    "ExchangeDaemon",
    "SharedDirTransport",
    "SocketTransport",
    "TagStat",
    "Transport",
    "TransportUnsupported",
    "as_transport",
]


def as_transport(obj, **kwargs) -> Transport:
    """Coerce a consumer's ``transport`` argument: a
    :class:`Transport` passes through; a string is a shared directory
    (the historical signature of every seam this fabric replaced)."""
    if isinstance(obj, Transport):
        return obj
    if isinstance(obj, (str, bytes)) or hasattr(obj, "__fspath__"):
        return SharedDirTransport(str(obj), **kwargs)
    raise TypeError(
        f"expected a Transport or a shared-directory path, "
        f"got {type(obj).__name__}")
