"""GSRP frame primitives — the length-prefixed wire layer, extracted.

PR 8 built the serving RPC on length-prefixed binary frames (magic |
version | type | payload length) and proved the discipline under a
fuzzer: a reader always knows where one message ends, a torn read is a
DETECTABLE ``MalformedFrame("truncated")`` instead of a parser wedged
mid-garbage, and an oversized length field is rejected before it can
allocate. The cluster fabric's socket backend needs exactly the same
contract, so the stateless framing layer lives here and
``serving/rpc.py`` re-exports it — one frame grammar for every socket
in the repo, one fuzz surface.

What moved: the constants (:data:`MAGIC`, :data:`VERSION`,
:data:`HEADER`, :data:`DEFAULT_MAX_FRAME`), the exception taxonomy
(:class:`Disconnect` at clean boundaries, :class:`MalformedFrame` with
its counted ``kind``), and the three functions (:func:`pack_frame`,
:func:`recv_exact`, :func:`read_frame`). What did NOT move: the RPC
``Wire`` endpoint class — its fault-injection hooks and ``rpc.*``
counters are serving-specific and stay with their fuzz tests.

Frame types are allocated per consumer from one registry below so two
protocols can never collide on a type byte: the RPC query path owns
1-9, the fabric exchange protocol 10-19.
"""

from __future__ import annotations

import struct
from typing import Tuple

#: frame magic (also the protocol's garbage detector)
MAGIC = b"GSRP"
VERSION = 1
#: header: magic | version | frame type | payload length
HEADER = struct.Struct("<4sBBI")
#: reject frames past this length before reading them (an attacker's —
#: or a corrupted peer's — length field must not allocate unboundedly)
DEFAULT_MAX_FRAME = 8 << 20

# ---- frame-type registry (one byte space, partitioned per consumer) --- #
T_REQ = 1    # serving RPC: client -> server, one query batch
T_RESP = 2   # serving RPC: server -> client, one batch's outcome
T_XREQ = 10   # fabric exchange: client -> daemon, one tag-store op
T_XRESP = 11  # fabric exchange: daemon -> client, the op's outcome


class Disconnect(Exception):
    """Peer closed at a frame boundary — the clean end of a connection."""


class MalformedFrame(ValueError):
    """The byte stream violated the frame contract; ``kind`` is the
    ``rpc.malformed{kind=...}`` / ``fabric.malformed{kind=...}`` label
    (magic/version/oversized/truncated/json/request)."""

    def __init__(self, kind: str, msg: str):
        super().__init__(msg)
        self.kind = kind


def pack_frame(ftype: int, payload: bytes) -> bytes:
    return HEADER.pack(MAGIC, VERSION, ftype, len(payload)) + payload


def recv_exact(sock, n: int, *, at_boundary: bool = False) -> bytes:
    """Read exactly ``n`` bytes. EOF (or a reset) before the FIRST byte
    of a frame is a clean :class:`Disconnect`; EOF mid-frame is a
    :class:`MalformedFrame` (``truncated``) — the distinction the fuzz
    tests pin."""
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError as e:
            if at_boundary and not buf:
                raise Disconnect(repr(e)) from e
            raise MalformedFrame(
                "truncated",
                f"connection lost after {len(buf)}/{n} bytes: {e!r}",
            ) from e
        if not chunk:
            if at_boundary and not buf:
                raise Disconnect("peer closed")
            raise MalformedFrame(
                "truncated", f"peer closed after {len(buf)}/{n} bytes"
            )
        buf += chunk
    return buf


def read_frame(sock, *, max_frame: int = DEFAULT_MAX_FRAME
               ) -> Tuple[int, bytes]:
    """One complete frame off the socket; raises :class:`Disconnect` at
    a clean boundary, :class:`MalformedFrame` for everything the frame
    contract rejects."""
    head = recv_exact(sock, HEADER.size, at_boundary=True)
    magic, version, ftype, length = HEADER.unpack(head)
    if magic != MAGIC:
        raise MalformedFrame("magic", f"bad magic {magic!r}")
    if version != VERSION:
        raise MalformedFrame("version", f"unsupported version {version}")
    if length > max_frame:
        raise MalformedFrame(
            "oversized", f"frame of {length} bytes exceeds {max_frame}"
        )
    payload = recv_exact(sock, length) if length else b""
    return ftype, payload
