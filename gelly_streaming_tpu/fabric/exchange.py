"""Socket-backed transport: GSRP frames against a tiny exchange daemon.

The shared-dir backend assumes every participant mounts one filesystem;
standbys and shards on separate machines need the same tag-store
contract over TCP. This module provides it in the repo's stdlib-only
stance: :class:`ExchangeDaemon` is an in-memory tag store behind a
listening socket (thread per connection, one lock around the dict —
the store IS the serialization point, exactly like the directory was),
and :class:`SocketTransport` is the client, speaking length-prefixed
GSRP frames (:mod:`~gelly_streaming_tpu.fabric.wire` — the PR 8 frame
grammar, same fuzz discipline) with the serving client's
bounded-reconnect behavior.

Deployment shape: the daemon runs on the coordinator (or any stable
host) and OWNS the exchange state, so tags survive worker kills and
restarts — the replay-safety the coordinated layer needs — but not a
daemon death. Durable restore state (epoch barriers, rendezvous
records) therefore stays on a persistent store; the daemon carries the
in-flight exchange/election traffic. ``put(overwrite=False)`` is
one-winner by construction: the daemon applies ops under its lock, so
exactly one concurrent put observes the tag absent.

Every wire fault is counted evidence (``fabric.malformed{kind=...}``,
``fabric.reconnects``) — the same contract the RPC fuzz tests pin for
``rpc.malformed``: no broad handler on the socket path may swallow
uncounted.
"""

from __future__ import annotations

import json
import socket as _socket
import struct
import threading
import time
from typing import List, NamedTuple, Optional, Tuple

from ..obs.registry import get_registry
from ..resilience.errors import TransientSourceError
from .base import TagStat, Transport
from .wire import (
    DEFAULT_MAX_FRAME,
    Disconnect,
    MalformedFrame,
    T_XREQ,
    T_XRESP,
    pack_frame,
    read_frame,
)

#: ops the exchange protocol speaks (one tag-store call each)
OPS = ("put", "get", "stat", "list", "delete", "ping")

_HEAD_LEN = struct.Struct("<I")


def _split_doc(payload: bytes, *, what: str) -> Tuple[dict, bytes]:
    """``json-length | json | body`` — the XREQ/XRESP payload shape."""
    if len(payload) < _HEAD_LEN.size:
        raise MalformedFrame(
            "truncated", f"{what} payload of {len(payload)} bytes has "
            f"no header")
    (n,) = _HEAD_LEN.unpack(payload[:_HEAD_LEN.size])
    head_end = _HEAD_LEN.size + n
    if len(payload) < head_end:
        raise MalformedFrame(
            "truncated",
            f"{what} header promises {n} json bytes, "
            f"{len(payload) - _HEAD_LEN.size} present")
    try:
        doc = json.loads(payload[_HEAD_LEN.size:head_end])
    except ValueError as e:
        raise MalformedFrame("json", f"{what} header: {e}") from e
    if not isinstance(doc, dict):
        raise MalformedFrame("json", f"{what} header is not an object")
    return doc, payload[head_end:]


def pack_request(op: str, tag: str = "", *, overwrite: bool = False,
                 prefix: str = "", body: bytes = b"") -> bytes:
    """One tag-store op as an XREQ payload."""
    doc = {"op": op, "tag": tag, "overwrite": bool(overwrite),
           "prefix": prefix}
    head = json.dumps(doc, separators=(",", ":")).encode("utf-8")
    return _HEAD_LEN.pack(len(head)) + head + body


def unpack_request(payload: bytes
                   ) -> Tuple[str, str, bool, str, bytes]:
    """Decode an XREQ payload -> ``(op, tag, overwrite, prefix, body)``;
    an unknown op is a :class:`MalformedFrame` (``request``)."""
    doc, body = _split_doc(payload, what="request")
    op = doc.get("op")
    if op not in OPS:
        raise MalformedFrame("request", f"unknown op {op!r}")
    return (op, str(doc.get("tag", "")),
            bool(doc.get("overwrite", False)),
            str(doc.get("prefix", "")), body)


class ExchangeResponse(NamedTuple):
    ok: bool
    created: bool
    found: bool
    size: int
    version: int
    tags: List[str]
    err: str
    body: bytes


def pack_response(*, ok: bool = True, created: bool = False,
                  found: bool = False, size: int = 0, version: int = 0,
                  tags: Tuple[str, ...] = (), err: str = "",
                  body: bytes = b"") -> bytes:
    """One op outcome as an XRESP payload."""
    doc = {"ok": bool(ok), "created": bool(created),
           "found": bool(found), "size": int(size),
           "version": int(version), "tags": list(tags), "err": err}
    head = json.dumps(doc, separators=(",", ":")).encode("utf-8")
    return _HEAD_LEN.pack(len(head)) + head + body


def unpack_response(payload: bytes) -> ExchangeResponse:
    """Decode an XRESP payload into :class:`ExchangeResponse`."""
    doc, body = _split_doc(payload, what="response")
    return ExchangeResponse(
        ok=bool(doc.get("ok", False)),
        created=bool(doc.get("created", False)),
        found=bool(doc.get("found", False)),
        size=int(doc.get("size", 0)),
        version=int(doc.get("version", 0)),
        tags=[str(t) for t in (doc.get("tags") or [])],
        err=str(doc.get("err", "")),
        body=body,
    )


class ExchangeDaemon:
    """The in-memory tag store behind a socket; see the module
    docstring. Start with :meth:`start`, address at ``(host, port)``;
    runs until :meth:`stop`."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 max_frame: int = DEFAULT_MAX_FRAME):
        self._store = {}  # tag -> (payload bytes, version int)
        self._next_version = 1
        self._lock = threading.Lock()
        self._max_frame = int(max_frame)
        self._stop = threading.Event()
        self._listener = _socket.socket(_socket.AF_INET,
                                        _socket.SOCK_STREAM)
        self._listener.setsockopt(_socket.SOL_SOCKET,
                                  _socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(64)
        self.host, self.port = self._listener.getsockname()[:2]
        self._accept_thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "ExchangeDaemon":
        t = threading.Thread(target=self._accept_loop,
                             name="fabric-exchange-accept", daemon=True)
        t.start()
        self._accept_thread = t
        return self

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                # listener closed by stop(): the loop's normal exit;
                # anything else also ends accept — count it either way
                # so an unexpected listener death is not silent
                get_registry().counter(
                    "fabric.swallowed", site="daemon_accept").inc()
                return
            self._spawn_conn(conn)

    def _spawn_conn(self, conn) -> None:
        """Hand ``conn``'s ownership to its serve thread (which closes
        it on every exit path)."""
        threading.Thread(
            target=self._serve, args=(conn,),
            name="fabric-exchange-conn", daemon=True,
        ).start()

    def _serve(self, conn) -> None:
        try:
            conn.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
            while not self._stop.is_set():
                try:
                    ftype, payload = read_frame(
                        conn, max_frame=self._max_frame)
                    if ftype != T_XREQ:
                        raise MalformedFrame(
                            "type", f"unexpected frame type {ftype}")
                    resp = self._handle(payload)
                except Disconnect:
                    return
                except MalformedFrame as e:
                    get_registry().counter(
                        "fabric.malformed", kind=e.kind).inc()
                    return
                try:
                    conn.sendall(pack_frame(T_XRESP, resp))
                except OSError:
                    get_registry().counter(
                        "fabric.swallowed", site="daemon_send").inc()
                    return
        except Exception:
            # a handler-thread death must leave evidence (the GL003
            # threaded-socket bar): count, then let the thread end
            get_registry().counter(
                "fabric.swallowed", site="daemon_conn").inc()
        finally:
            try:
                conn.close()
            except OSError:
                get_registry().counter(
                    "fabric.swallowed", site="daemon_close").inc()

    def _handle(self, payload: bytes) -> bytes:
        op, tag, overwrite, prefix, body = unpack_request(payload)
        with self._lock:
            if op == "put":
                if overwrite or tag not in self._store:
                    self._store[tag] = (body, self._next_version)
                    self._next_version += 1
                    return pack_response(created=True)
                return pack_response(created=False)
            if op == "get":
                hit = self._store.get(tag)
                if hit is None:
                    return pack_response(found=False)
                return pack_response(found=True, size=len(hit[0]),
                                     version=hit[1], body=hit[0])
            if op == "stat":
                hit = self._store.get(tag)
                if hit is None:
                    return pack_response(found=False)
                return pack_response(found=True, size=len(hit[0]),
                                     version=hit[1])
            if op == "list":
                tags = tuple(sorted(
                    t for t in self._store if t.startswith(prefix)))
                return pack_response(found=True, tags=tags)
            if op == "delete":
                return pack_response(
                    found=self._store.pop(tag, None) is not None)
            return pack_response(found=True)  # ping

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            get_registry().counter(
                "fabric.swallowed", site="daemon_stop").inc()
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)


class SocketTransport(Transport):
    """Tag store over one exchange daemon; see the module docstring.

    ``persistent`` is True in the sense the coordinated layer needs —
    tags survive WORKER kills and restarts (the daemon owns them) —
    but not a daemon death; durable restore state belongs on a
    shared-dir store.
    """

    backend = "socket"
    persistent = True

    #: reconnect attempts per request before the fault is the caller's
    MAX_ATTEMPTS = 5
    #: backoff start/cap between reconnect attempts
    BACKOFF_S = (0.02, 0.5)

    def __init__(self, address, process_id: int = 0,
                 num_processes: int = 1, *, timeout_s: float = 60.0,
                 poll_s: float = 0.002,
                 max_frame: int = DEFAULT_MAX_FRAME):
        if isinstance(address, str):
            host, _, port = address.rpartition(":")
            address = (host or "127.0.0.1", int(port))
        self.address = (str(address[0]), int(address[1]))
        self.process_id = int(process_id)
        self.num_processes = int(num_processes)
        self.timeout_s = float(timeout_s)
        self.poll_s = float(poll_s)
        self._max_frame = int(max_frame)
        self._sock = None
        self._lock = threading.Lock()

    # ---------------------------------------------------------------- #
    def _connected(self):
        if self._sock is None:
            s = _socket.create_connection(self.address, timeout=30.0)
            try:
                s.setsockopt(_socket.IPPROTO_TCP,
                             _socket.TCP_NODELAY, 1)
            except OSError:
                # a daemon that reset immediately: drop THIS socket,
                # let the reconnect loop classify the failure (GL010)
                s.close()
                raise
            self._sock = s
        return self._sock

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                get_registry().counter(
                    "fabric.swallowed", site="client_close").inc()
            self._sock = None

    def _request(self, req: bytes) -> ExchangeResponse:
        """One round-trip, with the serving client's bounded-reconnect
        discipline: a dropped/garbled connection is counted
        (``fabric.reconnects`` / ``fabric.malformed{kind}``), backed
        off, and retried a bounded number of times before the fault
        escalates as transient."""
        frame = pack_frame(T_XREQ, req)
        backoff, cap = self.BACKOFF_S
        last = "unreachable"
        for attempt in range(self.MAX_ATTEMPTS):
            with self._lock:
                try:
                    sock = self._connected()  # graftlint: disable=GL009 (the lock is the per-connection request serializer; a request IS connect+send+recv, and the next request must wait for this one's response frame)
                    sock.sendall(frame)  # graftlint: disable=GL009 (same: the lock serializes whole round-trips on the one socket)
                    ftype, payload = read_frame(  # graftlint: disable=GL009 (same: the response read completes the serialized round-trip)
                        sock, max_frame=self._max_frame)
                    if ftype != T_XRESP:
                        raise MalformedFrame(
                            "type", f"unexpected frame type {ftype}")
                    return unpack_response(payload)
                except MalformedFrame as e:
                    get_registry().counter(
                        "fabric.malformed", kind=e.kind).inc()
                    last = f"malformed:{e.kind}"
                    self._drop()
                except (OSError, Disconnect) as e:
                    get_registry().counter("fabric.reconnects").inc()
                    last = repr(e)
                    self._drop()
            if attempt + 1 < self.MAX_ATTEMPTS:
                time.sleep(backoff)
                backoff = min(cap, backoff * 2)
        raise TransientSourceError(
            f"exchange daemon {self.address[0]}:{self.address[1]} "
            f"unreachable after {self.MAX_ATTEMPTS} attempts ({last})"
        )

    # ---------------------------------------------------------------- #
    # The byte layer
    # ---------------------------------------------------------------- #
    def put(self, tag: str, payload: bytes, *,
            overwrite: bool = False) -> bool:
        resp = self._request(pack_request(
            "put", tag, overwrite=overwrite, body=payload))
        return resp.created

    def _get_once(self, tag: str) -> Optional[bytes]:
        resp = self._request(pack_request("get", tag))
        return resp.body if resp.found else None

    def stat(self, tag: str) -> Optional[TagStat]:
        resp = self._request(pack_request("stat", tag))
        if not resp.found:
            return None
        return TagStat(size=resp.size, version=resp.version)

    def list(self, prefix: str = "") -> List[str]:
        return self._request(pack_request("list", prefix=prefix)).tags

    def delete(self, tag: str) -> bool:
        return self._request(pack_request("delete", tag)).found

    def close(self) -> None:
        with self._lock:
            self._drop()
