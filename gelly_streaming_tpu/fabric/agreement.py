"""Multi-host cadence agreement: one elected K per epoch.

PR 15's control plane made superbatch K a LEARNED quantity — and the
coordinated layer rejected it, because each process learning its own K
tiles its windows differently and nothing guaranteed the barriers'
window ordinals still lined up. The fix is not to synchronize the
learners; it is to make the OPERATING K an agreed value: at each epoch
boundary every process proposes its locally-learned K under one
election tag and the transport's
:meth:`~gelly_streaming_tpu.fabric.base.Transport.elect` picks exactly
one winner for everyone.

WHERE the election runs matters. The drive loop prefetches groups on a
background thread (``prefetch_groups``), so the packer samples its
``k_fn`` at wall-clock times unrelated to the commit loop — any scheme
that swaps K "at commit time" hands different processes different K
for the same group and the tilings diverge. Instead :class:`ElectedK`
is driven entirely FROM the packer's own call sequence: the dynamic
packer calls ``current_k()`` exactly once per group it forms, so the
adapter can replicate the run loop's barrier rule purely from its call
history — it tracks the window ordinal where the next group starts
(``_index``) and opens a new epoch the first time a group starts
``every`` or more windows past the previous epoch's start, exactly
where ``AutoCheckpoint.run`` will land the barrier (``due`` fires at
the first group END at least ``every`` windows past the last barrier;
that end is this group's start). Every process replays the same rule
over the same agreed K sequence, so epoch boundaries — hence election
tags, hence winners — agree by induction, with no clock anywhere.

Election tags live in the ABSOLUTE window ordinal namespace
(``cadence.e{origin + index}``), so a process restored from epoch N
re-elects under the same tags the pre-kill run persisted: ``elect`` is
put-if-absent + read (replay-safe by the transport contract), so the
replay adopts the recorded winners and tiles forward exactly as the
survivors did. Value identity needs nothing more — the group-fold
contract guarantees emissions identical to the per-window path for ANY
tiling, so the tiling only has to agree ACROSS PROCESSES.

Caveat (documented, not load-bearing today): streams without a native
``superbatches_dynamic`` go through a generic fallback that probes
``k_fn`` ONE extra time for the prefetch depth. The probe pattern is
the same code path on every process, so agreement still holds, but the
tags shift off the true barrier ordinals by one phantom group. The
coordinated path streams (``SimpleEdgeStream``, ``_SkipStream``) all
take the native path.
"""

from __future__ import annotations

from ..obs import trace as _trace
from ..obs.registry import get_registry
from .base import Transport


class ElectedK:
    """The agreed-K controller adapter; see the module docstring.

    ``inner`` is the local learner (an
    :class:`~gelly_streaming_tpu.control.AutoK`) — it keeps learning
    from its own taps, so its proposals improve even while losing
    elections. Unknown attributes delegate to it so controller
    introspection (``k_max``, history) keeps working through the
    wrapper. ``every`` is the coordinated barrier cadence, ``done`` the
    restore epoch (both fixed integers on the coordinated path).
    """

    def __init__(self, inner, transport: Transport, *, every: int,
                 done: int = 0, tag_prefix: str = "cadence"):
        self.inner = inner
        self.transport = transport
        self.tag_prefix = str(tag_prefix)
        self._every = max(1, int(every))
        self._origin = int(done)  # absolute ordinal of window _index 0
        self._index = 0           # window ordinal where the next group starts
        self._seg = 0             # window ordinal where this epoch started
        self._won = {}            # epoch-start ordinal -> agreed K
        # persist the restore epoch's winner up front: k_agreed is live
        # before the packer's first call, and on the collective backend
        # every rank enters this election at the same program point
        self.k_agreed = self._k_for(0)

    def _k_for(self, seg: int) -> int:
        """The agreed K for the epoch starting at relative ordinal
        ``seg`` — elected once, then replayed from the memo (and, across
        restarts, from the transport's persisted winner)."""
        k = self._won.get(seg)
        if k is None:
            tag = f"{self.tag_prefix}.e{self._origin + seg:08d}"
            proposal = max(1, int(self.inner.current_k()))
            k = max(1, int(self.transport.elect(tag, proposal)))
            self._won[seg] = k
            if _trace.on():
                get_registry().counter(
                    "fabric.agree", backend=self.transport.backend,
                    epoch=self._origin + seg, k=k,
                ).inc()
        return k

    # ------------------------------------------------------------ #
    # The controller surface the drive loop consumes
    # ------------------------------------------------------------ #
    def current_k(self) -> int:
        """One call per group formed (the dynamic packer's contract):
        replicate the barrier rule from the call history, return the
        agreed K of the epoch this group belongs to."""
        if self._index - self._seg >= self._every:
            self._seg = self._index
        k = self._k_for(self._seg)
        self._index += k
        self.k_agreed = k
        return k

    def tap_group(self, n_windows: int, n_edges: int,
                  wall_s: float) -> int:
        """Feed the local learner (its proposals keep improving) but
        hold the operating point at the agreed K until the next
        epoch's election."""
        self.inner.tap_group(n_windows, n_edges, wall_s)
        return self.k_agreed

    def __getattr__(self, name: str):
        return getattr(self.inner, name)
