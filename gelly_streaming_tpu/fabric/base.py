"""The ``Transport`` contract: one exchange interface under every seam.

PAPER.md §1/§5 names the layer the reference gets for free from Flink —
the JVM/Netty network stack under ``keyBy``/``broadcast`` — and names
XLA collectives as its TPU-native equivalent. The repo grew four
cross-process seams before this module (coordinated epoch barriers,
the dict-exchange allgather, snapshot mirroring, heartbeat leases) and
each privately assumed a shared filesystem. This is the one interface
they all route through instead, with three backends:

- :class:`~gelly_streaming_tpu.fabric.shared_dir.SharedDirTransport` —
  today's semantics (tag = file under a shared directory), extracted.
- :class:`~gelly_streaming_tpu.fabric.exchange.SocketTransport` — GSRP
  frames against a tiny stdlib exchange daemon; the object-store-shaped
  backend for standbys/shards on separate machines.
- :class:`~gelly_streaming_tpu.fabric.collective.CollectiveTransport` —
  ``multihost_utils.process_allgather`` over a live ``jax.distributed``
  runtime (group primitives only; there is no store to put into).

The contract, in the recovery-safe terms the coordinated layer needs:

- **Tag store**: :meth:`~Transport.put` / :meth:`~Transport.get` /
  :meth:`~Transport.stat` / :meth:`~Transport.list` /
  :meth:`~Transport.delete` move raw bytes by string tag. A put is
  ATOMIC (a reader sees the previous value or the new one, never a
  torn middle) and ``put(overwrite=False)`` is ONE-WINNER (exactly one
  concurrent writer returns True; everyone else observes the winner's
  fully-written value).
- **Replay safety**: tags persist for the transport's lifetime (the
  ``persistent`` attribute — True when they also survive process
  restarts), so a process replaying work after a restore re-reads what
  its peers published BEFORE the failure instead of re-running their
  side of old exchanges.
- **Idempotence**: re-publishing a tag that exists is a no-op skip
  (proposals are pure functions of their inputs, so a replayed publish
  would be byte-identical anyway).
- **Group primitives** (:meth:`~Transport.allgather`,
  :meth:`~Transport.broadcast`, :meth:`~Transport.barrier`,
  :meth:`~Transport.elect`) are derived from the store by default —
  an allgather is N idempotent puts plus N polled gets — so a backend
  only implements the byte layer; the collective backend overrides the
  group layer natively instead.
- **Framed payloads**: :meth:`~Transport.put_framed` /
  :meth:`~Transport.get_framed` wrap the bytes in the repo's CRC
  container (``resilience/integrity.py``); a torn or corrupted payload
  is a counted :func:`~gelly_streaming_tpu.resilience.integrity.record_rejection`,
  never a silently-wrong read.

:meth:`~Transport.elect` is the agreement primitive the cadence layer
rides: every participant proposes a value under one tag, exactly one
proposal wins (the store's one-winner put), and every participant —
including one replaying after a restart — reads the SAME winner back.
"""

from __future__ import annotations

import abc
import io
import pickle
import time
from typing import List, NamedTuple, Optional

import numpy as np

from ..obs import trace as _trace
from ..obs.registry import get_registry
from ..resilience.errors import CheckpointCorrupt, TransientSourceError
from ..resilience.integrity import (
    record_rejection,
    unwrap_checksummed,
    wrap_checksummed,
)


class TagStat(NamedTuple):
    """Store metadata for one tag: payload size and a version that
    changes whenever the value does (backends choose the clock —
    mtime_ns for files, a put counter for the daemon)."""

    size: int
    version: int


class TransportUnsupported(RuntimeError):
    """The backend cannot provide this primitive (the collective
    transport has no tag store) — callers that need it must pick a
    store-backed transport."""


class Transport(abc.ABC):
    """One cluster exchange handle; see the module docstring for the
    contract. ``process_id``/``num_processes`` scope the group
    primitives; ``timeout_s``/``poll_s`` bound every wait."""

    #: backend label on counters/timeline lines
    backend: str = "abstract"
    #: tags survive process restarts (shared-dir: yes; the socket
    #: daemon: only as long as the daemon itself; collective: no store)
    persistent: bool = True

    process_id: int = 0
    num_processes: int = 1
    timeout_s: float = 60.0
    poll_s: float = 0.002

    # ---------------------------------------------------------------- #
    # The byte layer (backend-provided)
    # ---------------------------------------------------------------- #
    @abc.abstractmethod
    def put(self, tag: str, payload: bytes, *,
            overwrite: bool = False) -> bool:
        """Publish ``payload`` under ``tag`` atomically. Returns True
        when this call created/replaced the value; with
        ``overwrite=False`` a tag that already exists is left untouched
        and the call returns False (the one-winner primitive)."""

    @abc.abstractmethod
    def _get_once(self, tag: str) -> Optional[bytes]:
        """One non-blocking read: the full payload, or None when the
        tag does not exist (yet)."""

    @abc.abstractmethod
    def stat(self, tag: str) -> Optional[TagStat]:
        """Size + version of a tag, None when absent."""

    @abc.abstractmethod
    def list(self, prefix: str = "") -> List[str]:
        """Sorted tags starting with ``prefix`` (in-flight temp
        artifacts excluded)."""

    @abc.abstractmethod
    def delete(self, tag: str) -> bool:
        """Remove a tag; True when it existed."""

    def describe(self, tag: str) -> str:
        """A human-facing locator for ``tag`` — what rejection records
        and return values name as "the artifact". The shared-dir
        backend returns the real filesystem path (the historical
        surface every recovery test and operator runbook knows); other
        backends return ``backend:tag``."""
        return f"{self.backend}:{tag}"

    # ---------------------------------------------------------------- #
    # Waiting reads + framed payloads (shared)
    # ---------------------------------------------------------------- #
    def get(self, tag: str, *, timeout_s: float = 0.0
            ) -> Optional[bytes]:
        """Read a tag's payload, polling up to ``timeout_s`` for it to
        appear; None when still absent at the deadline."""
        deadline = time.monotonic() + float(timeout_s)
        while True:
            data = self._get_once(tag)
            if data is not None:
                return data
            if time.monotonic() >= deadline:
                return None
            time.sleep(self.poll_s)

    def put_framed(self, tag: str, payload: bytes, *,
                   overwrite: bool = False) -> bool:
        """``put`` with the CRC container around the payload."""
        return self.put(tag, wrap_checksummed(payload),
                        overwrite=overwrite)

    def get_framed(self, tag: str, *, timeout_s: float = 0.0
                   ) -> Optional[bytes]:
        """``get`` + CRC validation. A present-but-corrupt payload is
        RECORDED (``resilience.ckpt_rejected``) and read as absent —
        the caller's retry/fallback logic sees one consistent shape."""
        data = self.get(tag, timeout_s=timeout_s)
        if data is None:
            return None
        try:
            return unwrap_checksummed(data, origin=self.describe(tag))
        except CheckpointCorrupt as e:
            record_rejection(self.describe(tag), str(e))
            return None

    # ---------------------------------------------------------------- #
    # Group primitives (store-derived defaults)
    # ---------------------------------------------------------------- #
    def _member_tag(self, tag: str, rank: int) -> str:
        # the legacy exchange layout: <tag>.p<rank>.npy — kept
        # byte-identical so shared-dir runs written before the fabric
        # existed replay through it unchanged
        return f"{tag}.p{rank}.npy"

    def allgather(self, tag: str, arr: np.ndarray) -> list:
        """Every rank publishes its array under ``tag``; returns all
        ranks' arrays in rank order. Publication is idempotent (replay
        re-reads, never re-writes); a peer that never publishes fails
        the exchange with
        :class:`~gelly_streaming_tpu.resilience.errors.TransientSourceError`
        after ``timeout_s`` — the supervisor classifies that transient
        and restarts the cluster from the agreed epoch."""
        arr = np.asarray(arr)
        buf = io.BytesIO()
        np.save(buf, arr)
        self.put(self._member_tag(tag, self.process_id), buf.getvalue())
        if _trace.on():
            get_registry().counter(
                "fabric.exchange", backend=self.backend, tag=tag,
            ).inc()
        deadline = time.monotonic() + self.timeout_s
        out = []
        for rank in range(self.num_processes):
            member = self._member_tag(tag, rank)
            while True:
                data = self._get_once(member)
                if data is not None:
                    try:
                        out.append(np.load(io.BytesIO(data),
                                           allow_pickle=False))
                        break
                    except ValueError:
                        # a torn publish from a non-atomic writer:
                        # treat as not-yet-published and keep polling
                        data = None
                if time.monotonic() >= deadline:
                    raise TransientSourceError(
                        f"exchange {tag!r}: rank {rank} never "
                        f"published within {self.timeout_s}s"
                    )
                time.sleep(self.poll_s)
        return out

    def broadcast(self, tag: str, payload: Optional[bytes] = None, *,
                  root: int = 0) -> bytes:
        """Root publishes ``payload`` (CRC-framed) under ``tag``; every
        rank returns the root's bytes."""
        member = f"{tag}.b{int(root)}"
        if self.process_id == int(root) and payload is not None:
            self.put_framed(member, payload)
        data = self.get_framed(member, timeout_s=self.timeout_s)
        if data is None:
            raise TransientSourceError(
                f"broadcast {tag!r}: root {root} never published "
                f"within {self.timeout_s}s"
            )
        return data

    def barrier(self, tag: str) -> None:
        """All ranks reach ``tag`` before any returns — a zero-payload
        allgather, so it inherits the replay/timeout discipline."""
        self.allgather(tag, np.zeros(1, np.int8))

    def elect(self, tag: str, value):
        """One-winner agreement: every participant proposes ``value``
        under ``tag``; the store's one-winner put picks EXACTLY one
        proposal and every participant returns the winner's value —
        including a participant replaying after a restart, which finds
        the persisted winner and re-reads it (never re-votes). The
        winner's payload rides the CRC container; a corrupted winner is
        recorded and raised, never silently mis-read."""
        blob = wrap_checksummed(pickle.dumps(value, protocol=4))
        won = self.put(tag, blob)
        if _trace.on():
            get_registry().counter(
                "fabric.elect", backend=self.backend, tag=tag,
                won=str(bool(won)).lower(),
            ).inc()
        data = self.get(tag, timeout_s=self.timeout_s)
        if data is None:
            raise TransientSourceError(
                f"elect {tag!r}: no winner within {self.timeout_s}s"
            )
        try:
            payload = unwrap_checksummed(data, origin=f"elect:{tag}")
        except CheckpointCorrupt as e:
            record_rejection(f"{self.backend}:{tag}", str(e))
            raise
        return pickle.loads(payload)
