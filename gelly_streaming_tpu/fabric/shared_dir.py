"""Shared-directory transport: today's semantics, extracted.

Every pre-fabric seam (``FileExchangeTransport`` allgathers, the
coordinated layer's ``.ckpt``/``.json`` rendezvous records, mirrored
snapshots, heartbeat leases) was a hand-rolled variation of the same
three moves on a shared filesystem: write a temp name, commit with an
atomic rename, poll for peers' files. This backend IS those moves —
tag ↔ ``<root>/<tag>``, bytes verbatim — so the file layouts the repo's
recovery tests inspect and corrupt on disk stay byte-identical, while
every caller now goes through the :class:`~gelly_streaming_tpu.fabric.base.Transport`
interface instead of touching the directory itself.

The one-winner ``put(overwrite=False)`` is the part that needs care: an
exists-check + rename has a two-writer race, and ``open(path, "xb")``
exposes a torn file under the final name if the writer dies mid-write.
``os.link`` of a FULLY-WRITTEN temp file gives both properties at once
— the link either lands (this writer won, and the visible bytes are
complete by construction) or raises ``FileExistsError`` (a peer won
first); there is no state in between.
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional

from .base import TagStat, Transport


class SharedDirTransport(Transport):
    """Tag store over one shared directory; see the module docstring.
    ``process_id``/``num_processes`` scope the inherited group
    primitives — a pure store user (snapshot mirror, lease) leaves the
    defaults."""

    backend = "shared_dir"
    persistent = True

    def __init__(self, root: str, process_id: int = 0,
                 num_processes: int = 1, *, timeout_s: float = 60.0,
                 poll_s: float = 0.002):
        self.root = root
        self.process_id = int(process_id)
        self.num_processes = int(num_processes)
        self.timeout_s = float(timeout_s)
        self.poll_s = float(poll_s)

    def _path(self, tag: str) -> str:
        return os.path.join(self.root, tag)

    def describe(self, tag: str) -> str:
        return self._path(tag)

    def _tmp(self, path: str) -> str:
        # unique per writer THREAD, not just per process: in-process
        # cluster harnesses run one rank per thread, and an election
        # has every rank writing a temp for the SAME tag concurrently
        return f"{path}.tmp{os.getpid()}.{threading.get_ident()}"

    def put(self, tag: str, payload: bytes, *,
            overwrite: bool = False) -> bool:
        # created on first WRITE, not in the constructor: read-side
        # coercions (a lease probe on a directory that may not exist
        # yet) must stay side-effect free
        os.makedirs(self.root, exist_ok=True)
        path = self._path(tag)
        if not overwrite and os.path.exists(path):
            return False
        tmp = self._tmp(path)
        with open(tmp, "wb") as f:
            f.write(payload)
        if overwrite:
            os.replace(tmp, path)
            return True
        try:
            os.link(tmp, path)
            return True
        except FileExistsError:
            return False
        finally:
            try:
                os.unlink(tmp)
            except FileNotFoundError:
                pass

    def _get_once(self, tag: str) -> Optional[bytes]:
        try:
            with open(self._path(tag), "rb") as f:
                return f.read()
        except (FileNotFoundError, IsADirectoryError):
            return None

    def stat(self, tag: str) -> Optional[TagStat]:
        try:
            st = os.stat(self._path(tag))
        except FileNotFoundError:
            return None
        return TagStat(size=int(st.st_size), version=int(st.st_mtime_ns))

    def list(self, prefix: str = "") -> List[str]:
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return []
        return sorted(
            n for n in names
            if n.startswith(prefix) and ".tmp" not in n
            and not os.path.isdir(os.path.join(self.root, n))
        )

    def delete(self, tag: str) -> bool:
        try:
            os.unlink(self._path(tag))
            return True
        except FileNotFoundError:
            return False
