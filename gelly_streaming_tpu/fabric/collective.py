"""Collective-backed transport: XLA collectives over the live runtime.

When ``jax.distributed`` is initialized (a TPU pod, or multi-process
CPU where the backend implements cross-process collectives), the
runtime's own allgather IS the exchange layer — ICI within a slice,
DCN across slices, no daemon and no shared directory. This backend
generalizes the old ``JaxAllgatherTransport``: the group primitives
(:meth:`~CollectiveTransport.allgather`, barrier, broadcast, elect)
ride ``multihost_utils.process_allgather``; the tag STORE does not
exist (``persistent = False`` — there is nothing to replay from), so
:meth:`~CollectiveTransport.put`/``get`` raise
:class:`~gelly_streaming_tpu.fabric.base.TransportUnsupported` and
store-shaped consumers (snapshot mirrors, rendezvous records) must
pick a store-backed transport.

Elections still hold their determinism contract WITHIN a process:
every ranks' proposals are gathered, the lowest rank's proposal wins
(a pure function of the gathered set), and the winner is cached per
tag so a replayed ``elect`` on this process returns the same value
without re-entering the collective — the property the cadence
agreement layer needs when a drive loop replays windows after an
in-process restore.

Capability is an ENVIRONMENT property (the CPU backend may implement
no cross-process collectives at all); tests probe it the way
``tests/test_multiprocess.py`` does and skip when absent.
"""

from __future__ import annotations

import pickle
from typing import List, Optional

import numpy as np

from ..obs import trace as _trace
from ..obs.registry import get_registry
from .base import TagStat, Transport, TransportUnsupported


class CollectiveTransport(Transport):
    """Group primitives over ``jax.distributed``; no tag store. Rank
    and group size come from the live runtime, read lazily so the
    transport can be constructed before ``initialize``."""

    backend = "collective"
    persistent = False

    def __init__(self, *, timeout_s: float = 60.0):
        self.timeout_s = float(timeout_s)
        self._elected = {}  # tag -> winning value (replay cache)

    @property
    def process_id(self) -> int:  # type: ignore[override]
        import jax

        return int(jax.process_index())

    @property
    def num_processes(self) -> int:  # type: ignore[override]
        import jax

        return int(jax.process_count())

    # ---------------------------------------------------------------- #
    # Group primitives (native)
    # ---------------------------------------------------------------- #
    def allgather(self, tag: str, arr: np.ndarray) -> list:
        """``multihost_utils.process_allgather`` — tags are ignored;
        the runtime's collective ordering IS the alignment."""
        from jax.experimental import multihost_utils

        arr = np.asarray(arr)
        if _trace.on():
            get_registry().counter(
                "fabric.exchange", backend=self.backend, tag=tag,
            ).inc()
        out = np.asarray(multihost_utils.process_allgather(arr))
        return list(out.reshape((-1,) + arr.shape))

    def barrier(self, tag: str) -> None:
        self.allgather(tag, np.zeros(1, np.int8))

    def broadcast(self, tag: str, payload: Optional[bytes] = None, *,
                  root: int = 0) -> bytes:
        gathered = self._gather_blobs(
            tag, payload if payload is not None else b"")
        return gathered[int(root)]

    def elect(self, tag: str, value):
        """Lowest-rank proposal wins; cached per tag so an in-process
        replay re-reads this process's recorded winner instead of
        re-entering the collective (peers are not replaying with us)."""
        if tag in self._elected:
            return self._elected[tag]
        blobs = self._gather_blobs(tag, pickle.dumps(value, protocol=4))
        winner = pickle.loads(blobs[0])
        if _trace.on():
            get_registry().counter(
                "fabric.elect", backend=self.backend, tag=tag,
                won=str(self.process_id == 0).lower(),
            ).inc()
        self._elected[tag] = winner
        return winner

    def _gather_blobs(self, tag: str, blob: bytes) -> List[bytes]:
        """Allgather variable-length byte strings: lengths first, then
        one shared-capacity uint8 plane per rank."""
        lengths = np.concatenate([
            np.asarray(n).reshape(-1)
            for n in self.allgather(
                tag + ".len", np.array([len(blob)], np.int32))
        ])
        cap = max(1, int(lengths.max()))
        padded = np.zeros(cap, np.uint8)
        padded[: len(blob)] = np.frombuffer(blob, np.uint8)
        planes = self.allgather(tag + ".bytes", padded)
        return [
            np.asarray(p)[: int(lengths[i])].tobytes()
            for i, p in enumerate(planes)
        ]

    # ---------------------------------------------------------------- #
    # No tag store
    # ---------------------------------------------------------------- #
    def put(self, tag: str, payload: bytes, *,
            overwrite: bool = False) -> bool:
        raise TransportUnsupported(
            "collective transport has no tag store: put() needs a "
            "shared-dir or socket transport")

    def _get_once(self, tag: str) -> Optional[bytes]:
        raise TransportUnsupported(
            "collective transport has no tag store: get() needs a "
            "shared-dir or socket transport")

    def stat(self, tag: str) -> Optional[TagStat]:
        raise TransportUnsupported(
            "collective transport has no tag store: stat() needs a "
            "shared-dir or socket transport")

    def list(self, prefix: str = "") -> List[str]:
        raise TransportUnsupported(
            "collective transport has no tag store: list() needs a "
            "shared-dir or socket transport")

    def delete(self, tag: str) -> bool:
        raise TransportUnsupported(
            "collective transport has no tag store: delete() needs a "
            "shared-dir or socket transport")
