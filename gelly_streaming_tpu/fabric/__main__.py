"""Fabric CLI: the exchange daemon and the 2-process smoke.

``python -m gelly_streaming_tpu.fabric --daemon [--host H] [--port N]``
runs :class:`~gelly_streaming_tpu.fabric.exchange.ExchangeDaemon` in
the foreground (prints ``host:port`` on stdout, serves until killed).

``python -m gelly_streaming_tpu.fabric --smoke`` is the CI gate: for
each locally-runnable backend (shared-dir, socket) it spawns TWO real
subprocesses that allgather, elect one winner, cross a barrier, and
exchange tagged payloads — then asserts both processes agreed. Exit 0
and a JSON verdict on stdout when every backend passes.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np


def _worker(backend: str, target: str, pid: int, nprocs: int) -> int:
    """One smoke participant (run as a subprocess)."""
    from . import SharedDirTransport, SocketTransport

    if backend == "socket":
        tr = SocketTransport(target, pid, nprocs, timeout_s=30.0)
    else:
        tr = SharedDirTransport(target, pid, nprocs, timeout_s=30.0)
    gathered = tr.allgather("smoke.ag", np.array([pid], np.int32))
    k = tr.elect("smoke.k", 10 + pid)
    k_replay = tr.elect("smoke.k", 99)  # replay must re-read, not re-vote
    tr.barrier("smoke.bar")
    tr.put(f"smoke.tag.p{pid}", f"payload-{pid}".encode())
    peers = [
        tr.get(f"smoke.tag.p{r}", timeout_s=30.0)
        for r in range(nprocs)
    ]
    print(json.dumps({
        "pid": pid,
        "gathered": [int(np.asarray(g).reshape(-1)[0]) for g in gathered],
        "k": int(k),
        "k_replay": int(k_replay),
        "peers": [p.decode() if p is not None else None for p in peers],
    }))
    return 0


def _spawn_workers(backend: str, target: str, nprocs: int) -> list:
    procs = []
    outs = []
    try:
        for i in range(nprocs):
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "gelly_streaming_tpu.fabric",
                 "--worker", backend, target, str(i), str(nprocs)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
            ))
        for p in procs:
            try:
                out, err = p.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                raise SystemExit(
                    f"smoke[{backend}]: worker timed out")
            outs.append((p.returncode, out, err))
    finally:
        # every edge (a failed spawn, the timeout, a signal) reaps the
        # whole pack — no orphaned workers, no leaked pipe fds
        for q in procs:
            if q.poll() is None:
                q.kill()
                q.communicate()
    return outs


def _check(backend: str, outs: list, nprocs: int) -> dict:
    docs = []
    for rc, out, err in outs:
        if rc != 0:
            raise SystemExit(
                f"smoke[{backend}]: worker rc={rc}\n{err[-2000:]}")
        docs.append(json.loads(out.strip().splitlines()[-1]))
    ks = {d["k"] for d in docs} | {d["k_replay"] for d in docs}
    want_g = list(range(nprocs))
    want_p = [f"payload-{r}" for r in range(nprocs)]
    ok = (
        len(ks) == 1
        and ks.issubset({10 + r for r in range(nprocs)})
        and all(d["gathered"] == want_g for d in docs)
        and all(d["peers"] == want_p for d in docs)
    )
    if not ok:
        raise SystemExit(f"smoke[{backend}]: disagreement: {docs}")
    return {"ok": True, "elected_k": ks.pop(), "processes": nprocs}


def _smoke() -> int:
    from .exchange import ExchangeDaemon

    nprocs = 2
    verdict = {}
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory(prefix="fabric-smoke-") as root:
        verdict["shared_dir"] = _check(
            "shared_dir", _spawn_workers("shared_dir", root, nprocs),
            nprocs)
    daemon = ExchangeDaemon().start()
    try:
        verdict["socket"] = _check(
            "socket", _spawn_workers("socket", daemon.address, nprocs),
            nprocs)
    finally:
        daemon.stop()
    verdict["wall_s"] = round(time.perf_counter() - t0, 3)
    print(json.dumps({"smoke": verdict}, indent=2))
    return 0


def _daemon(argv: list) -> int:
    from .exchange import ExchangeDaemon

    host, port = "127.0.0.1", 0
    if "--host" in argv:
        host = argv[argv.index("--host") + 1]
    if "--port" in argv:
        port = int(argv[argv.index("--port") + 1])
    daemon = ExchangeDaemon(host, port).start()
    print(daemon.address, flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        daemon.stop()
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--worker" in argv:
        i = argv.index("--worker")
        backend, target, pid, nprocs = argv[i + 1:i + 5]
        return _worker(backend, target, int(pid), int(nprocs))
    if "--smoke" in argv:
        return _smoke()
    if "--daemon" in argv:
        return _daemon(argv)
    print(
        "usage: python -m gelly_streaming_tpu.fabric "
        "(--smoke | --daemon [--host H] [--port N])",
        file=sys.stderr,
    )
    return 2


if __name__ == "__main__":
    sys.exit(main())
