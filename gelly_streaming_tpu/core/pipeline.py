"""Host/device overlap: background block prefetch.

SURVEY.md §7 lists host↔device overlap as where p50 window latency is won:
while the device computes window N, the host should already be parsing,
bucketing, and padding window N+1. :func:`prefetch` runs any block (or
emission) iterator on a daemon thread with a small bounded queue — the
moral equivalent of Flink's pipelined exchanges between the source and the
first keyed operator.

Usage::

    stream = SimpleEdgeStream(..., window=CountWindow(1 << 20))
    for comps in agg.run(stream.prefetched()):   # or prefetch(iterator)
        ...

Exceptions raised by the producer are re-raised at the consumer's next
pull, after the already-queued items drain.
"""

from __future__ import annotations

import queue
import threading
import time
import warnings
from typing import Any, Iterator, Optional, TypeVar

from ..obs import trace as _trace
from ..obs.registry import get_registry
from ..resilience import faults as _faults
from ..resilience.errors import StallError

T = TypeVar("T")

_SENTINEL = object()


def superbatch_prefetch_depth(superbatch: int, base: int = 2) -> int:
    """Prefetch depth matched to a superbatch of K windows.

    The engine's superbatch path (``SummaryAggregation._superbatch_step``)
    consumes K blocks per dispatch, so a depth-2 queue — sized for the
    per-window cadence — would stall the device scan while the host
    assembles most of the next group. Covering a full group plus one
    window (``K + 1``) lets the host windower run a whole superbatch
    ahead: it assembles group N+1 while the device scans group N, the
    superbatch analog of the per-window double buffer. Memory cost is
    the queued blocks themselves (~K x window bytes), which is the same
    data the stacked block materializes anyway.
    """
    return max(int(base), int(superbatch) + 1)


def bounded_put(q: "queue.Queue", item: Any, stop: threading.Event, *,
                timeout: float = 0.1,
                on_wait: Optional[Any] = None,
                on_done: Optional[Any] = None) -> bool:
    """Put ``item`` on a bounded queue, polling ``stop`` between
    attempts — the backpressure primitive shared by :func:`prefetch`'s
    producer and the sharded ingest readers
    (:class:`~gelly_streaming_tpu.core.ingest.ShardedEdgeSource`): a
    FULL queue blocks the producer right here, which for a socket
    reader means ``recv`` stops and TCP flow control pushes back on the
    peer — overload degrades to bounded staleness, never unbounded
    buffering.

    ``on_wait(waited_s)`` fires after each full-queue timeout slice
    with the cumulative approximate wait (stall detection without extra
    clock reads on the put fast path); ``on_done(waited_s)`` fires once
    after a successful put. Returns False when ``stop`` was set before
    the item could be enqueued (the consumer is gone)."""
    waited = 0.0
    while not stop.is_set():
        try:
            q.put(item, timeout=timeout)
        except queue.Full:
            waited += timeout
            if on_wait is not None:
                on_wait(waited)
            continue
        if on_done is not None:
            on_done(waited)
        return True
    return False


def prefetch(iterator: Iterator[T], depth: int = 2,
             name: str = "pipeline", *,
             stall_timeout_s: Optional[float] = None,
             join_timeout_s: float = 10.0,
             tuner=None) -> Iterator[T]:
    """Iterate ``iterator`` on a background thread, ``depth`` items ahead.

    If the consumer abandons the generator early (break / exception /
    garbage collection), the producer thread notices via a stop flag and
    exits instead of blocking forever on the bounded queue; the source
    iterator is closed so file handles are released. If the producer
    does NOT exit within ``join_timeout_s`` (wedged in a device op or a
    blocking read), the leak is no longer silent: a warning fires and
    ``<name>.producer_leaked`` increments in the obs registry.

    ``stall_timeout_s`` arms a consumer-side stall watchdog: when the
    queue stays empty that long, a
    :class:`~gelly_streaming_tpu.resilience.errors.StallError` is
    raised (``<name>.stalls`` counts it) so a supervisor can restart
    the pipeline instead of waiting forever. The timeout is a BUDGET
    on inter-item gaps, whatever their cause — the consumer cannot
    distinguish a wedged producer from one inside a long legitimate
    stage, so set it above the worst-case honest gap (a mid-stream
    recompile, a slow corpus read). The FIRST item is exempt: its gap
    legitimately includes jit compilation of the whole window step.
    Off (None) by default: bounded sources legitimately pause (a
    socket between bursts).

    With observability on (``obs.enable()``), the coupling itself is
    measured into the global registry — the signals the ROADMAP auto-K
    follow-on tunes against:

    - ``<name>.queue_depth`` gauge: items ready at each consumer pull;
    - ``<name>.producer_blocked_s`` counter: host time blocked on a FULL
      queue (the device/consumer is the bottleneck — host idle);
    - ``<name>.consumer_idle_s`` counter: consumer time blocked on an
      EMPTY queue (the host/producer is the bottleneck — device idle).

    Disabled, none of the extra clock reads happen (checked once per
    item against the trace flag).

    ``tuner`` (a :class:`~gelly_streaming_tpu.control.PrefetchTuner`)
    makes the depth ADAPTIVE: the queue is allocated at the tuner's
    ``depth_max`` and the producer honors the tuner's live ``depth`` as
    a soft cap, while both sides tap their blocked/idle seconds into
    the tuner — which moves the depth with hysteresis and bounded steps
    (ISSUE 15). Opting into a tuner opts into one clock read per item
    on each side, measured regardless of the obs flag (the tuner IS the
    consumer of the measurement); ``depth`` is then ignored.
    """
    soft_cap = None if tuner is None else tuner
    maxsize = max(1, depth) if tuner is None else max(1, tuner.depth_max)
    q: "queue.Queue[Any]" = queue.Queue(maxsize=maxsize)
    # the soft cap's wake-up channel: the consumer notifies after every
    # pull, so a producer waiting at the cap blocks on a condition (no
    # CPU) exactly like the hard queue's put — a qsize() poll loop here
    # measured up to ~20% off the 2-core steady throughput, the wakeups
    # contending with the two busy pipeline threads
    space = threading.Condition() if soft_cap is not None else None
    error: list = []
    stop = threading.Event()
    # instruments resolve lazily on first enabled item so a prefetch
    # started before obs.enable() still reports
    inst: list = [None]

    def _instruments():
        if inst[0] is None:
            reg = get_registry()
            inst[0] = (
                reg.gauge(name + ".queue_depth"),
                reg.counter(name + ".producer_blocked_s"),
                reg.counter(name + ".consumer_idle_s"),
            )
        return inst[0]

    def _put(item) -> bool:
        """Bounded put that gives up once the consumer is gone."""
        obs = _trace.on()
        measured = obs or soft_cap is not None
        t0 = time.perf_counter() if measured else 0.0
        if soft_cap is not None:
            # soft depth cap: the tuner's live depth bounds how far the
            # producer runs ahead even though the queue is allocated at
            # depth_max (so raising the knob needs no re-allocation).
            # Condition-wait, not a semaphore: the cap MOVES between
            # puts (a token count would need reconciliation on every
            # retune); the consumer's per-pull notify wakes us the
            # moment space opens, and the timeout slice only covers
            # stop/retune races
            with space:
                while q.qsize() >= soft_cap.depth:
                    if stop.is_set():
                        return False
                    space.wait(0.05)

        def done(_waited):
            if measured:
                dt = time.perf_counter() - t0
                if soft_cap is not None:
                    soft_cap.tap_put(dt if dt > 1e-4 else 0.0)
                if obs and dt > 1e-4:  # count real blocking, not put cost
                    _instruments()[1].inc(dt)

        return bounded_put(q, item, stop, on_done=done)

    def produce():
        try:
            for item in iterator:
                if not _put(item):
                    break
        except BaseException as e:  # re-raised consumer-side
            error.append(e)
        finally:
            if stop.is_set():
                close = getattr(iterator, "close", None)
                if close is not None:
                    try:
                        close()
                    except Exception:
                        # abandoned-consumer teardown: the close
                        # failure must not displace the consumer's own
                        # exit path, but a producer thread swallowing
                        # errors invisibly is the bug class GL003
                        # exists for — count it
                        get_registry().counter(
                            name + ".swallowed", site="iterator_close"
                        ).inc()
            _put(_SENTINEL)

    def _blocking_get():
        """One queue pull, stall-watched when armed: an empty queue
        past the ``stall_timeout_s`` budget fails loudly rather than
        waiting forever. The first item is exempt (its gap includes
        jit compile); a dead producer always leaves the sentinel, so
        a timeout means no progress, not a clean end."""
        if stall_timeout_s is None or n == 0:
            return q.get()
        try:
            return q.get(timeout=stall_timeout_s)
        except queue.Empty:
            get_registry().counter(name + ".stalls").inc()
            raise StallError(
                f"{name}: no item for {stall_timeout_s}s with the "
                "producer thread "
                + ("alive" if t.is_alive() else "gone")
            ) from None

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    n = 0
    try:
        while True:
            obs = _trace.on()
            if obs or soft_cap is not None:
                if obs:
                    depth_g, _pw, cw = _instruments()
                    depth_g.set(q.qsize())
                t0 = time.perf_counter()
                item = _blocking_get()
                dt = time.perf_counter() - t0
                if soft_cap is not None:
                    # wake a producer waiting at the soft cap: a slot
                    # just opened
                    with space:
                        space.notify()
                    soft_cap.tap_get(dt if dt > 1e-4 else 0.0)
                if obs and dt > 1e-4:  # real starvation, not get cost
                    cw.inc(dt)
            else:
                item = _blocking_get()
            if item is _SENTINEL:
                if error:
                    raise error[0]
                return
            if _faults.active():  # chaos hook: kill/stall at item n
                _faults.fire("pipeline.item", index=n)
            n += 1
            yield item
    finally:
        stop.set()
        # wait for the producer to leave its current item: a daemon thread
        # killed at interpreter teardown MID-DEVICE-OP aborts the process
        # (libc terminate), so hand-off must complete before shutdown
        t.join(timeout=join_timeout_s)
        if t.is_alive():
            # the silent leak (round-4 shape): a producer that never
            # honored the stop flag is still holding its iterator (and
            # possibly a device); surface it instead of quietly leaking
            # graftlint: disable=GL005 (teardown-only, fires at most once per prefetch lifetime; the leak must stay countable in disabled-obs production runs where warning filters can eat the RuntimeWarning)
            get_registry().counter(name + ".producer_leaked").inc()
            warnings.warn(
                f"{name}: prefetch producer thread did not exit within "
                f"{join_timeout_s}s of consumer shutdown; thread (and "
                "its source iterator) leaked",
                RuntimeWarning,
                stacklevel=2,
            )
