"""Host-side window discretization: unbounded edge stream -> EdgeBlocks.

The reference discretizes streams with Flink tumbling windows — per-key
``timeWindow`` inside the engine (``SummaryBulkAggregation.java:79-81``) and
``slice(Time)`` at the API level (``SimpleEdgeStream.java:135-167``). Window
firing is driven by ingestion time by default and event time when a timestamp
extractor is supplied (``SimpleEdgeStream.java:69-90``).

The TPU-native equivalent lives entirely on the host: a ``Windower`` consumes
an iterator of host edge records, runs them through the
:class:`~gelly_streaming_tpu.core.vertexdict.VertexDict` (the keyBy analog),
and emits padded, capacity-bucketed
:class:`~gelly_streaming_tpu.core.edgeblock.EdgeBlock` batches — one per
tumbling window. Two policies:

- ``CountWindow(n)``: every ``n`` edges is a window. This is the reproducible
  analog of the reference's processing-time windows (whose content depends on
  wall clock; tests there pin parallelism=1 for determinism —
  ``ConnectedComponentsTest.java:62-64``). Count windows make the same tests
  deterministic by construction.
- ``EventTimeWindow(size)``: tumbling windows over a user-extracted timestamp,
  the analog of event-time ``timeWindow`` with an ascending-timestamp
  extractor (``SimpleEdgeStream.java:86-90``).

Blocks carry *compact* int32 ids; raw ids stay host-side in the dict.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence, Tuple

import numpy as np

from ..obs import trace as _trace
from .edgeblock import (
    EdgeBlock,
    StackedEdgeBlock,
    bucket_capacity,
    stack_blocks,
    stack_host_cols,
)
from .vertexdict import VertexDict


def is_column_input(edges) -> bool:
    """True when ``edges`` is vectorized column input: an ``[N, k]``
    ndarray or a ``(src, dst[, val][, ts])`` tuple/list of 1-D arrays.

    THE shared fast-path predicate — the windower's array windows, the
    superbatch packer, and ``SimpleEdgeStream``'s ingest dispatch must
    always agree on which inputs take the array route (the per-window /
    superbatch emission-equivalence contract depends on it), so the
    rule lives in exactly one place."""
    if isinstance(edges, np.ndarray):
        return True
    return (
        isinstance(edges, (tuple, list))
        and len(edges) >= 2
        and all(isinstance(c, np.ndarray) and c.ndim == 1 for c in edges)
    )


@dataclasses.dataclass
class WindowPolicy:
    """Base class for window assignment policies."""


@dataclasses.dataclass(frozen=True)
class WindowInfo:
    """Host-side metadata for one emitted window (the ``TimeWindow`` analog).

    ``start``/``end`` are event-time bounds (end exclusive, Flink-style) for
    event-time windows, None for count windows; ``index`` counts emitted
    windows from 0 either way.
    """

    index: int
    start: Optional[float]
    end: Optional[float]

    @property
    def max_timestamp(self) -> Optional[float]:
        """Inclusive end, matching Flink's ``TimeWindow.maxTimestamp()``."""
        return None if self.end is None else self.end - 1


@dataclasses.dataclass
class CountWindow(WindowPolicy):
    """Tumbling window of a fixed number of edges."""

    size: int


class ScheduledCountWindow(CountWindow):
    """Count windows whose SIZE follows a window-indexed schedule — the
    mid-stream window-size-shift harness for the adaptive packer
    (``bench.py --autotune``'s shift cell and the controller tests).

    ``schedule`` is ``((start_index, size), ...)`` with ascending start
    indices, the first at 0: window ``i`` has the size of the last
    segment whose start is ``<= i``. Only the DYNAMIC packer
    (:meth:`Windower.superbatches_dynamic`) honors the schedule — it
    re-reads the size per group and caps each group at the next
    boundary so a group never spans two sizes; the static paths read
    ``.size`` (the first segment) like any ``CountWindow``."""

    def __init__(self, schedule):
        sched = tuple((int(a), int(b)) for a, b in schedule)
        if not sched or sched[0][0] != 0:
            raise ValueError(
                "schedule must be non-empty with its first segment at "
                f"window 0, got {schedule!r}"
            )
        for (a, sa), (b, sb) in zip(sched, sched[1:]):
            if b <= a:
                raise ValueError(
                    f"schedule starts must ascend, got {a} then {b}"
                )
        if any(s < 1 for _a, s in sched):
            raise ValueError("every scheduled size must be >= 1")
        super().__init__(size=sched[0][1])
        self.schedule = sched

    def size_at(self, index: int) -> int:
        """The window size at window ``index``."""
        size = self.schedule[0][1]
        for start, s in self.schedule:
            if start > index:
                break
            size = s
        return size

    def run_length(self, index: int) -> Optional[int]:
        """Windows from ``index`` (inclusive) until the next size
        boundary; None inside the final segment (no boundary ahead)."""
        for start, _s in self.schedule:
            if start > index:
                return start - index
        return None


@dataclasses.dataclass
class ProcessingTimeWindow(WindowPolicy):
    """Tumbling wall-clock window: close when ``seconds`` have elapsed
    since the window's first record — the micro-batch/low-latency policy
    for unbounded live sources (Flink's processing-time ``timeWindow``).

    ``max_count`` additionally caps the window's record count (close on
    whichever trips first), bounding device block capacity under bursts.
    Live sources that can go idle should yield ``None`` ticks (see
    :class:`~gelly_streaming_tpu.core.sources.SocketEdgeSource`): the
    windower treats them as pure time signals, so an open window still
    closes on schedule when no records arrive."""

    seconds: float
    max_count: int = 1 << 20


@dataclasses.dataclass
class EventTimeWindow(WindowPolicy):
    """Tumbling event-time window of ``size`` time units.

    ``timestamp_fn(edge) -> number`` extracts the (ascending) event time, the
    analog of the reference's ``AscendingTimestampExtractor`` ctor path.

    Column contract on the array fast path: array input is ``[N, 2|3]``
    (src, dst[, third]) or a (src, dst[, val][, ts]) column tuple, and
    ``timestamp_fn`` is applied to the column tuple itself — an index-based
    extractor like ``lambda e: e[2]`` therefore selects the same column it
    would select per-record, vectorized for free. A non-indexing fn must be
    numpy-broadcastable or the windower raises.
    """

    size: float
    timestamp_fn: Callable[[Tuple], float] = None  # type: ignore[assignment]


class Windower:
    """Discretize host edge records into EdgeBlocks under a window policy.

    Edge records are ``(src, dst)`` or ``(src, dst, val)`` tuples (raw ids).
    The windower owns the stream's VertexDict so compact ids are stable across
    windows — carried device state (labels, degrees, ranks) indexed by compact
    id stays valid as new vertices appear (vertex capacity only grows, in
    power-of-two buckets).
    """

    def __init__(
        self,
        policy: WindowPolicy,
        vertex_dict: Optional[VertexDict] = None,
        *,
        val_dtype=np.float32,
        capacity: Optional[int] = None,
    ):
        self.policy = policy
        self.vertex_dict = vertex_dict if vertex_dict is not None else VertexDict()
        self.val_dtype = val_dtype
        self.capacity = capacity  # fixed capacity override (else bucketed)

    # ------------------------------------------------------------------ #
    def _rows_to_cols(self, rows: Sequence[Tuple]) -> Tuple:
        """One window's record tuples -> raw ``(src, dst, val|None)``
        columns — THE record-parsing rule (val presence decided by the
        window's first record), shared by the per-window block path and
        the record superbatch packer so the two cannot drift."""
        n = len(rows)
        raw_src = np.fromiter((r[0] for r in rows), dtype=np.int64, count=n)
        raw_dst = np.fromiter((r[1] for r in rows), dtype=np.int64, count=n)
        if n and len(rows[0]) > 2 and rows[0][2] is not None:
            val = np.asarray([r[2] for r in rows], dtype=self.val_dtype)
        else:
            val = None
        return raw_src, raw_dst, val

    def _make_block(self, rows: Sequence[Tuple]) -> EdgeBlock:
        return self._block_from_arrays(*self._rows_to_cols(rows))

    def _block_from_arrays(
        self, raw_src: np.ndarray, raw_dst: np.ndarray, val: Optional[np.ndarray]
    ) -> EdgeBlock:
        n = raw_src.shape[0]
        # the span covers the whole host pack: encode + pad + device put
        # (the per-window fixed cost the superbatch path amortizes)
        with _trace.span(
            "window.pack",
            {"edges": int(n)} if _trace.on() else None,
        ):
            # Paired encode keeps first-seen order by edge arrival (src
            # before dst per edge), matching the reference's per-record
            # processing.
            src, dst = self.vertex_dict.encode_pair(raw_src, raw_dst)
            cap = (
                self.capacity if self.capacity is not None
                else bucket_capacity(n)
            )
            block = EdgeBlock.from_arrays(
                src, dst, val, n_vertices=self.vertex_dict.capacity,
                capacity=cap, val_dtype=self.val_dtype,
            )
            host_val = (
                np.zeros(n, dtype=self.val_dtype)
                if val is None
                else np.asarray(val, self.val_dtype)
            )
            return block.with_host_cache(src, dst, host_val)

    def _block_from_encoded(
        self, src: np.ndarray, dst: np.ndarray, val: Optional[np.ndarray]
    ) -> EdgeBlock:
        """Build a block from already-compact int32 columns (the fused
        native parse+encode path — the vertex dict was updated upstream)."""
        n = src.shape[0]
        with _trace.span(
            "window.pack",
            {"edges": int(n), "encoded": True} if _trace.on() else None,
        ):
            src = np.ascontiguousarray(src, np.int32)
            dst = np.ascontiguousarray(dst, np.int32)
            cap = (
                self.capacity if self.capacity is not None
                else bucket_capacity(n)
            )
            block = EdgeBlock.from_arrays(
                src, dst, val, n_vertices=self.vertex_dict.capacity,
                capacity=cap, val_dtype=self.val_dtype,
            )
            host_val = (
                np.zeros(n, dtype=self.val_dtype)
                if val is None
                else np.asarray(val, self.val_dtype)
            )
            return block.with_host_cache(src, dst, host_val)

    def blocks(self, edges: Iterable[Tuple]) -> Iterator[EdgeBlock]:
        """Yield one EdgeBlock per tumbling window."""
        for _, block in self.blocks_with_info(edges):
            yield block

    def blocks_with_info(
        self, edges: Iterable[Tuple]
    ) -> Iterator[Tuple["WindowInfo", EdgeBlock]]:
        """Like :meth:`blocks` but paired with host-side window metadata.

        The metadata stays OUT of the EdgeBlock pytree on purpose: a
        per-window id inside the block would be a static leaf changing every
        window and defeat jit caching. Flink's analog is the ``TimeWindow``
        handed to window functions (``SnapshotStream.java:146``).
        """
        policy = self.policy
        index = 0
        if is_column_input(edges):
            yield from self._array_windows(edges)
            return
        if callable(getattr(edges, "iter_chunks", None)) and isinstance(
            policy, CountWindow
        ):
            # chunk-capable source (GeneratorSource): consume column
            # chunks directly instead of per-record tuples — the
            # synthetic load generator must not itself be the
            # bottleneck. Count windows only: time policies read
            # per-record semantics (ticks, timestamps) chunks don't
            # carry, so they keep the record path.
            yield from self.blocks_from_chunks(edges.iter_chunks())
            return
        if isinstance(policy, CountWindow):
            buf: list[Tuple] = []
            for e in edges:
                if e is None:  # live-source time tick; count windows ignore
                    continue
                buf.append(e)
                if len(buf) >= policy.size:
                    yield WindowInfo(index, None, None), self._make_block(buf)
                    index += 1
                    buf = []
            if buf:
                yield WindowInfo(index, None, None), self._make_block(buf)
        elif isinstance(policy, ProcessingTimeWindow):
            import time as _time

            buf = []
            t0: Optional[float] = None
            for e in edges:
                now = _time.perf_counter()
                if e is not None:
                    if t0 is None:
                        t0 = now
                    buf.append(e)
                if buf and (
                    now - t0 >= policy.seconds or len(buf) >= policy.max_count
                ):
                    yield WindowInfo(index, None, None), self._make_block(buf)
                    index += 1
                    buf = []
                    t0 = None
            if buf:
                yield WindowInfo(index, None, None), self._make_block(buf)
        elif isinstance(policy, EventTimeWindow):
            if policy.timestamp_fn is None:
                raise ValueError(
                    "EventTimeWindow requires timestamp_fn — without it the "
                    "edge value would silently be read as the event time"
                )
            ts_fn = policy.timestamp_fn
            buf = []
            current: Optional[int] = None
            for e in edges:
                if e is None:
                    # live-source idle tick: event-time windows close on
                    # event time, never wall clock, so ticks are no-ops
                    continue
                w = int(ts_fn(e) // policy.size)
                if current is None:
                    current = w
                if w != current:
                    if buf:
                        yield self._info(index, current), self._make_block(buf)
                        index += 1
                    buf = []
                    current = w
                buf.append(e)
            if buf:
                yield self._info(index, current), self._make_block(buf)
        else:
            raise TypeError(f"unknown window policy {policy!r}")

    def _info(self, index: int, time_slot: int) -> "WindowInfo":
        size = self.policy.size
        return WindowInfo(index, time_slot * size, (time_slot + 1) * size)

    # ------------------------------------------------------------------ #
    # Superbatch packing: K windows -> one ingest group
    # ------------------------------------------------------------------ #
    def superbatches(
        self, edges: Iterable[Tuple], k: int
    ) -> Iterator["SuperbatchGroup"]:
        """Pack K consecutive windows into one :class:`SuperbatchGroup`
        (the final group may be shorter).

        This is the ingest half of the superbatch execution path: the
        per-window fixed cost below ~64k-edge windows is dominated by
        assembling one device EdgeBlock PER WINDOW (compact-id encode +
        padding + several host->device puts each), so the packer's array
        fast path (count windows over column input) never builds
        per-window blocks at all — it encodes the whole group once and
        hands out per-window host column views; the ``[K, cap]`` device
        stack materializes lazily only for consumers that dispatch on it
        (``SummaryAggregation._superbatch_step``). Window BOUNDARIES are
        unchanged — each member window keeps its own WindowInfo and mask
        row, so emission semantics stay per-window.
        """
        if k < 1:
            raise ValueError(f"superbatch k must be >= 1, got {k}")
        policy = self.policy
        if isinstance(policy, CountWindow) and is_column_input(edges):
            yield from self._array_superbatches(edges, k)
            return
        if isinstance(policy, CountWindow) and not callable(
            getattr(edges, "iter_chunks", None)
        ):
            yield from self._record_superbatches(iter(edges), k)
            return
        yield from superbatches_from_blocks(
            self.blocks_with_info(edges), k, with_info=True,
            val_dtype=self.val_dtype,
        )

    def _array_superbatches(self, edges, k: int) -> Iterator["SuperbatchGroup"]:
        """Count-window column fast path: slice the raw columns into
        per-window triples and delegate to :meth:`pack_window_cols` —
        THE one group-packing implementation (slicing here, encode +
        group assembly there), so the fast path, the sharded-ingest
        path, and the latency-curve bench all measure the same code."""
        if isinstance(edges, np.ndarray):
            if edges.ndim != 2 or not 2 <= edges.shape[1] <= 3:
                raise ValueError("edge array must be [N, 2] or [N, 3]")
            cols = [edges[:, i] for i in range(edges.shape[1])]
        else:
            cols = [np.asarray(c) for c in edges]
        src = cols[0].astype(np.int64)
        dst = cols[1].astype(np.int64)
        val = cols[2].astype(self.val_dtype) if len(cols) > 2 else None
        n = src.shape[0]
        size = self.policy.size
        index = 0
        for g0 in range(0, n, size * k):
            g1 = min(g0 + size * k, n)
            win_cols = [
                (src[w0:min(w0 + size, g1)], dst[w0:min(w0 + size, g1)],
                 None if val is None else val[w0:min(w0 + size, g1)])
                for w0 in range(g0, g1, size)
            ]
            yield self.pack_window_cols(win_cols, first_index=index)
            index += len(win_cols)

    def _record_superbatches(
        self, edges: Iterator[Tuple], k: int
    ) -> Iterator["SuperbatchGroup"]:
        """Count-window RECORD path: buffer K windows' raw records,
        convert each window to raw columns once, and pack the group
        through :meth:`pack_window_cols` — the same one-group-encode
        ingest fusion the column fast path gets. Record streams
        previously fell back to per-window block assembly + generic
        packing, which both paid the per-window device cost the
        superbatch exists to amortize AND left the group without the
        packer's seen-count watermark (``SuperbatchGroup.n_seen_before``).
        Live-source ``None`` ticks are ignored, as in :meth:`blocks`."""
        size = self.policy.size
        index = 0
        win_rows: list = []
        rows: list = []

        def flush():
            nonlocal win_rows, index
            cols = [self._rows_to_cols(rws) for rws in win_rows]
            group = self.pack_window_cols(cols, first_index=index)
            index += len(cols)
            win_rows = []
            return group

        for e in edges:
            if e is None:  # live-source time tick; count windows ignore
                continue
            rows.append(e)
            if len(rows) >= size:
                win_rows.append(rows)
                rows = []
                if len(win_rows) >= k:
                    yield flush()
        if rows:
            win_rows.append(rows)
        if win_rows:
            yield flush()

    #: windows per group while the dynamic packer replays a resume skip
    #: (packed for the vertex-dictionary replay, never surfaced — the
    #: tiling of unsurfaced groups is free to be whatever amortizes the
    #: encode best)
    SKIP_GROUP_WINDOWS = 256

    def superbatches_dynamic(
        self, edges: Iterable[Tuple], k_fn, skip: int = 0
    ) -> Iterator["SuperbatchGroup"]:
        """Adaptive-K superbatch packing: like :meth:`superbatches`, but
        the group size is re-read from ``k_fn()`` at EVERY group
        boundary — the ingest half of ``superbatch="auto"`` (the
        controller moves K between groups; window boundaries, packing,
        and emission semantics are exactly the fixed-K path's, group
        TILING is the only degree of freedom). Count windows re-read
        ``policy.size`` per window too, so a
        :class:`ScheduledCountWindow` shifts window size mid-stream
        with groups capped at each size boundary (a group never spans
        two sizes). ``skip`` consumes (packs, for the vertex-dictionary
        replay) the first ``skip`` windows without surfacing them — the
        checkpoint-resume fast-forward
        (``autockpt._SkipStream.superbatches_dynamic``)."""
        if skip < 0:
            raise ValueError(f"skip must be >= 0, got {skip}")
        policy = self.policy
        if isinstance(policy, CountWindow) and is_column_input(edges):
            yield from self._dynamic_array_superbatches(edges, k_fn, skip)
            return
        if isinstance(policy, CountWindow) and not callable(
            getattr(edges, "iter_chunks", None)
        ):
            yield from self._dynamic_record_superbatches(
                iter(edges), k_fn, skip
            )
            return
        blocks = self.blocks_with_info(edges)
        for _ in range(skip):
            if next(blocks, None) is None:
                break
        yield from superbatches_from_blocks_dynamic(
            blocks, k_fn, with_info=True, val_dtype=self.val_dtype,
        )

    def _group_k(self, index: int, k_fn, skip: int) -> Tuple[int, int]:
        """(window size, group window count) for the group starting at
        window ``index`` — the one tiling rule of the dynamic packer:
        the scheduled size at the index, the controller's K (or the
        skip-replay tile), capped so a group never crosses a size
        boundary or the skip watermark."""
        policy = self.policy
        size_at = getattr(policy, "size_at", None)
        size = int(size_at(index)) if callable(size_at) \
            else int(policy.size)
        if index < skip:
            k = min(self.SKIP_GROUP_WINDOWS, skip - index)
        else:
            k = max(1, int(k_fn()))
        run_length = getattr(policy, "run_length", None)
        if callable(run_length):
            rl = run_length(index)
            if rl is not None:
                k = min(k, max(1, rl))
        return size, k

    def _dynamic_array_superbatches(
        self, edges, k_fn, skip: int
    ) -> Iterator["SuperbatchGroup"]:
        """Count-window column fast path with per-group tiling — same
        slicing + :meth:`pack_window_cols` shape as
        :meth:`_array_superbatches`, group size decided per group."""
        if isinstance(edges, np.ndarray):
            if edges.ndim != 2 or not 2 <= edges.shape[1] <= 3:
                raise ValueError("edge array must be [N, 2] or [N, 3]")
            cols = [edges[:, i] for i in range(edges.shape[1])]
        else:
            cols = [np.asarray(c) for c in edges]
        src = cols[0].astype(np.int64)
        dst = cols[1].astype(np.int64)
        val = cols[2].astype(self.val_dtype) if len(cols) > 2 else None
        n = src.shape[0]
        index = 0
        g0 = 0
        while g0 < n:
            size, k = self._group_k(index, k_fn, skip)
            g1 = min(g0 + size * k, n)
            win_cols = [
                (src[w0:min(w0 + size, g1)], dst[w0:min(w0 + size, g1)],
                 None if val is None else val[w0:min(w0 + size, g1)])
                for w0 in range(g0, g1, size)
            ]
            group = self.pack_window_cols(win_cols, first_index=index)
            index += len(win_cols)
            g0 = g1
            if index > skip:  # groups never straddle skip (capped above)
                yield group

    def _dynamic_record_superbatches(
        self, edges: Iterator[Tuple], k_fn, skip: int
    ) -> Iterator["SuperbatchGroup"]:
        """Count-window RECORD path with per-group tiling (the dynamic
        analog of :meth:`_record_superbatches`); live-source ``None``
        ticks are ignored, as everywhere count windows consume them."""
        index = 0
        win_rows: list = []
        rows: list = []
        size, k_target = self._group_k(index, k_fn, skip)

        def flush():
            nonlocal win_rows, index, size, k_target
            cols = [self._rows_to_cols(rws) for rws in win_rows]
            group = self.pack_window_cols(cols, first_index=index)
            start = index
            index += len(cols)
            win_rows = []
            size, k_target = self._group_k(index, k_fn, skip)
            return group if start >= skip else None

        for e in edges:
            if e is None:
                continue
            rows.append(e)
            if len(rows) >= size:
                win_rows.append(rows)
                rows = []
                if len(win_rows) >= k_target:
                    group = flush()
                    if group is not None:
                        yield group
        if rows:
            win_rows.append(rows)
        if win_rows:
            group = flush()
            if group is not None:
                yield group

    def pack_window_cols(
        self, win_cols: Sequence[Tuple], first_index: int = 0
    ) -> "SuperbatchGroup":
        """Pack ALREADY-CLOSED windows (raw-id column triples
        ``(src, dst, val|None)``) into one :class:`SuperbatchGroup`
        with a single group encode and ZERO per-window device work —
        the superbatch ingest fusion for window boundaries decided
        upstream (the sharded ingest's per-shard windowers,
        ``core/ingest.py``). The count-window column fast path
        (:meth:`_array_superbatches`) is the same shape with the
        boundary slicing done here too."""
        k = len(win_cols)
        lens = [len(c[0]) for c in win_cols]
        with _trace.span(
            "window.superbatch_pack",
            {"k": k, "edges": int(sum(lens)), "window_index": first_index}
            if _trace.on() else None,
        ):
            # seen-vertex watermark BEFORE the group encode: together
            # with the encoded columns this reconstructs every member
            # window's post-encode len(vertex_dict)
            # (SuperbatchGroup.n_seen_per_window) — the per-window value
            # group-folded workloads that read the seen count
            # (IncrementalPageRank's teleport mass) need for value
            # identity with the per-window path
            n_seen_before = len(self.vertex_dict)
            if k == 1:
                src = np.ascontiguousarray(win_cols[0][0], np.int64)
                dst = np.ascontiguousarray(win_cols[0][1], np.int64)
            else:
                src = np.concatenate(
                    [np.asarray(c[0], np.int64) for c in win_cols]
                )
                dst = np.concatenate(
                    [np.asarray(c[1], np.int64) for c in win_cols]
                )
            s_g, d_g = self.vertex_dict.encode_pair(src, dst)
            s_g = np.asarray(s_g, np.int32)
            d_g = np.asarray(d_g, np.int32)
            nv = self.vertex_dict.capacity
            cols = []
            infos = []
            a = 0
            for j, c in enumerate(win_cols):
                b = a + lens[j]
                v = c[2]
                cols.append((
                    s_g[a:b], d_g[a:b],
                    None if v is None else np.asarray(v, self.val_dtype),
                ))
                infos.append(WindowInfo(first_index + j, None, None))
                a = b
            return SuperbatchGroup(
                infos, cols, nv, val_dtype=self.val_dtype,
                n_seen_before=n_seen_before,
            )

    # ------------------------------------------------------------------ #
    # Vectorized ingest: numpy columns instead of per-record tuples
    # ------------------------------------------------------------------ #
    def _array_windows(self, edges) -> Iterator[Tuple["WindowInfo", EdgeBlock]]:
        """Array fast path: ``edges`` is an [N,2|3] ndarray or a
        (src, dst[, val][, ts]) tuple/list of 1-D arrays. Window boundaries
        are computed with numpy (no per-record Python), the host ingest
        throughput fix for large streams.
        """
        if isinstance(edges, np.ndarray):
            if edges.ndim != 2 or not 2 <= edges.shape[1] <= 3:
                raise ValueError("edge array must be [N, 2] or [N, 3]")
            cols = [edges[:, i] for i in range(edges.shape[1])]
        else:
            cols = [np.asarray(c) for c in edges]
        src = cols[0].astype(np.int64)
        dst = cols[1].astype(np.int64)
        val = cols[2].astype(self.val_dtype) if len(cols) > 2 else None
        n = src.shape[0]
        policy = self.policy
        ts = None
        if isinstance(policy, EventTimeWindow):
            # Same contract as the record path: the caller must say which
            # column is the event time — never silently read the value
            # column as a timestamp.
            if policy.timestamp_fn is None:
                raise ValueError(
                    "EventTimeWindow requires timestamp_fn — without it the "
                    "edge value would silently be read as the event time"
                )
            # Apply the extractor to the column tuple: an index-based fn
            # (lambda e: e[k]) picks the same column it picks per-record,
            # vectorized. Anything non-broadcastable errors here rather
            # than silently windowing on the wrong column.
            try:
                ts = np.asarray(policy.timestamp_fn(tuple(cols)), np.float64)
            except Exception as e:
                raise ValueError(
                    "EventTimeWindow.timestamp_fn could not be applied to "
                    "the column tuple on the array ingest path; use an "
                    "index-based extractor (lambda e: e[k]) or a numpy-"
                    f"broadcastable fn ({e})"
                ) from e
            if ts.shape != (n,):
                raise ValueError(
                    "EventTimeWindow.timestamp_fn returned shape "
                    f"{ts.shape} on the array path; expected ({n},)"
                )
        if isinstance(policy, CountWindow):
            index = 0
            for start in range(0, n, policy.size):
                end = min(start + policy.size, n)
                yield WindowInfo(index, None, None), self._block_from_arrays(
                    src[start:end], dst[start:end],
                    None if val is None else val[start:end],
                )
                index += 1
        elif isinstance(policy, EventTimeWindow):
            slots = (np.asarray(ts, np.float64) // policy.size).astype(np.int64)
            # ascending timestamps: window boundaries are runs of equal slot
            bounds = np.nonzero(np.diff(slots))[0] + 1
            starts = np.concatenate([[0], bounds])
            ends = np.concatenate([bounds, [n]])
            for index, (a, b) in enumerate(zip(starts, ends)):
                yield self._info(index, int(slots[a])), self._block_from_arrays(
                    src[a:b], dst[a:b], None if val is None else val[a:b]
                )
        else:
            raise TypeError(f"unknown window policy {policy!r}")


    # ------------------------------------------------------------------ #
    # Chunked-column ingest: file-scale streams (datasets.stream_file)
    # ------------------------------------------------------------------ #
    def blocks_from_chunks(
        self, chunks: Iterable[Tuple], encoded: bool = False
    ) -> Iterator[Tuple["WindowInfo", EdgeBlock]]:
        """Discretize an iterator of column chunks ``(src, dst[, val])``
        into windows, re-slicing across chunk boundaries.

        This is the bounded-memory ingest path for file-backed streams
        (``native.iter_edge_chunks`` yields ~fixed-size column chunks; the
        window policy decides the actual block boundaries). Count windows
        buffer columns until ``size`` edges are pending; event-time windows
        assume ascending timestamps (the reference's
        ``AscendingTimestampExtractor`` contract) and flush a window when
        its slot is passed.

        ``encoded=True`` marks chunks whose endpoint columns are already
        compact int32 ids from this windower's VertexDict (the fused native
        ingest, ``VertexDict.iter_encode_file``); on that path an
        event-time ``timestamp_fn`` sees compact ids in columns 0/1.
        """
        policy = self.policy
        if isinstance(policy, CountWindow):
            yield from self._chunk_count_windows(chunks, policy.size, encoded)
        elif isinstance(policy, EventTimeWindow):
            yield from self._chunk_time_windows(chunks, policy, encoded)
        else:
            raise TypeError(f"unknown window policy {policy!r}")

    def _chunk_count_windows(self, chunks, size: int, encoded: bool = False):
        pending: list[Tuple] = []  # (src, dst, val|None) column triples
        have = 0
        index = 0
        build = self._block_from_encoded if encoded else self._block_from_arrays
        for cols in chunks:
            src, dst = np.asarray(cols[0]), np.asarray(cols[1])
            val = cols[2] if len(cols) > 2 else None
            if len(src) == 0:
                continue
            pending.append((src, dst, val))
            have += len(src)
            while have >= size:
                have -= size
                yield WindowInfo(index, None, None), build(
                    *take_cols(pending, size, self.val_dtype)
                )
                index += 1
        if have:
            yield WindowInfo(index, None, None), build(
                *take_cols(pending, have, self.val_dtype)
            )

    def _chunk_time_windows(
        self, chunks, policy: EventTimeWindow, encoded: bool = False
    ):
        build = self._block_from_encoded if encoded else self._block_from_arrays
        runs = iter_time_slot_runs(chunks, policy, val_dtype=self.val_dtype)
        for index, (slot, src, dst, val) in enumerate(runs):
            yield self._info(index, slot), build(src, dst, val)


def take_cols(pend: list, take: int, val_dtype=np.float64):
    """Slice ``take`` edges off a pending list of ``(src, dst,
    val|None)`` column chunks, mutating ``pend`` in place — THE
    take-N-across-chunk-boundaries rule, shared by the windower's
    chunked count windows and the sharded ingest's per-shard window
    assembly (``core/ingest.py``). Single-chunk takes hand out slice
    VIEWS (no concatenation copy — the encoder reads views);
    multi-chunk takes concatenate once, zero-filling ``None`` value
    chunks when any chunk carries values.

    Chunks may carry a 4th element — the i64 event-time ``ts`` column of
    a GSEW v2 frame (ISSUE 18); the take then returns a matching
    4-tuple, slicing ``ts`` in lockstep. Mixed pending lists (some
    chunks timestamped, some not) are a caller bug and raise: a window
    half of whose records lost their timestamps cannot be assigned to
    event-time panes honestly."""
    with_ts = len(pend[0]) == 4
    s_parts, d_parts, v_parts, t_parts = [], [], [], []
    got = 0
    while got < take:
        chunk = pend[0]
        if (len(chunk) == 4) != with_ts:
            raise ValueError(
                "pending column chunks disagree on carrying a ts column"
            )
        s, d, v = chunk[0], chunk[1], chunk[2]
        t = chunk[3] if with_ts else None
        need = take - got
        if len(s) <= need:
            s_parts.append(s)
            d_parts.append(d)
            v_parts.append(v)
            t_parts.append(t)
            pend.pop(0)
            got += len(s)
        else:
            s_parts.append(s[:need])
            d_parts.append(d[:need])
            v_parts.append(None if v is None else v[:need])
            t_parts.append(None if t is None else t[:need])
            rest = (s[need:], d[need:],
                    None if v is None else v[need:])
            pend[0] = rest + (t[need:],) if with_ts else rest
            got = take
    if len(s_parts) == 1:
        out = (s_parts[0], d_parts[0], v_parts[0])
        return out + (t_parts[0],) if with_ts else out
    src = np.concatenate(s_parts)
    dst = np.concatenate(d_parts)
    if any(v is not None for v in v_parts):
        val = np.concatenate(
            [
                np.zeros(len(s), val_dtype) if v is None
                else np.asarray(v, val_dtype)
                for s, v in zip(s_parts, v_parts)
            ]
        )
    else:
        val = None
    if with_ts:
        ts = np.concatenate(
            [np.asarray(t, np.int64) for t in t_parts]
        )
        return src, dst, val, ts
    return src, dst, val


def iter_time_slot_runs(chunks, policy: "EventTimeWindow",
                        val_dtype=np.float64):
    """The ONE chunked event-time splitter: consume (src, dst[, val])
    column chunks and yield ``(slot, src, dst, val|None)`` per completed
    tumbling window (ascending timestamps; boundaries are runs of equal
    ``ts // size``; the final partial window is included). Carried runs
    accumulate as a LIST and concatenate once per flush — a window
    spanning many chunks costs O(window), not a per-chunk re-copy of the
    whole carry. Shared by the Windower's chunked path and the
    device-encode ingest (``datasets._device_encoded_blocks``) so slot
    semantics cannot diverge between them."""
    if policy.timestamp_fn is None:
        raise ValueError(
            "EventTimeWindow requires timestamp_fn — without it the "
            "edge value would silently be read as the event time"
        )
    slot: Optional[int] = None
    pend: list = []

    def flush():
        if not pend:
            return None
        src = np.concatenate([p[0] for p in pend])
        dst = np.concatenate([p[1] for p in pend])
        if any(p[2] is not None for p in pend):
            val = np.concatenate(
                [
                    np.zeros(len(p[0]), val_dtype) if p[2] is None
                    else np.asarray(p[2], val_dtype)
                    for p in pend
                ]
            )
        else:
            val = None
        out = (slot, src, dst, val)
        pend.clear()
        return out

    for cols in chunks:
        src, dst = np.asarray(cols[0]), np.asarray(cols[1])
        val = cols[2] if len(cols) > 2 else None
        n = len(src)
        if n == 0:
            continue
        ts = np.asarray(
            policy.timestamp_fn(tuple(
                np.asarray(c) if c is not None else None for c in cols
            )),
            np.float64,
        )
        if ts.shape != (n,):
            raise ValueError(
                "EventTimeWindow.timestamp_fn returned shape "
                f"{ts.shape} on the chunked path; expected ({n},)"
            )
        slots = (ts // policy.size).astype(np.int64)
        bounds = np.nonzero(np.diff(slots))[0] + 1
        starts = np.concatenate([[0], bounds])
        ends = np.concatenate([bounds, [n]])
        for a, b in zip(starts, ends):
            run_slot = int(slots[a])
            if slot is not None and run_slot != slot:
                w = flush()
                if w is not None:
                    yield w
            slot = run_slot
            pend.append(
                (src[a:b], dst[a:b], None if val is None else val[a:b])
            )
    w = flush()
    if w is not None:
        yield w


class SuperbatchGroup:
    """K consecutive windows as ONE ingest unit (the superbatch).

    ``cols`` holds per-window host column triples ``(src, dst, val|None)``
    of compact int32 ids — the zero-device-work view the windowed CC
    carries consume; ``None`` when the member windows were
    device-transformed (no usable host caches). :meth:`stacked`
    materializes (and caches) the ``[K, cap]``
    :class:`~gelly_streaming_tpu.core.edgeblock.StackedEdgeBlock` for
    consumers that dispatch on the device stack — built from ``cols``
    with ONE host->device transfer per column, or from the member
    blocks' device arrays as the fallback.

    ``n_seen_before`` records ``len(vertex_dict)`` at the moment the
    packer started the group encode (None when the group was packed
    from pre-built blocks and the watermark is unknown); see
    :meth:`n_seen_per_window`.
    """

    __slots__ = ("infos", "cols", "n_vertices", "val_dtype", "_blocks",
                 "_stacked", "n_seen_before")

    def __init__(self, infos, cols, n_vertices: int, *,
                 val_dtype=np.float32, blocks=None,
                 n_seen_before: Optional[int] = None):
        self.infos = infos
        self.cols = cols
        self.n_vertices = n_vertices
        self.val_dtype = val_dtype
        self._blocks = blocks
        self._stacked = None
        self.n_seen_before = n_seen_before

    def __len__(self) -> int:
        return len(self.infos)

    def n_seen_per_window(self) -> Optional[list]:
        """Per-member-window seen-vertex counts — the ``len(vertex_dict)``
        a per-window consumer would have read after each window's encode
        — reconstructed from the group's encoded columns.

        Both dictionary kinds assign/observe monotonically in first-seen
        order (``VertexDict`` hands out sequential compact ids;
        ``IdentityDict.observe`` tracks ``max raw id + 1``), so the count
        after window ``i`` is exactly ``max(n_seen_before, 1 + max
        compact id over windows <= i)``. Returns None when the packer
        did not record the pre-encode watermark (generic block packing)
        — consumers needing per-window counts then take their
        per-window fallback."""
        if self.cols is None or self.n_seen_before is None:
            return None
        out = []
        n = int(self.n_seen_before)
        for s, d, _ in self.cols:
            if len(s):
                hi = 1 + int(max(s.max(), d.max()))
                if hi > n:
                    n = hi
            out.append(n)
        return out

    def blocks(self) -> Iterator[EdgeBlock]:
        """The member windows as per-window :class:`EdgeBlock`\\ s — the
        group's PER-WINDOW fallback view (``GroupFoldable``
        implementations route unsupported groups through it). Pre-built
        blocks are handed out as-is; column-backed groups assemble one
        block per window (paying exactly the per-window device cost the
        fused path avoids — that is the point of a fallback)."""
        if self._blocks is not None:
            yield from self._blocks
            return
        for s, d, v in self.cols:
            block = EdgeBlock.from_arrays(
                np.ascontiguousarray(s, np.int32),
                np.ascontiguousarray(d, np.int32),
                v, n_vertices=self.n_vertices, val_dtype=self.val_dtype,
            )
            host_val = (
                np.zeros(len(s), dtype=self.val_dtype) if v is None
                else np.asarray(v, self.val_dtype)
            )
            yield block.with_host_cache(
                np.asarray(s, np.int32), np.asarray(d, np.int32), host_val
            )

    def stacked(self) -> StackedEdgeBlock:
        if self._stacked is not None:
            return self._stacked
        # span covers the [K, cap] device-stack materialization (one
        # host->device transfer per column on the cols path, a device
        # stack of the member blocks on the fallback)
        with _trace.span(
            "window.stack",
            {"k": len(self), "from_cols": self.cols is not None}
            if _trace.on() else None,
        ):
            if self.cols is not None:
                self._stacked = stack_host_cols(
                    self.cols, self.n_vertices, val_dtype=self.val_dtype
                )
            else:
                self._stacked = stack_blocks(self._blocks)
        return self._stacked


def _group_from_blocks(group: list, infos: list,
                       val_dtype) -> SuperbatchGroup:
    """One pre-built-block group as a :class:`SuperbatchGroup` — the
    shared emit of the fixed and dynamic block packers."""
    cols = None
    # same honesty guard as stack_blocks: prefix-aligned caches with
    # plain ndarray vals only — pytree vals (tuple-valued map_edges)
    # cannot fill a single [K, cap] val plane and take the device
    # stacking fallback instead
    if all(
        getattr(b, "_host_cache", None) is not None
        and getattr(b, "_host_cache_pos", None) is None
        and (b._host_cache[2] is None
             or isinstance(b._host_cache[2], np.ndarray))
        for b in group
    ):
        cols = [b._host_cache for b in group]
    return SuperbatchGroup(
        infos, cols, max(b.n_vertices for b in group),
        val_dtype=val_dtype, blocks=group,
    )


def superbatches_from_blocks(
    blocks: Iterable, k: int, with_info: bool = False,
    val_dtype=np.float32,
) -> Iterator[SuperbatchGroup]:
    """Pack an EdgeBlock iterator into :class:`SuperbatchGroup`\\ s of K
    (generic fallback — per-window blocks were already assembled, so
    this recovers only the dispatch fusion, not the ingest fusion).
    Host column views come from the blocks' prefix-aligned host caches
    when every member has one; otherwise ``cols`` is None and consumers
    use the device stack."""
    group: list = []
    infos: list = []
    for item in blocks:
        info, block = item if with_info else (None, item)
        group.append(block)
        infos.append(info)
        if len(group) >= k:
            yield _group_from_blocks(group, infos, val_dtype)
            group, infos = [], []
    if group:
        yield _group_from_blocks(group, infos, val_dtype)


def superbatches_from_blocks_dynamic(
    blocks: Iterable, k_fn, with_info: bool = False,
    val_dtype=np.float32,
) -> Iterator[SuperbatchGroup]:
    """The adaptive-K analog of :func:`superbatches_from_blocks`: the
    group size is re-read from ``k_fn()`` at every group boundary, so a
    controller moves the tiling between groups on streams that only
    offer pre-built blocks (derived/prefetched streams — dispatch
    fusion only, like the fixed generic path)."""
    group: list = []
    infos: list = []
    want = max(1, int(k_fn()))
    for item in blocks:
        info, block = item if with_info else (None, item)
        group.append(block)
        infos.append(info)
        if len(group) >= want:
            yield _group_from_blocks(group, infos, val_dtype)
            group, infos = [], []
            want = max(1, int(k_fn()))
    if group:
        yield _group_from_blocks(group, infos, val_dtype)


def iter_superbatches(stream, k: int) -> Iterator[SuperbatchGroup]:
    """Superbatch groups for any stream: the stream's own packer when it
    offers one (``SimpleEdgeStream.superbatches`` routes to the
    Windower's zero-per-window-device-work fast path;
    ``autockpt._SkipStream`` wraps the inner packer with a
    group-granular replay skip), else generic packing of its block
    iterator. Streams can OPT OUT of the fast path by setting
    ``superbatches = None``.

    On the generic path the block iterator is prefetched
    :func:`~gelly_streaming_tpu.core.pipeline.superbatch_prefetch_depth`
    windows deep — per-window block assembly still happens on that path
    (the blocks pre-exist), so a depth sized for the per-window cadence
    would stall each group behind its own K assemblies."""
    fast = getattr(stream, "superbatches", None)
    if callable(fast):
        yield from fast(k)
        return
    from .pipeline import prefetch, superbatch_prefetch_depth

    yield from superbatches_from_blocks(
        prefetch(stream.blocks(), superbatch_prefetch_depth(k)), k
    )


def iter_superbatches_dynamic(stream, k_fn) -> Iterator[SuperbatchGroup]:
    """Adaptive-K superbatch groups for any stream — the
    ``superbatch="auto"`` analog of :func:`iter_superbatches`: the
    stream's own dynamic packer when it offers one
    (``SimpleEdgeStream.superbatches_dynamic`` routes to the Windower's
    zero-per-window-device-work fast path;
    ``autockpt._SkipStream.superbatches_dynamic`` adds the resume
    skip), else generic dynamic packing of its block iterator."""
    fast = getattr(stream, "superbatches_dynamic", None)
    if callable(fast):
        yield from fast(k_fn)
        return
    from .pipeline import prefetch, superbatch_prefetch_depth

    yield from superbatches_from_blocks_dynamic(
        prefetch(
            stream.blocks(),
            superbatch_prefetch_depth(max(1, int(k_fn()))),
        ),
        k_fn,
    )


def blocks_from_edges(
    edges: Iterable[Tuple],
    window_size: int,
    vertex_dict: Optional[VertexDict] = None,
    **kw: Any,
) -> Iterator[EdgeBlock]:
    """Convenience: count-window discretization of an edge iterable."""
    w = Windower(CountWindow(window_size), vertex_dict, **kw)
    return w.blocks(edges)
