"""Sharded parallel ingest: the million-writes path (ISSUE 11).

The engine sustains multi-million-eps window dispatch, but until now
every edge entered through ONE Python reader yielding tuples one at a
time (``core/sources.py``). The reference distributes exactly this
stage via Flink's keyed shuffle between its source and windowing layers
(PAPER.md §1 L1/L2: parallel sources -> keyBy -> per-key windows). This
module is the TPU-native equivalent, kept on the host:

- :class:`ShardedEdgeSource` — N concurrent TCP connections, one per
  shard, records partitioned by **edge-endpoint hash**
  (:func:`shard_of`, the one partition rule the producer, the readers,
  and the oracle tests all share — the keyed-shuffle analog). Each
  shard's reader thread decodes, assembles **per-shard count windows**,
  and hands closed windows over a bounded queue; the merge side yields
  them in arrival order.
- **GSEW binary wire format** — length-prefixed frames carrying raw
  little-endian i32/i64 edge columns (the PR 8 ``GSRP`` frame codec is
  the template), decoded into numpy columns by ONE native call per
  frame (``native.decode_edge_frame``; numpy fallback without the
  toolchain) instead of per-line ``int()``. Frames carry a
  per-connection sequence number, so a reconnecting peer can replay
  from any earlier point (**at-least-once**) and the reader dedupes to
  exactly-once at frame granularity.
- **Explicit backpressure** — each shard queue is BOUNDED
  (:func:`~gelly_streaming_tpu.core.pipeline.bounded_put`): a slow
  consumer blocks the reader's put, which stops ``recv``, which lets
  TCP flow control push back on the producer. Overload degrades to
  bounded staleness, never unbounded buffering. Evidence:
  ``source.shard_depth{shard}`` gauge, ``source.backpressure_s``
  counter, ``source.backpressure_stalls/resumes{shard}`` episode
  counters (the timeline's INGEST-STALL / INGEST-RESUME story lines).
- :class:`ShardedEdgeStream` — merges closed shard windows into the
  existing block/superbatch execution path: per-window blocks via the
  shared :class:`~gelly_streaming_tpu.core.window.Windower`, and
  ``superbatches(k)`` packs K closed windows with ONE group encode and
  zero per-window device work
  (:meth:`~gelly_streaming_tpu.core.window.Windower.pack_window_cols`).

RESILIENCE (the ``SocketEdgeSource`` contract, reused): connection
errors reconnect with bounded exponential backoff (``reconnect``
attempts, ``source.reconnects`` counted); a malformed byte stream —
bad magic/version, oversized or geometry-inconsistent length, torn
frames — is a counted ``source.malformed_frames{kind}`` plus a clean
reconnect (framing cannot resync mid-garbage), never a dead reader
thread. A CLEAN peer close at a frame boundary ends that shard. The
installed :class:`~gelly_streaming_tpu.resilience.FaultPlan`'s
``disconnect_at_record`` fires per record ordinal, dropping the whole
in-flight frame so the peer's replay re-delivers it exactly once.

``python -m gelly_streaming_tpu.core.ingest --serve ...`` is the
serve-from-memory load-generator peer ``bench.py --ingest`` spawns:
it synthesizes an R-MAT stream, partitions it with :func:`shard_of`,
pre-encodes each shard's frames (or text lines), and serves each
connection from memory.
"""

from __future__ import annotations

import queue
import socket as _socket
import struct
import threading
import time
import warnings
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import trace as _trace
from ..obs.registry import get_registry
from ..resilience import faults as _faults
from ..resilience.errors import TransientSourceError
from ..resilience.retry import exp_backoff
from .pipeline import bounded_put
from .stream import SimpleEdgeStream

# --------------------------------------------------------------------- #
# GSEW wire format
# --------------------------------------------------------------------- #
#: frame magic (also the protocol's garbage detector)
MAGIC = b"GSEW"
VERSION = 1
#: GSEW v2: identical header/column layout plus the optional i64
#: event-timestamp column (``F_TS``). v1 frames stay byte-identical —
#: a ts-less stream never pays the version bump, and every reader
#: accepts both (the ISSUE 18 wire compat rule).
VERSION_TS = 2
#: header: magic | version | flags | n_edges | payload length | sequence
HEADER = struct.Struct("<4sBBIIQ")
#: flags bit 0: int64 endpoint columns (else int32)
F_WIDE = 1
#: flags bit 1: float64 value column present
F_VAL = 2
#: flags bit 2: int64 event-timestamp column present (v2 frames only;
#: the column rides LAST in the payload so the native column decoder
#: consumes the unchanged prefix)
F_TS = 4
#: reject frames declaring more edges than this before reading them
MAX_FRAME_EDGES = 1 << 22
#: reject payloads past this byte length before reading them
DEFAULT_MAX_FRAME = 64 << 20

_DONE = object()  # per-shard end-of-stream sentinel on the window queue


class Disconnect(Exception):
    """Peer closed at a frame boundary — the clean end of a shard."""


class MalformedFrame(ValueError):
    """The byte stream violated the frame contract; ``kind`` is the
    ``source.malformed_frames{kind=...}`` label (magic/version/
    oversized/columns/truncated/ts_missing)."""

    def __init__(self, kind: str, msg: str):
        super().__init__(msg)
        self.kind = kind


class _Stopped(Exception):
    """Internal unwind: the source was closed mid-read."""


def pack_edge_frame(
    src: np.ndarray,
    dst: np.ndarray,
    val: Optional[np.ndarray] = None,
    *,
    seq: int = 0,
    wide: Optional[bool] = None,
    ts: Optional[np.ndarray] = None,
) -> bytes:
    """Encode one GSEW frame: header + raw little-endian columns
    (src, then dst, then the optional float64 value column, then the
    optional int64 event-timestamp column).

    ``wide=None`` picks int32 columns when every id fits (half the
    wire bytes — the common dense-id case), int64 otherwise. ``seq``
    is the per-connection frame sequence number (1-based; 0 = unknown,
    never deduped) the reader uses to drop at-least-once replays.
    ``ts`` makes the frame GSEW v2 (``F_TS``); without it the frame is
    byte-identical v1 — old readers never see a version they cannot
    parse unless the stream actually carries event time.
    """
    src = np.ascontiguousarray(src, np.int64)
    dst = np.ascontiguousarray(dst, np.int64)
    n = src.shape[0]
    if dst.shape[0] != n:
        raise ValueError("src/dst column lengths disagree")
    if n > MAX_FRAME_EDGES:
        raise ValueError(
            f"{n} edges exceeds the {MAX_FRAME_EDGES}-edge frame bound"
        )
    if wide is None:
        i32 = np.iinfo(np.int32)
        wide = bool(n) and bool(
            min(int(src.min()), int(dst.min())) < i32.min
            or max(int(src.max()), int(dst.max())) > i32.max
        )
    # encoder and reader must agree on BOTH bounds (the GL011 ethos):
    # a frame the encoder emits but every reader rejects as oversized
    # would dead-loop the replay path, so reject it at pack time
    nbytes = (
        n * (8 if wide else 4) * 2
        + (8 * n if val is not None else 0)
        + (8 * n if ts is not None else 0)
    )
    if nbytes > DEFAULT_MAX_FRAME:
        raise ValueError(
            f"frame payload of {nbytes} bytes exceeds the reader bound "
            f"{DEFAULT_MAX_FRAME}; lower frame_edges (wide/val/ts "
            "columns cost up to 32 bytes per edge)"
        )
    dt = "<i8" if wide else "<i4"
    flags = (
        (F_WIDE if wide else 0)
        | (F_VAL if val is not None else 0)
        | (F_TS if ts is not None else 0)
    )
    parts = [src.astype(dt, copy=False).tobytes(),
             dst.astype(dt, copy=False).tobytes()]
    if val is not None:
        val = np.ascontiguousarray(val, np.float64)
        if val.shape[0] != n:
            raise ValueError("val column length disagrees with src/dst")
        parts.append(val.astype("<f8", copy=False).tobytes())
    if ts is not None:
        ts = np.ascontiguousarray(ts, np.int64)
        if ts.shape[0] != n:
            raise ValueError("ts column length disagrees with src/dst")
        parts.append(ts.astype("<i8", copy=False).tobytes())
    payload = b"".join(parts)
    version = VERSION_TS if ts is not None else VERSION
    return HEADER.pack(MAGIC, version, flags, n, len(payload), seq) + payload


def decode_frame_payload(payload: bytes, n_edges: int, flags: int):
    """Decode a frame payload into ``(src i64, dst i64, val f64|None)``
    columns — one native call per frame
    (:func:`gelly_streaming_tpu.native.decode_edge_frame`) — plus a
    trailing ``ts i64`` column when the frame carries ``F_TS`` (the
    return arity mirrors the flags, the codec-symmetry rule: a v1
    frame decodes exactly as it always did). The ts column rides LAST
    in the payload precisely so the native decoder's prefix stays
    byte-identical across versions."""
    from .. import native as _native

    ts = None
    if flags & F_TS:
        tail = 8 * n_edges
        if len(payload) < tail:
            raise MalformedFrame(
                "columns",
                f"payload of {len(payload)} bytes cannot carry a "
                f"{tail}-byte ts column",
            )
        ts = np.frombuffer(
            payload, "<i8", n_edges, len(payload) - tail
        ).astype(np.int64, copy=True)
        payload = payload[:-tail] if tail else payload
    try:
        cols = _native.decode_edge_frame(
            payload, n_edges, bool(flags & F_WIDE), bool(flags & F_VAL)
        )
    except ValueError as e:
        raise MalformedFrame("columns", str(e)) from e
    return cols if ts is None else cols + (ts,)


def frame_geometry(n_edges: int, flags: int) -> int:
    """Payload byte length the header's (n_edges, flags) pair implies."""
    isz = 8 if flags & F_WIDE else 4
    return (
        n_edges * isz * 2
        + (8 * n_edges if flags & F_VAL else 0)
        + (8 * n_edges if flags & F_TS else 0)
    )


def read_edge_frame(
    sock,
    *,
    max_edges: int = MAX_FRAME_EDGES,
    max_frame: int = DEFAULT_MAX_FRAME,
    stop: Optional[threading.Event] = None,
) -> Tuple[int, int, int, bytes]:
    """One complete frame off the socket: ``(seq, flags, n_edges,
    payload)``. Raises :class:`Disconnect` at a clean frame boundary,
    :class:`MalformedFrame` for everything the frame contract rejects,
    and re-raises ``socket.timeout`` only when it struck at a boundary
    with nothing read (an idle tick the caller may poll through)."""
    head = _recv_exact(sock, HEADER.size, at_boundary=True, stop=stop)
    magic, version, flags, n_edges, plen, seq = HEADER.unpack(head)
    if magic != MAGIC:
        raise MalformedFrame("magic", f"bad magic {magic!r}")
    if version not in (VERSION, VERSION_TS):
        raise MalformedFrame("version", f"unsupported version {version}")
    if version == VERSION and flags & F_TS:
        # the ts column is exactly what v2 versions: a v1 frame
        # claiming one is a contract violation, not a decode attempt
        raise MalformedFrame(
            "version", "ts column flag requires a version-2 frame"
        )
    if n_edges > max_edges or plen > max_frame:
        raise MalformedFrame(
            "oversized",
            f"frame declares {n_edges} edges / {plen} payload bytes "
            f"(bounds: {max_edges} edges, {max_frame} bytes)",
        )
    want = frame_geometry(n_edges, flags)
    if plen != want:
        raise MalformedFrame(
            "columns",
            f"payload length {plen} disagrees with the column geometry "
            f"{want} (n={n_edges}, flags={flags})",
        )
    payload = _recv_exact(sock, plen, stop=stop) if plen else b""
    return seq, flags, n_edges, payload


def _recv_exact(
    sock,
    n: int,
    *,
    at_boundary: bool = False,
    stop: Optional[threading.Event] = None,
) -> bytes:
    """Read exactly ``n`` bytes. An orderly EOF (``recv() == b""``,
    i.e. the peer's FIN) before the FIRST byte of a frame is a clean
    :class:`Disconnect` — the ONLY clean end; a reset at a boundary
    re-raises as the OSError it is (a reconnectable failure, never a
    silent end-of-stream). EOF or a reset mid-frame is a
    :class:`MalformedFrame` (``truncated``). A receive timeout at a
    boundary with nothing read propagates (the reader's idle/stop poll
    tick); mid-frame it keeps waiting — a slow peer is not a torn one —
    unless ``stop`` was set, which unwinds via :class:`_Stopped`."""
    buf = b""
    while len(buf) < n:
        if stop is not None and stop.is_set():
            raise _Stopped()
        try:
            chunk = sock.recv(n - len(buf))
        except _socket.timeout:
            if at_boundary and not buf:
                raise
            continue
        except OSError as e:
            if at_boundary and not buf:
                # a reset between frames is NOT a clean close: only the
                # peer's FIN (empty recv below) may end the shard —
                # mapping resets to Disconnect would silently truncate
                # the stream while budget remains to reconnect
                raise
            raise MalformedFrame(
                "truncated",
                f"connection lost after {len(buf)}/{n} bytes: {e!r}",
            ) from e
        if not chunk:
            if at_boundary and not buf:
                raise Disconnect("peer closed")
            raise MalformedFrame(
                "truncated", f"peer closed after {len(buf)}/{n} bytes"
            )
        buf += chunk
    return buf


# --------------------------------------------------------------------- #
# Partitioning: the keyed-shuffle rule
# --------------------------------------------------------------------- #
def shard_of(src, dst, nshards: int) -> np.ndarray:
    """Deterministic edge -> shard assignment by endpoint hash.

    THE one partition rule (same ethos as ``window.is_column_input``):
    the load generator, any real producer, and the oracle tests must
    agree on which shard owns an edge, so the rule lives in exactly one
    place. Vectorized 64-bit mix of both endpoints; stable across runs
    and processes."""
    s = np.asarray(src).astype(np.uint64)
    d = np.asarray(dst).astype(np.uint64)
    h = s * np.uint64(0x9E3779B97F4A7C15) ^ (
        d * np.uint64(0xC2B2AE3D27D4EB4F)
    )
    h ^= h >> np.uint64(33)
    return (h % np.uint64(nshards)).astype(np.int64)


def partition_edges(
    src, dst, val=None, nshards: int = 1, ts=None
) -> List[Tuple]:
    """Split edge columns into per-shard column triples, stream order
    preserved within each shard (what a keyed shuffle delivers). With
    ``ts`` (an aligned i64 event-timestamp column) each entry is the
    4-tuple ``(src, dst, val|None, ts)`` instead — order preservation
    is what keeps per-shard watermarks honest."""
    src = np.asarray(src)
    dst = np.asarray(dst)
    owner = shard_of(src, dst, nshards)
    out = []
    for i in range(nshards):
        m = owner == i
        cols = (
            src[m], dst[m], None if val is None else np.asarray(val)[m]
        )
        if ts is not None:
            cols = cols + (np.asarray(ts, np.int64)[m],)
        out.append(cols)
    return out


def vertex_owner(ids, nshards: int) -> np.ndarray:
    """Deterministic vertex -> shard assignment for SERVING keyspace
    partitioning: the one vertex rule, DERIVED from :func:`shard_of`
    (a vertex is the degenerate edge ``(v, v)``) so producers, the
    query router, and the oracle tests all agree through one hash."""
    return shard_of(ids, ids, nshards)


def partition_edges_by_vertex(
    src, dst, val=None, nshards: int = 1
) -> List[Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]]:
    """Split edge columns by VERTEX ownership: each edge is delivered
    to the owner of EACH endpoint (one copy when both endpoints share
    an owner), stream order preserved within each shard.

    This is the sharded-serving delivery rule (:func:`vertex_owner`):
    a vertex's owner shard receives every edge incident to it, so
    per-vertex answers (degree, rank mass) are owner-complete, while
    global connectivity stays reconstructable as the union of per-shard
    summaries (every edge lives in at least one shard)."""
    src = np.asarray(src)
    dst = np.asarray(dst)
    os_ = vertex_owner(src, nshards)
    od = vertex_owner(dst, nshards)
    out = []
    for i in range(nshards):
        m = (os_ == i) | (od == i)
        out.append((
            src[m], dst[m], None if val is None else np.asarray(val)[m]
        ))
    return out


# --------------------------------------------------------------------- #
# Ownership epochs: the elastic-resharding rule
# --------------------------------------------------------------------- #
def split_side(ids, salt: int) -> np.ndarray:
    """The per-vertex coin of ONE split generation: a salt-keyed 64-bit
    finalizer over the raw vertex id, reduced to its low bit. True
    means the vertex moves to the split's CHILD shard, False means it
    stays with the parent. Deterministic across processes (the same
    ethos as :func:`shard_of`) and INDEPENDENT of the base hash — the
    salt decorrelates the coin from ``vertex_owner``'s bucket choice so
    a split moves ~half the parent's keyspace, not a skewed sliver."""
    h = np.asarray(ids).astype(np.uint64) ^ np.uint64(salt & (2**64 - 1))
    h = (h ^ (h >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    h = (h ^ (h >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    h ^= h >> np.uint64(31)
    return (h & np.uint64(1)).astype(bool)


def vertex_owner_epoch(ids, nshards: int, splits=()) -> np.ndarray:
    """Vertex ownership under an epoch of live splits: epoch 0 is
    :func:`vertex_owner` over the BOOT shard count, and each entry of
    ``splits`` (applied in order — the ownership epoch is the prefix
    length) re-assigns the parent-owned vertices whose
    :func:`split_side` coin came up True to the split's child shard.

    Every ruling party — routers fanning out, the load generator
    aiming keys, the oracle tests — derives ownership through THIS one
    function, so a split can never make two components disagree about
    who owns a vertex at a given epoch. A split dict carries
    ``{"parent": int, "child": int, "salt": int}``; the salt is chosen
    by the split coordinator (one per split) and travels inside the
    elected plan."""
    own = vertex_owner(ids, nshards)
    for sp in splits:
        parent = int(sp["parent"])
        child = int(sp["child"])
        m = own == parent
        if not np.any(m):
            continue
        side = split_side(np.asarray(ids)[m], int(sp["salt"]))
        moved = own[m]
        moved[side] = child
        own[m] = moved
    return own


# --------------------------------------------------------------------- #
# The sharded source
# --------------------------------------------------------------------- #
class _Shard:
    """One connection's reader state: the bounded window queue, the
    replay-dedup watermark, and lazily-resolved obs instruments."""

    __slots__ = ("index", "addr", "q", "thread", "error", "last_seq",
                 "nrec", "pend", "have", "watermark", "_gauge", "_stall",
                 "_resume", "_late")

    def __init__(self, index: int, addr, depth: int):
        self.index = index
        self.addr = addr
        self.q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self.thread: Optional[threading.Thread] = None
        self.error: Optional[BaseException] = None
        self.last_seq = 0   # highest accepted frame seq (replay dedup)
        self.nrec = 0       # accepted-record ordinal (fault hook index)
        self.pend: list = []  # buffered column triples of the open window
        self.have = 0
        # per-shard event-time watermark: max observed ts (monotone;
        # GSEW preserves per-shard arrival order so the max IS the
        # promise). Written only by this shard's reader thread; the
        # cross-shard merge happens on demand at the consumer.
        self.watermark: Optional[int] = None
        self._gauge = None
        self._stall = None
        self._resume = None
        self._late = None  # lazy eventtime.late_dropped counter


class ShardedEdgeSource:
    """N concurrent shard connections feeding per-shard count windows.

    ``addresses`` is one ``(host, port)`` per shard; the peer must serve
    each connection the records :func:`shard_of` assigns to that shard
    (the keyed-shuffle contract — :func:`partition_edges` implements it
    for in-memory producers, the ``--serve`` CLI for subprocesses).
    ``window`` is the per-shard count-window size; closed windows are
    handed over a bounded queue of ``queue_windows`` entries — the
    explicit backpressure boundary (see the module docstring).

    ``fmt="binary"`` reads GSEW frames (exactly-once across reconnects
    via frame sequence dedup); ``fmt="text"`` reads the line protocol
    ``SocketEdgeSource`` speaks, batch-parsed natively per recv
    (at-least-once across reconnects — lines carry no sequence).

    Consume via :meth:`windows` (closed windows in arrival order) or
    :meth:`stream` (a :class:`ShardedEdgeStream` on the block/superbatch
    execution path). Single-use, like every stream source here.
    """

    def __init__(
        self,
        addresses: Sequence[Tuple[str, int]],
        *,
        window: int,
        fmt: str = "binary",
        queue_windows: int = 4,
        weighted: bool = False,
        timestamps: bool = False,
        allowed_lateness_s: int = 0,
        tick_s: float = 0.2,
        reconnect: int = 5,
        reconnect_base_s: float = 0.05,
        reconnect_max_s: float = 2.0,
        stall_event_s: float = 0.5,
        max_frame_edges: int = MAX_FRAME_EDGES,
    ):
        if fmt not in ("binary", "text"):
            raise ValueError(f"fmt must be binary/text, got {fmt!r}")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if timestamps and fmt != "binary":
            raise ValueError(
                "timestamps=True requires fmt='binary' (the line "
                "protocol carries no ts column; use SocketEdgeSource's "
                "ts extractor for text streams)"
            )
        if allowed_lateness_s < 0:
            raise ValueError(
                f"allowed_lateness_s must be >= 0, got {allowed_lateness_s}"
            )
        self.window = int(window)
        self.fmt = fmt
        self.weighted = weighted
        self.timestamps = bool(timestamps)
        self.allowed_lateness_s = int(allowed_lateness_s)
        self.tick_s = float(tick_s)
        self.reconnect = int(reconnect)
        self.reconnect_base_s = float(reconnect_base_s)
        self.reconnect_max_s = float(reconnect_max_s)
        self.stall_event_s = float(stall_event_s)
        self.max_frame_edges = int(max_frame_edges)
        self._stop = threading.Event()
        self._tokens: "queue.Queue[int]" = queue.Queue()
        self._shards = [
            _Shard(i, tuple(a), queue_windows)
            for i, a in enumerate(addresses)
        ]
        self._started = False
        self._consumed = False
        self._ended: set = set()  # shards whose _DONE was consumed

    @property
    def nshards(self) -> int:
        return len(self._shards)

    # ------------------------------------------------------------------ #
    def start(self) -> "ShardedEdgeSource":
        if self._started:
            return self
        self._started = True
        for sh in self._shards:
            t = threading.Thread(
                target=self._run_reader, args=(sh,), daemon=True,
                name=f"ingest-shard-{sh.index}",
            )
            sh.thread = t
            t.start()
        return self

    def close(self, join_timeout_s: float = 10.0) -> None:
        self._stop.set()
        # ONE total budget across every reader join (the GL008 deadline
        # discipline): N slow threads share join_timeout_s, they do not
        # each get a fresh one
        deadline = time.monotonic() + join_timeout_s
        for sh in self._shards:
            t = sh.thread
            if t is None:
                continue
            t.join(timeout=max(0.0, deadline - time.monotonic()))
            if t.is_alive():
                # same posture as pipeline.prefetch: a reader that never
                # honored the stop flag is a silent leak — surface it
                get_registry().counter("source.reader_leaked").inc()
                warnings.warn(
                    f"ingest shard {sh.index}: reader thread did not "
                    f"exit within {join_timeout_s}s of close; thread "
                    "(and its socket) leaked",
                    RuntimeWarning,
                    stacklevel=2,
                )

    # ------------------------------------------------------------------ #
    def windows(
        self,
    ) -> Iterator[Tuple[int, np.ndarray, np.ndarray, Optional[np.ndarray]]]:
        """Yield ``(shard, src, dst, val|None)`` closed windows in
        arrival order until every shard ends cleanly. Single use. A
        shard's reader error (exhausted reconnect budget, injected
        fatal) re-raises HERE, after its queued windows drained."""
        for sh, item in self._merged_items():
            yield (sh.index,) + item[:3]

    def windows_ts(
        self,
    ) -> Iterator[Tuple[int, np.ndarray, np.ndarray,
                        Optional[np.ndarray], np.ndarray]]:
        """Yield ``(shard, src, dst, val|None, ts)`` closed windows in
        arrival order — the event-time consumer surface (what
        :func:`gelly_streaming_tpu.eventtime.stream.drive_sliding`
        drives). Requires ``timestamps=True``; single use."""
        if not self.timestamps:
            raise RuntimeError(
                "windows_ts() requires ShardedEdgeSource(timestamps=True)"
            )
        for sh, item in self._merged_items():
            yield (sh.index,) + item

    def _merged_items(self):
        if self._consumed:
            raise RuntimeError("ShardedEdgeSource is single-use")
        self._consumed = True
        self.start()
        done = 0
        n = len(self._shards)
        try:
            while done < n:
                try:
                    tok = self._tokens.get(timeout=1.0)
                except queue.Empty:
                    if self._stop.is_set():
                        return
                    if not any(
                        sh.thread is not None and sh.thread.is_alive()
                        for sh in self._shards
                    ) and all(sh.q.empty() for sh in self._shards):
                        raise RuntimeError(
                            "ingest reader threads died without handoff"
                        )
                    continue
                sh = self._shards[tok]
                try:
                    item = sh.q.get_nowait()
                except queue.Empty:
                    continue  # close() raced the token; nothing to do
                if item is _DONE:
                    done += 1
                    self._ended.add(sh.index)
                    if sh.error is not None:
                        raise sh.error
                    continue
                yield sh, item
        finally:
            self.close()

    # ------------------------------------------------------------------ #
    # Event-time progress (timestamps=True)
    # ------------------------------------------------------------------ #
    def shard_watermarks(self) -> List[int]:
        """Per-shard watermarks (max accepted ts; ``NO_WATERMARK`` for
        a shard that has not observed event time yet)."""
        from ..eventtime.watermark import NO_WATERMARK

        return [
            NO_WATERMARK if sh.watermark is None else sh.watermark
            for sh in self._shards
        ]

    def watermark(self) -> int:
        """The merged event-time watermark: the min over LIVE shards'
        marks (THE cross-shard rule,
        :func:`gelly_streaming_tpu.eventtime.watermark.merge_watermarks`).
        Ended shards leave the merge — a closed stream holds nothing
        back."""
        from ..eventtime.watermark import NO_WATERMARK, merge_watermarks

        return merge_watermarks(
            NO_WATERMARK if sh.watermark is None else sh.watermark
            for sh in self._shards
            if sh.index not in self._ended
        )

    def stream(self, vertex_dict=None, context=None, *,
               val_dtype=np.float32) -> "ShardedEdgeStream":
        """The merged stream on the block/superbatch execution path."""
        return ShardedEdgeStream(
            self, vertex_dict=vertex_dict, context=context,
            val_dtype=val_dtype,
        )

    # ------------------------------------------------------------------ #
    # Reader threads
    # ------------------------------------------------------------------ #
    def _run_reader(self, sh: _Shard) -> None:
        try:
            if self.fmt == "binary":
                self._read_binary(sh)
            else:
                self._read_text(sh)
        except _Stopped:
            pass
        except BaseException as e:
            # not a swallow: the error is COUNTED here and re-raised at
            # the consumer's merge loop once this shard's queue drains
            sh.error = e
            get_registry().counter(
                "source.reader_errors", shard=str(sh.index)
            ).inc()
        finally:
            if bounded_put(sh.q, _DONE, self._stop):
                self._tokens.put(sh.index)

    def _read_binary(self, sh: _Shard) -> None:
        attempts = 0
        # consecutive malformed frames with NO new data accepted in
        # between: a deterministic mid-stream corruption would otherwise
        # reconnect forever (each replay's intact prefix refills the
        # reconnect budget while seq-dedup yields no progress)
        malformed_streak = 0
        while not self._stop.is_set():
            try:
                sock = _socket.create_connection(sh.addr, timeout=5.0)
            except OSError as e:
                attempts += 1
                self._backoff(sh, attempts, e)
                continue
            clean = False
            failure: Optional[Exception] = None  # reconnect cause
            try:
                sock.settimeout(self.tick_s)
                while True:
                    try:
                        seq, flags, n, payload = read_edge_frame(
                            sock, max_edges=self.max_frame_edges,
                            stop=self._stop,
                        )
                    except _socket.timeout:
                        if self._stop.is_set():
                            raise _Stopped() from None
                        continue  # idle boundary tick
                    except Disconnect:
                        clean = True
                        break
                    attempts = 0  # an intact frame refills the budget
                    if seq and seq <= sh.last_seq:
                        # at-least-once replay after a reconnect: the
                        # peer re-served an already-accepted frame
                        get_registry().counter(
                            "source.replayed_frames", shard=str(sh.index)
                        ).inc()
                        continue
                    with _trace.span(
                        "ingest.decode",
                        {"edges": int(n), "shard": sh.index}
                        if _trace.on() else None,
                    ):
                        cols = decode_frame_payload(payload, n, flags)
                    src, dst, val = cols[:3]
                    ts = cols[3] if len(cols) > 3 else None
                    if self.timestamps and ts is None:
                        # a ts-expecting reader fed a ts-less stream is
                        # a misconfigured pairing, not decodable data:
                        # counted malformed + reconnect, and the streak
                        # guard classifies the determinism
                        raise MalformedFrame(
                            "ts_missing",
                            "reader expects event timestamps but the "
                            "frame carries no ts column (GSEW v1 peer?)",
                        )
                    # fault hook BEFORE the frame is accepted: an
                    # injected disconnect drops the WHOLE frame (seq
                    # watermark unmoved), so the peer's replay
                    # re-delivers it exactly once
                    if _faults.active():
                        for j in range(n):
                            _faults.fire(
                                "source.record", index=sh.nrec + j
                            )
                    if seq:
                        sh.last_seq = seq
                    sh.nrec += n
                    malformed_streak = 0  # real progress, not a replay
                    if not self.weighted:
                        val = None
                    if not self.timestamps:
                        ts = None  # tolerated, unused: count windows
                    elif ts is not None and len(ts):
                        ts, src, dst, val = self._drop_late(
                            sh, ts, src, dst, val
                        )
                        hi = int(ts.max()) if len(ts) else None
                        if hi is not None and (
                            sh.watermark is None or hi > sh.watermark
                        ):
                            sh.watermark = hi
                        if not len(src):
                            continue
                    if not self._buffer_cols(sh, src, dst, val, ts):
                        raise _Stopped()
            except MalformedFrame as e:
                # counted evidence + clean reconnect: framing cannot
                # resync mid-garbage, so the connection is dropped and
                # the budgeted backoff below applies
                self._count_malformed(sh, e.kind)
                malformed_streak += 1
                failure = e
            except OSError as e:
                # reset / injected disconnect mid-stream: reconnect;
                # the in-flight frame died with the connection and the
                # peer re-serves it (at-least-once, deduped by seq)
                failure = e
            finally:
                sock.close()
            if clean:
                self._flush_tail(sh)
                return
            if failure is not None:
                if malformed_streak > self.reconnect:
                    # the stream is corrupt, not flaky: every reconnect
                    # replays the same garbage at the same point — give
                    # up with a classified error instead of looping
                    raise TransientSourceError(
                        f"ingest shard {sh.index} "
                        f"({sh.addr[0]}:{sh.addr[1]}): "
                        f"{malformed_streak} consecutive malformed "
                        "frames with no new data between reconnects"
                    ) from failure
                # backoff AFTER teardown, outside the handler: an
                # exhausted budget raises TransientSourceError (a
                # ConnectionError), which the except OSError above
                # must never re-catch
                attempts += 1
                self._backoff(sh, attempts, failure)

    def _read_text(self, sh: _Shard) -> None:
        attempts = 0
        while not self._stop.is_set():
            try:
                sock = _socket.create_connection(sh.addr, timeout=5.0)
            except OSError as e:
                attempts += 1
                self._backoff(sh, attempts, e)
                continue
            buf = b""
            clean = False
            failure: Optional[Exception] = None
            try:
                sock.settimeout(self.tick_s)
                while True:
                    if self._stop.is_set():
                        raise _Stopped()
                    try:
                        data = sock.recv(1 << 16)
                    except _socket.timeout:
                        continue
                    if not data:
                        clean = True
                        break
                    attempts = 0
                    buf += data
                    if b"\n" not in buf:
                        continue
                    lines, buf = buf.rsplit(b"\n", 1)
                    if not self._parse_text_chunk(sh, lines):
                        raise _Stopped()
            except OSError as e:
                failure = e
            finally:
                sock.close()
            if clean:
                if buf.strip():
                    self._parse_text_chunk(sh, buf)
                self._flush_tail(sh)
                return
            if failure is not None:
                attempts += 1
                self._backoff(sh, attempts, failure)

    def _parse_text_chunk(self, sh: _Shard, lines: bytes) -> bool:
        from .. import native as _native

        with _trace.span(
            "ingest.decode",
            {"bytes": len(lines), "shard": sh.index}
            if _trace.on() else None,
        ):
            src, dst, val, malformed = _native.parse_edge_lines(lines)
        if malformed:
            get_registry().counter(
                "source.malformed_lines"
            ).inc(malformed)
        n = len(src)
        if n == 0:
            return True
        if _faults.active():
            for j in range(n):
                _faults.fire("source.record", index=sh.nrec + j)
        sh.nrec += n
        if not self.weighted:
            val = None
        return self._buffer_cols(sh, src, dst, val)

    # ------------------------------------------------------------------ #
    # Window assembly + the backpressure boundary
    # ------------------------------------------------------------------ #
    def _drop_late(self, sh: _Shard, ts, src, dst, val):
        """The source-level lateness policy: a record older than this
        shard's watermark minus ``allowed_lateness_s`` is DROPPED and
        counted ``eventtime.late_dropped`` (the LATE-DROP story line) —
        never silently absorbed into a window that event time already
        passed. Within the allowance, out-of-order records pass through
        (the pane assembler buffers them into their proper pane)."""
        if sh.watermark is None:
            return ts, src, dst, val
        late = ts < sh.watermark - self.allowed_lateness_s
        n_late = int(late.sum())
        if not n_late:
            return ts, src, dst, val
        if sh._late is None:
            sh._late = get_registry().counter(
                "eventtime.late_dropped", shard=str(sh.index)
            )
        sh._late.inc(n_late)
        keep = ~late
        return (
            ts[keep], src[keep], dst[keep],
            None if val is None else val[keep],
        )

    def _buffer_cols(self, sh: _Shard, src, dst, val, ts=None) -> bool:
        from .window import take_cols

        sh.pend.append(
            (src, dst, val) if ts is None else (src, dst, val, ts)
        )
        sh.have += len(src)
        while sh.have >= self.window:
            sh.have -= self.window
            if not self._put_window(sh, take_cols(sh.pend, self.window)):
                return False
        return True

    def _flush_tail(self, sh: _Shard) -> None:
        from .window import take_cols

        if sh.have:
            take = sh.have
            sh.have = 0
            self._put_window(sh, take_cols(sh.pend, take))

    def _put_window(self, sh: _Shard, cols) -> bool:
        stalled = [False]

        def on_wait(waited: float) -> None:
            if not stalled[0] and waited >= self.stall_event_s:
                stalled[0] = True
                if sh._stall is None:
                    sh._stall = get_registry().counter(
                        "source.backpressure_stalls", shard=str(sh.index)
                    )
                sh._stall.inc()

        def on_done(waited: float) -> None:
            if waited > 0:
                get_registry().counter("source.backpressure_s").inc(waited)
            if stalled[0]:
                if sh._resume is None:
                    sh._resume = get_registry().counter(
                        "source.backpressure_resumes", shard=str(sh.index)
                    )
                sh._resume.inc()

        if not bounded_put(
            sh.q, cols, self._stop, on_wait=on_wait, on_done=on_done
        ):
            return False
        if sh._gauge is None:
            sh._gauge = get_registry().gauge(
                "source.shard_depth", shard=str(sh.index)
            )
        sh._gauge.set(sh.q.qsize())
        self._tokens.put(sh.index)
        return True

    def _count_malformed(self, sh: _Shard, kind: str) -> None:
        # every frame-contract violation is counted evidence (the fuzz
        # contract: a malformed byte stream is a clean reconnect, never
        # a dead reader thread — and never a silent one)
        get_registry().counter(
            "source.malformed_frames", kind=kind, shard=str(sh.index)
        ).inc()

    # ------------------------------------------------------------------ #
    def _backoff(self, sh: _Shard, attempts: int, err: Exception) -> None:
        """One budgeted reconnect delay (the ``SocketEdgeSource``
        resilience contract): counted, bounded-exponential, waited out
        in slices so ``close()`` never blocks a full delay. Raises
        :class:`TransientSourceError` past the budget."""
        get_registry().counter("source.reconnects").inc()
        if attempts > self.reconnect:
            raise TransientSourceError(
                f"ingest shard {sh.index} ({sh.addr[0]}:{sh.addr[1]}) "
                f"gave up after {attempts - 1} reconnect attempts"
            ) from err
        delay = exp_backoff(
            attempts - 1, self.reconnect_base_s, self.reconnect_max_s
        )
        while delay > 0:
            if self._stop.is_set():
                raise _Stopped()
            step = min(0.05, delay)
            time.sleep(step)
            delay -= step


# --------------------------------------------------------------------- #
# The merged stream: closed shard windows -> block/superbatch path
# --------------------------------------------------------------------- #
class ShardedEdgeStream(SimpleEdgeStream):
    """A real :class:`~gelly_streaming_tpu.core.stream.SimpleEdgeStream`
    over a :class:`ShardedEdgeSource`'s merged windows: aggregations,
    transforms, emission streams, and serving ingest all work unchanged.

    Per-window blocks go through the shared
    :class:`~gelly_streaming_tpu.core.window.Windower` (one encode + one
    device block per closed shard window), and :meth:`superbatches`
    packs K closed windows with ONE group encode and zero per-window
    device work
    (:meth:`~gelly_streaming_tpu.core.window.Windower.pack_window_cols`)
    — the sharded analog of the count-window column fast path. Single
    use, like the source underneath."""

    def __init__(self, source: ShardedEdgeSource, *, vertex_dict=None,
                 context=None, val_dtype=np.float32):
        from .window import CountWindow, Windower

        windower = Windower(
            CountWindow(source.window), vertex_dict, val_dtype=val_dtype
        )
        self._sharded_source = source
        self._shard_windower = windower
        super().__init__(
            context=context, _blocks=self._shard_blocks,
            _vdict=windower.vertex_dict,
        )

    def _shard_blocks(self):
        w = self._shard_windower
        for _shard, src, dst, val in self._sharded_source.windows():
            yield w._block_from_arrays(src, dst, val)

    def superbatches(self, k: int):
        if k < 1:
            raise ValueError(f"superbatch k must be >= 1, got {k}")

        def gen():
            w = self._shard_windower
            group: list = []
            index = 0
            for _shard, src, dst, val in self._sharded_source.windows():
                group.append((src, dst, val))
                if len(group) >= k:
                    yield w.pack_window_cols(group, index)
                    index += len(group)
                    group = []
            if group:
                yield w.pack_window_cols(group, index)

        return gen()


# --------------------------------------------------------------------- #
# Serve-from-memory peer (the load generator's server half)
# --------------------------------------------------------------------- #
def encode_shard_frames(
    src, dst, val=None, *, frame_edges: int = 8192,
    wide: Optional[bool] = None, ts=None,
) -> bytes:
    """Pre-encode one shard's whole stream as consecutive GSEW frames
    (seq 1..N) — what the serve-from-memory peer sends verbatim.
    ``ts`` (an i64 column aligned with src/dst) makes every frame
    GSEW v2."""
    src = np.asarray(src)
    dst = np.asarray(dst)
    if ts is not None:
        ts = np.asarray(ts, np.int64)
    parts = []
    seq = 0
    for a in range(0, len(src), frame_edges):
        b = a + frame_edges
        seq += 1
        parts.append(pack_edge_frame(
            src[a:b], dst[a:b],
            None if val is None else np.asarray(val)[a:b],
            seq=seq, wide=wide,
            ts=None if ts is None else ts[a:b],
        ))
    return b"".join(parts)


def encode_shard_text(src, dst) -> bytes:
    """One shard's stream as the line protocol (the text baseline)."""
    return "".join(
        f"{int(s)}\t{int(d)}\n"
        for s, d in zip(np.asarray(src).tolist(), np.asarray(dst).tolist())
    ).encode()


def serve_blobs(
    blobs: Sequence[bytes], *, host: str = "127.0.0.1",
    accepts: int = 1, chunk: int = 1 << 18,
) -> Tuple[List[int], List[threading.Thread], threading.Event]:
    """Serve each pre-encoded blob on its own listening port: accept up
    to ``accepts`` connections sequentially and send the WHOLE blob to
    each (a re-accept replays from the start — the at-least-once peer
    the reconnect tests need). Returns ``(ports, threads, stop)``;
    setting ``stop`` ends the accept loops at their next poll."""
    stop = threading.Event()
    ports: List[int] = []
    threads: List[threading.Thread] = []
    for i, blob in enumerate(blobs):
        srv = _socket.create_server((host, 0))
        srv.settimeout(0.2)
        ports.append(srv.getsockname()[1])

        def run(srv=srv, blob=blob, shard=i):
            served = 0
            try:
                while served < accepts and not stop.is_set():
                    try:
                        conn, _ = srv.accept()
                    except _socket.timeout:
                        continue
                    except OSError:
                        # listener torn down under us: the stop path
                        get_registry().counter(
                            "source.swallowed", site="serve_accept"
                        ).inc()
                        return
                    try:
                        for a in range(0, len(blob), chunk):
                            if stop.is_set():
                                break
                            conn.sendall(blob[a:a + chunk])
                    except OSError:
                        # peer vanished mid-send (reconnect tests kill
                        # readers on purpose): count, move to the next
                        # accept — the replay is the contract
                        get_registry().counter(
                            "source.swallowed", site="serve_send"
                        ).inc()
                    finally:
                        conn.close()
                    served += 1
            finally:
                srv.close()

        t = threading.Thread(target=run, daemon=True,
                             name=f"ingest-serve-{i}")
        t.start()
        threads.append(t)
    return ports, threads, stop


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m gelly_streaming_tpu.core.ingest --serve ...`` — the
    serve-from-memory load-generator peer ``bench.py --ingest`` spawns.
    Synthesizes an R-MAT stream, partitions it by :func:`shard_of`,
    pre-encodes per-shard blobs, prints ``{"ports": [...]}`` on stdout
    once ready, serves one connection per shard, and exits."""
    import json
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)

    def take(flag: str, default=None):
        if flag in argv:
            i = argv.index(flag)
            v = argv[i + 1]
            del argv[i:i + 2]
            return v
        return default

    if "--serve" not in argv:
        print(
            "usage: python -m gelly_streaming_tpu.core.ingest --serve "
            "--shards N --edges M [--scale S] [--seed K] "
            "[--format binary|text] [--frame-edges F] [--accepts A] "
            "[--timestamps] [--ts-rate R]",
            file=sys.stderr,
        )
        return 2
    argv.remove("--serve")
    shards = int(take("--shards", "1"))
    n_edges = int(take("--edges", str(1 << 20)))
    scale = int(take("--scale", "20"))
    seed = int(take("--seed", "7"))
    fmt = take("--format", "binary")
    frame_edges = int(take("--frame-edges", "8192"))
    accepts = int(take("--accepts", "1"))
    timestamps = "--timestamps" in argv
    if timestamps:
        argv.remove("--timestamps")
    ts_rate = int(take("--ts-rate", "4096"))
    from ..datasets import rmat_edges

    src, dst = rmat_edges(n_edges, scale, seed=seed)
    ts = None
    if timestamps:
        # synthetic event time: ts_rate edges per tick, monotone over
        # the pre-partition stream (per-shard order preserved, so each
        # shard's watermark promise holds on the wire)
        ts = np.arange(n_edges, dtype=np.int64) // max(1, ts_rate)
    parts = partition_edges(src, dst, None, shards, ts=ts)
    if fmt == "binary":
        blobs = [
            encode_shard_frames(
                p[0], p[1], frame_edges=frame_edges,
                ts=p[3] if timestamps else None,
            )
            for p in parts
        ]
    else:
        blobs = [encode_shard_text(p[0], p[1]) for p in parts]
    ports, threads, _stop = serve_blobs(blobs, accepts=accepts)
    print(json.dumps({
        "ports": ports,
        "edges": int(n_edges),
        "per_shard": [int(len(p[0])) for p in parts],
        "format": fmt,
        "timestamps": bool(timestamps),
    }), flush=True)
    for t in threads:
        t.join()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
