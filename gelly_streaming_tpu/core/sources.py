"""Unbounded / live edge sources.

The reference gets these free from Flink ``DataStream``: sockets
(``env.socketTextStream``), collections, files (SURVEY.md §1 L1;
``/root/reference/pom.xml:19-29`` pulls the whole streaming runtime). The
repo's file/array/iterator ingest covers the bounded cases; this module
adds the LIVE ones — an edge stream with no known end, consumed as it
arrives:

- :class:`SocketEdgeSource` — line-delimited edge records over TCP, the
  ``socketTextStream`` parity path. Since ISSUE 11 the TEXT protocol is
  parsed with the file parser's grammar, one chunk-parse call per
  ``recv`` (``native.parse_edge_lines`` — the AVX-512 line scanner when
  the toolchain is available, the byte-equivalent regex fallback
  otherwise) instead of per-line Python ``split()``/``int()``.
- :class:`GeneratorSource` — unbounded synthetic stream (R-MAT chunks),
  for tests/benches that need "no end" semantics without a network.
  :meth:`GeneratorSource.iter_chunks` exposes the R-MAT columns
  directly (no per-edge tuple round trip); the windower consumes them
  on its chunk fast path, so the load generator is never itself the
  ingest bottleneck.

These are the SINGLE-connection sources. The scale-out path — N
connections partitioned by edge-endpoint hash, the **GSEW binary wire
format** decoded natively, per-shard windowers with explicit
backpressure — lives in :mod:`gelly_streaming_tpu.core.ingest`
(``ShardedEdgeSource``; README "Ingest at scale").

Both yield ``None`` ticks while idle so a
:class:`~gelly_streaming_tpu.core.window.ProcessingTimeWindow` can close
an open window on schedule even when no records arrive — the windower's
records-driven analog of Flink's processing-time timers.

RESILIENCE (ISSUE 4): a live socket must survive the network. Connection
errors — refused connects, resets mid-stream, injected disconnects —
trigger RECONNECT with bounded exponential backoff (``reconnect``
attempts, ``source.reconnects`` counted in the obs registry) instead of
killing the pipeline; only an exhausted budget raises
:class:`~gelly_streaming_tpu.resilience.errors.TransientSourceError`
(which a :class:`~gelly_streaming_tpu.resilience.Supervisor` classifies
as restartable). A CLEAN peer close still ends iteration — that is the
bounded-stream test contract, not a failure. Malformed lines are counted
(``source.malformed_lines``) rather than silently discarded, and both
sources honor an installed
:class:`~gelly_streaming_tpu.resilience.FaultPlan` (record
drop/duplicate/reorder, disconnect-at-record-n) for deterministic chaos
testing.
"""

from __future__ import annotations

import socket
import time
from typing import Iterator, Optional, Tuple

import numpy as np

from ..obs.registry import get_registry
from ..resilience import faults as _faults
from ..resilience.errors import TransientSourceError
from ..resilience.retry import exp_backoff


def _perturbed(records: Iterator) -> Iterator:
    """Route a record iterator through the installed fault plan's
    drop/duplicate/reorder schedule (no-op — and no wrapper generator —
    when no plan with record perturbations is installed)."""
    plan = _faults.plan()
    if plan is not None and plan.perturbs_records():
        return plan.perturb_records(records)
    return records


def _with_ts(records: Iterator, extractor) -> Iterator:
    """Append an event timestamp to each record: ``(s, d, v)`` becomes
    ``(s, d, v, ts)`` via ``extractor(s, d, v) -> int``. ``None`` idle
    ticks pass through. Applied BEFORE the fault-plan perturbation so
    an installed skew schedule (``FaultPlan.skew_records``) jitters the
    extracted ts like any other field — chaos sees the same record
    shape the pipeline does."""
    for rec in records:
        if rec is None:
            yield None
            continue
        yield rec + (int(extractor(*rec)),)


class SocketEdgeSource:
    """Unbounded edge records over TCP (``env.socketTextStream`` parity).

    Lines follow the FILE parser's grammar (``native.parse_edge_lines``;
    space/tab/comma separators, ``#``/``%`` comments, third column as
    number or ``+``/``-`` event flag) and complete lines are parsed in
    ONE chunk-parse call per ``recv`` — the AVX-512 scanner when the
    native toolchain is available, the byte-equivalent regex fallback
    otherwise — instead of per-line Python ``split()``/``int()``
    (ISSUE 11 satellite). Malformed lines (non-blank, non-comment,
    grammar-rejected) are counted into the obs registry
    (``source.malformed_lines``) and skipped, exactly as before; when a
    fault plan is installed the source drops back to per-line parsing
    so record-ordinal faults interleave with parsing exactly as the
    wire delivered them. Iteration ends when the peer closes the
    connection CLEANLY (a live deployment would simply never close).
    ``tick_s``: receive timeout after which a ``None`` time tick is
    yielded instead of a record.

    Connection ERRORS (refused, reset, timeout at connect) reconnect
    with bounded exponential backoff: up to ``reconnect`` consecutive
    failed attempts, each waiting ``reconnect_base_s * 2**attempt``
    capped at ``reconnect_max_s`` — waited out in ``tick_s`` slices
    with a ``None`` tick yielded per slice, so processing-time windows
    keep closing on schedule all the way through an outage. The budget
    resets whenever data arrives; exhausting it raises
    :class:`TransientSourceError`. ``reconnect=0`` restores the
    fail-fast behavior.
    """

    def __init__(
        self,
        host: str,
        port: int,
        tick_s: float = 0.05,
        weighted: bool = False,
        reconnect: int = 5,
        reconnect_base_s: float = 0.05,
        reconnect_max_s: float = 2.0,
        ts_extractor=None,
    ):
        self.host = host
        self.port = port
        self.tick_s = tick_s
        self.weighted = weighted
        self.reconnect = int(reconnect)
        self.reconnect_base_s = float(reconnect_base_s)
        self.reconnect_max_s = float(reconnect_max_s)
        # event-time extractor (ISSUE 18): ``f(s, d, v) -> int`` turns
        # each record into the 4-tuple ``(s, d, v, ts)`` — the line
        # protocol carries no ts column, so event time rides whatever
        # field the deployment encodes it in (typically the value)
        self.ts_extractor = ts_extractor
        self._malformed = None  # lazy counter (registry may be swapped)

    def __iter__(self) -> Iterator[Optional[Tuple]]:
        records = self._records()
        if self.ts_extractor is not None:
            records = _with_ts(records, self.ts_extractor)
        return _perturbed(records)

    # ------------------------------------------------------------------ #
    def _records(self) -> Iterator[Optional[Tuple]]:
        attempts = 0  # consecutive failures since the last received data
        nrec = 0      # record ordinal for the injection hook
        while True:
            try:
                sock = socket.create_connection((self.host, self.port))
            except OSError as e:
                attempts += 1
                yield from self._backoff_ticks(attempts, e)
                continue
            sock.settimeout(self.tick_s)
            buf = b""
            clean_close = False
            try:
                while True:
                    try:
                        data = sock.recv(1 << 16)
                    except socket.timeout:
                        yield None  # idle tick: lets time windows close
                        continue
                    if not data:  # peer closed CLEANLY: the stream's end
                        clean_close = True
                        break
                    attempts = 0  # data flowed: reconnect budget refills
                    buf += data
                    if b"\n" not in buf:
                        continue
                    lines, buf = buf.rsplit(b"\n", 1)
                    if _faults.active():
                        # per-line path: record-ordinal faults must
                        # interleave with parsing exactly as the wire
                        # delivered the lines (a chunk parse would
                        # count lines past an injected disconnect)
                        for line in lines.split(b"\n"):
                            rec = self._parse_one(line)
                            if rec is not None:
                                _faults.fire("source.record", index=nrec)
                                nrec += 1
                                yield rec
                    else:
                        for rec in self._parse_chunk(lines):
                            nrec += 1
                            yield rec
            except OSError as e:
                # reset / injected disconnect mid-stream: reconnect.
                # Parsed-but-unyielded tail records of the dead
                # connection are dropped with it — the peer re-serves
                # (at-least-once), exactly Flink's source-replay shape.
                attempts += 1
                yield from self._backoff_ticks(attempts, e)
                continue
            finally:
                sock.close()
            if clean_close:
                rec = self._parse_one(buf)
                if rec is not None:
                    if _faults.active():
                        _faults.fire("source.record", index=nrec)
                    yield rec
                return

    def _backoff_ticks(self, attempts: int, err: OSError):
        """Record one connection failure, then wait out the
        bounded-exponential delay in ``tick_s`` slices, yielding a
        ``None`` tick per slice — processing-time windows keep closing
        on schedule THROUGH the outage, not only between backoffs.
        Raises :class:`TransientSourceError` past the budget
        (``reconnect=0`` fails fast, the legacy behavior)."""
        get_registry().counter("source.reconnects").inc()
        if attempts > self.reconnect:
            raise TransientSourceError(
                f"socket source {self.host}:{self.port} gave up after "
                f"{attempts - 1} reconnect attempts"
            ) from err
        delay = exp_backoff(
            attempts - 1, self.reconnect_base_s, self.reconnect_max_s
        )
        while True:
            yield None
            if delay <= 0:
                return
            step = min(max(self.tick_s, 1e-3), delay)
            time.sleep(step)
            delay -= step

    def _parse_chunk(self, lines: bytes) -> Iterator[Tuple]:
        """Parse a recv batch of complete lines in ONE chunk-parse call
        (the file parser's grammar; malformed lines counted) and yield
        per-record tuples."""
        from .. import native as _native

        src, dst, val, malformed = _native.parse_edge_lines(lines)
        if malformed:
            self._count_malformed(malformed)
        if self.weighted and val is not None:
            for s, d, v in zip(src.tolist(), dst.tolist(), val.tolist()):
                yield (s, d, v)
        else:
            for s, d in zip(src.tolist(), dst.tolist()):
                yield (s, d, 0.0)

    def _parse_one(self, line: bytes) -> Optional[Tuple]:
        """One line through the same grammar as the chunk path (used on
        the fault-interleaved path and for the clean-close tail)."""
        from .. import native as _native

        src, dst, val, malformed = _native.parse_edge_lines(line)
        if malformed:
            self._count_malformed(malformed)
        if len(src) == 0:
            return None
        v = (
            float(val[0])
            if self.weighted and val is not None
            else 0.0
        )
        return (int(src[0]), int(dst[0]), v)

    def _count_malformed(self, n: int = 1) -> None:
        # a malformed line is DATA the operator should know about, not
        # noise (satellite: no silent discards); resolved lazily so a
        # source built before obs/test registry swaps still reports
        if self._malformed is None:
            self._malformed = get_registry().counter(
                "source.malformed_lines"
            )
        self._malformed.inc(n)


class GeneratorSource:
    """Unbounded synthetic edge stream: R-MAT chunks, forever (or for
    ``limit`` edges when given — tests need an end). Honors an installed
    fault plan's record perturbations like the socket source."""

    def __init__(
        self,
        scale: int = 16,
        chunk: int = 1 << 14,
        seed: int = 0,
        limit: Optional[int] = None,
        ts_rate: Optional[int] = None,
        ts_start: int = 0,
    ):
        self.scale = scale
        self.chunk = chunk
        self.seed = seed
        self.limit = limit
        # synthetic event time (ISSUE 18): ``ts_rate`` edges per tick
        # starting at ``ts_start`` — monotone by construction, so the
        # stream's own max IS a valid watermark promise
        if ts_rate is not None and ts_rate < 1:
            raise ValueError(f"ts_rate must be >= 1, got {ts_rate}")
        self.ts_rate = ts_rate
        self.ts_start = int(ts_start)

    def __iter__(self) -> Iterator[Tuple]:
        if self.ts_rate is not None:
            return _perturbed(self._records_ts())
        return _perturbed(self._records())

    def iter_chunks(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Column-chunk fast path (ISSUE 11 satellite): yield the R-MAT
        ``(src, dst)`` int64 columns directly, no ``.tolist()`` +
        per-edge tuple round trip — the windower's chunk path
        (``Windower.blocks_from_chunks``) consumes these as-is, so the
        synthetic load generator is never itself the bottleneck.

        When an installed fault plan perturbs records, the chunks are
        re-assembled FROM the perturbed record path (perturbation
        schedules are per-record), so chaos runs see identical streams
        on either path."""
        plan = _faults.plan()
        if plan is not None and plan.perturbs_records():
            yield from self._rechunked_records()
            return
        yield from self._column_chunks()

    def _column_chunks(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        from ..datasets import rmat_edges

        produced = 0
        step = 0
        while self.limit is None or produced < self.limit:
            n = self.chunk
            if self.limit is not None:
                n = min(n, self.limit - produced)
            yield rmat_edges(n, self.scale, seed=self.seed + step)
            produced += n
            step += 1

    def _rechunked_records(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        buf_s: list = []
        buf_d: list = []
        for rec in _perturbed(self._records()):
            if rec is None:
                continue
            buf_s.append(rec[0])
            buf_d.append(rec[1])
            if len(buf_s) >= self.chunk:
                yield (np.asarray(buf_s, np.int64),
                       np.asarray(buf_d, np.int64))
                buf_s, buf_d = [], []
        if buf_s:
            yield np.asarray(buf_s, np.int64), np.asarray(buf_d, np.int64)

    def _records(self) -> Iterator[Tuple]:
        for src, dst in self._column_chunks():
            for s, d in zip(src.tolist(), dst.tolist()):
                yield (s, d, 0.0)

    # ------------------------------------------------------------------ #
    # Event time (ISSUE 18)
    # ------------------------------------------------------------------ #
    def _ts_of(self, ordinal: int) -> int:
        return self.ts_start + ordinal // self.ts_rate

    def _records_ts(self) -> Iterator[Tuple]:
        ordinal = 0
        for src, dst in self._column_chunks():
            for s, d in zip(src.tolist(), dst.tolist()):
                yield (s, d, 0.0, self._ts_of(ordinal))
                ordinal += 1

    def iter_chunks_ts(
        self,
    ) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """``iter_chunks`` with the synthetic i64 ts column appended:
        ``(src, dst, ts)`` — what an event-time drive feeds straight
        into :class:`~gelly_streaming_tpu.eventtime.SlidingGraphAggregator`.
        Requires ``ts_rate``. Under an installed fault plan with record
        perturbations the chunks re-assemble from the perturbed record
        path (including any ts skew schedule), like
        :meth:`iter_chunks`."""
        if self.ts_rate is None:
            raise RuntimeError(
                "iter_chunks_ts() requires GeneratorSource(ts_rate=...)"
            )
        plan = _faults.plan()
        if plan is not None and plan.perturbs_records():
            bs: list = []
            bd: list = []
            bt: list = []
            for rec in _perturbed(self._records_ts()):
                if rec is None:
                    continue
                bs.append(rec[0])
                bd.append(rec[1])
                bt.append(rec[3])
                if len(bs) >= self.chunk:
                    yield (np.asarray(bs, np.int64),
                           np.asarray(bd, np.int64),
                           np.asarray(bt, np.int64))
                    bs, bd, bt = [], [], []
            if bs:
                yield (np.asarray(bs, np.int64),
                       np.asarray(bd, np.int64),
                       np.asarray(bt, np.int64))
            return
        produced = 0
        for src, dst in self._column_chunks():
            n = len(src)
            ts = (
                self.ts_start
                + (produced + np.arange(n, dtype=np.int64))
                // self.ts_rate
            )
            produced += n
            yield src, dst, ts
