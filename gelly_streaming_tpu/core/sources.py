"""Unbounded / live edge sources.

The reference gets these free from Flink ``DataStream``: sockets
(``env.socketTextStream``), collections, files (SURVEY.md §1 L1;
``/root/reference/pom.xml:19-29`` pulls the whole streaming runtime). The
repo's file/array/iterator ingest covers the bounded cases; this module
adds the LIVE ones — an edge stream with no known end, consumed as it
arrives:

- :class:`SocketEdgeSource` — line-delimited edge records over TCP, the
  ``socketTextStream`` parity path.
- :class:`GeneratorSource` — unbounded synthetic stream (R-MAT chunks),
  for tests/benches that need "no end" semantics without a network.

Both yield ``None`` ticks while idle so a
:class:`~gelly_streaming_tpu.core.window.ProcessingTimeWindow` can close
an open window on schedule even when no records arrive — the windower's
records-driven analog of Flink's processing-time timers.
"""

from __future__ import annotations

import socket
from typing import Iterator, Optional, Tuple

import numpy as np


class SocketEdgeSource:
    """Unbounded edge records over TCP (``env.socketTextStream`` parity).

    Lines are whitespace- or tab-separated ``src dst [val]``; malformed
    lines and ``#`` comments are skipped, like the file parser. Iteration
    ends when the peer closes the connection (a live deployment would
    simply never close). ``tick_s``: receive timeout after which a
    ``None`` time tick is yielded instead of a record.
    """

    def __init__(
        self,
        host: str,
        port: int,
        tick_s: float = 0.05,
        weighted: bool = False,
    ):
        self.host = host
        self.port = port
        self.tick_s = tick_s
        self.weighted = weighted

    def __iter__(self) -> Iterator[Optional[Tuple]]:
        sock = socket.create_connection((self.host, self.port))
        sock.settimeout(self.tick_s)
        buf = b""
        try:
            while True:
                try:
                    data = sock.recv(1 << 16)
                except socket.timeout:
                    yield None  # idle tick: lets time windows close
                    continue
                if not data:  # peer closed: the stream's (test-only) end
                    break
                buf += data
                if b"\n" not in buf:
                    continue
                lines, buf = buf.rsplit(b"\n", 1)
                for line in lines.split(b"\n"):
                    rec = self._parse(line)
                    if rec is not None:
                        yield rec
            rec = self._parse(buf)
            if rec is not None:
                yield rec
        finally:
            sock.close()

    def _parse(self, line: bytes) -> Optional[Tuple]:
        line = line.strip()
        if not line or line.startswith(b"#"):
            return None
        parts = line.split()
        if len(parts) < 2:
            return None
        try:
            s, d = int(parts[0]), int(parts[1])
            v = float(parts[2]) if self.weighted and len(parts) > 2 else 0.0
        except ValueError:
            return None
        return (s, d, v)


class GeneratorSource:
    """Unbounded synthetic edge stream: R-MAT chunks, forever (or for
    ``limit`` edges when given — tests need an end)."""

    def __init__(
        self,
        scale: int = 16,
        chunk: int = 1 << 14,
        seed: int = 0,
        limit: Optional[int] = None,
    ):
        self.scale = scale
        self.chunk = chunk
        self.seed = seed
        self.limit = limit

    def __iter__(self) -> Iterator[Tuple]:
        from ..datasets import rmat_edges

        produced = 0
        step = 0
        while self.limit is None or produced < self.limit:
            n = self.chunk
            if self.limit is not None:
                n = min(n, self.limit - produced)
            src, dst = rmat_edges(n, self.scale, seed=self.seed + step)
            for s, d in zip(src.tolist(), dst.tolist()):
                yield (s, d, 0.0)
            produced += n
            step += 1
