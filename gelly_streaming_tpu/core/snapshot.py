"""SnapshotStream: discretized graph snapshots + neighborhood aggregations.

TPU-native re-design of ``SnapshotStream.java``: the result of
``GraphStream.slice()`` — a stream of discrete graphs, one per tumbling
window, on which per-vertex neighborhood aggregations run. The reference
implements these as Flink ``WindowedStream`` fold/reduce/apply with per-key
iteration (``SnapshotStream.java:61-181``); here each window is one compiled
device step over its EdgeBlock:

- :meth:`fold_neighbors`  -> segmented fold in arrival order (``ops.segment.
  segmented_fold``), the exact ``EdgesFold`` analog.
- :meth:`reduce_on_edges` -> segment reduction: monoid fast path
  (scatter-reduce) for ``"sum"/"min"/"max"``, segmented associative scan for
  arbitrary associative callables (the ``EdgesReduce`` analog).
- :meth:`apply_on_neighbors` -> dense padded neighborhoods + ``vmap``-ed UDF
  (the ``EdgesApply`` analog); the UDF sees the whole (masked) neighborhood
  row at once instead of an Iterable.

Direction semantics match the reference's ``slice(Time, EdgeDirection)``
(``SimpleEdgeStream.java:135-167``): OUT keys by src (neighbor=dst), IN keys
by dst (neighbor=src), ALL keys both directions.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .edgeblock import EdgeBlock, bucket_capacity
from .types import EdgeDirection
from .vertexdict import VertexDict


def expand_direction(
    block: EdgeBlock, direction: EdgeDirection
) -> Tuple[jax.Array, jax.Array, Any, jax.Array]:
    """Return (key, neighbor, val, mask) arrays for the given direction."""
    if direction == EdgeDirection.OUT:
        return block.src, block.dst, block.val, block.mask
    if direction == EdgeDirection.IN:
        return block.dst, block.src, block.val, block.mask
    key = jnp.concatenate([block.src, block.dst])
    nbr = jnp.concatenate([block.dst, block.src])
    val = jax.tree.map(lambda v: jnp.concatenate([v, v]), block.val)
    mask = jnp.concatenate([block.mask, block.mask])
    return key, nbr, val, mask


class SnapshotStream:
    """A stream of discrete graph snapshots (``SnapshotStream.java:46``)."""

    def __init__(
        self,
        block_iter_fn: Callable[[], Iterator[EdgeBlock]],
        direction: EdgeDirection,
        vdict: VertexDict,
        context,
    ):
        self._block_iter_fn = block_iter_fn
        self.direction = direction
        self._vdict = vdict
        self.context = context

    # ------------------------------------------------------------------ #
    def _raw32(self) -> jax.Array:
        return self._vdict.raw_table()

    def _mesh(self):
        """The context mesh when it has a >1-wide edge axis, else None.
        Only the monoid ``reduce_on_edges`` path shards; arrival-order
        folds and whole-neighborhood applies are per-window single-device
        (an arbitrary ``fold_fn`` has no cross-shard merge)."""
        from ..parallel.mesh import EDGE_AXIS

        mesh = getattr(self.context, "mesh", None)
        if mesh is None or EDGE_AXIS not in mesh.shape or mesh.shape[EDGE_AXIS] == 1:
            return None
        return mesh

    def _emit(self, result, nonempty, vdict_size_hint: Optional[int] = None):
        """Yield (raw_vertex_id, record) for each nonempty vertex.

        Batched: one decode for the window's changed set and one host
        download per result leaf (no per-record ``decode_one``)."""
        nonempty_h = np.asarray(nonempty)
        idxs = np.nonzero(nonempty_h)[0]
        if idxs.size == 0:
            return
        raws = self._vdict.decode(idxs).tolist()
        leaves_are_struct = not isinstance(result, (jnp.ndarray, np.ndarray))
        if not leaves_are_struct:
            vals = np.asarray(result)[idxs]
            scalar = vals.ndim == 1
            for i, raw in enumerate(raws):
                v = vals[i]
                yield int(raw), (v.item() if scalar else v)
            return
        sliced = jax.tree.map(lambda a: np.asarray(a)[idxs], result)
        for i, raw in enumerate(raws):
            rec = jax.tree.map(
                lambda a: a[i].item() if a[i].ndim == 0 else a[i], sliced
            )
            yield int(raw), rec

    def _emit_pairs(self, vids: np.ndarray, result_h):
        """Yield (raw_vertex_id, record) for pre-selected vertices whose
        results are already host arrays aligned with ``vids``."""
        raws = self._vdict.decode(vids).tolist()
        leaves_are_struct = not isinstance(result_h, np.ndarray)
        if not leaves_are_struct:
            scalar = result_h.ndim == 1
            for i, raw in enumerate(raws):
                v = result_h[i]
                yield int(raw), (v.item() if scalar else v)
            return
        for i, raw in enumerate(raws):
            rec = jax.tree.map(
                lambda a: a[i].item() if a[i].ndim == 0 else a[i], result_h
            )
            yield int(raw), rec

    # ------------------------------------------------------------------ #
    def fold_neighbors(self, initial_value: Any, fold_fn: Callable) -> Iterator[Tuple[int, Any]]:
        """Per-vertex arrival-order fold over the windowed neighborhood.

        ``fold_fn(accum, vertex_id, neighbor_id, edge_value) -> accum`` — the
        ``EdgesFold.foldEdges`` analog (``SnapshotStream.java:61-86``), traced
        by JAX and scanned over the window's sorted edges. Vertex/neighbor
        ids presented to the UDF are raw ids.
        """
        from ..ops.segment import segmented_fold

        @jax.jit
        def _window(block: EdgeBlock, raw: jax.Array):
            key, nbr, val, mask = expand_direction(block, self.direction)
            return segmented_fold(
                initial_value, fold_fn, key, nbr, val, mask,
                num_segments=block.n_vertices,
                id_of_segment=raw, id_of_neighbor=raw,
            )

        for b in self._block_iter_fn():
            result, nonempty = _window(b, self._raw32())
            yield from self._emit(result, nonempty)

    def reduce_on_edges(self, reduce_fn) -> Iterator[Tuple[int, Any]]:
        """Per-vertex associative reduction of edge values
        (``SnapshotStream.java:100-120``).

        ``reduce_fn`` is either one of ``"sum" | "min" | "max"`` (monoid fast
        path: XLA scatter-reduce, no sort) or an associative callable
        ``(a, b) -> c`` (segmented associative scan).
        """
        from ..ops.segment import segment_reduce, segmented_reduce_generic, segment_count

        if isinstance(reduce_fn, str):
            op = reduce_fn
            mesh = self._mesh()

            if mesh is not None:
                # Distributed snapshot reduce: shard the expanded edge
                # arrays over the mesh edge axis; each shard scatter-reduces
                # into a local V-table and one ICI all-reduce merges them —
                # the keyBy+window funnel as a collective (SURVEY.md §2.6).
                from jax.sharding import PartitionSpec as P

                from ..parallel import comm
                from ..parallel.mesh import EDGE_AXIS

                @jax.jit
                def _window(block: EdgeBlock):
                    key, _nbr, val, mask = expand_direction(block, self.direction)
                    V = block.n_vertices

                    def shard_fn(key, val, mask):
                        out = segment_reduce(val, key, mask, V, op=op)
                        cnt = segment_count(key, mask, V)
                        return (
                            comm.all_reduce(out, EDGE_AXIS, op=op),
                            comm.all_reduce(cnt, EDGE_AXIS),
                        )

                    in_specs = (
                        P(EDGE_AXIS),
                        jax.tree.map(lambda _: P(EDGE_AXIS), val),
                        P(EDGE_AXIS),
                    )
                    out, cnt = comm.shard_map(
                        shard_fn, mesh, in_specs=in_specs, out_specs=(P(), P())
                    )(key, val, mask)
                    return out, cnt > 0

            else:

                @jax.jit
                def _window(block: EdgeBlock):
                    key, _nbr, val, mask = expand_direction(block, self.direction)
                    out = segment_reduce(val, key, mask, block.n_vertices, op=op)
                    cnt = segment_count(key, mask, block.n_vertices)
                    return out, cnt > 0

        else:

            @jax.jit
            def _window(block: EdgeBlock):
                key, _nbr, val, mask = expand_direction(block, self.direction)
                return segmented_reduce_generic(
                    val, key, mask, block.n_vertices, combine=reduce_fn
                )

        for b in self._block_iter_fn():
            result, nonempty = _window(b)
            yield from self._emit(result, nonempty)

    def _window_degrees(self, b: EdgeBlock, csr) -> np.ndarray:
        """Per-vertex degrees for the class planner, WITHOUT reading the
        device back when the block carries host columns (the ingest
        path): a direction-aware host bincount costs O(W+V) beside the
        stream, where ``np.asarray(csr.degree)`` is a blocking
        device->host read that serializes the window pipeline (~0.5-3 s
        per read through the remote tunnel — round-4 verdict weak #4;
        same novelty-shadow discipline as the spanner/triangle paths).
        Device-transformed blocks (no host columns) fall back to the
        one-read-per-window path via :meth:`_degree_readback`."""
        cache = getattr(b, "_host_cache", None)
        if cache is None:
            return self._degree_readback(csr)
        src, dst = cache[0], cache[1]
        n = b.n_vertices
        if self.direction == EdgeDirection.OUT:
            return np.bincount(src, minlength=n)
        if self.direction == EdgeDirection.IN:
            return np.bincount(dst, minlength=n)
        return np.bincount(src, minlength=n) + np.bincount(dst, minlength=n)

    def _degree_readback(self, csr) -> np.ndarray:
        """The documented mid-stream D2H fallback (cache-less blocks
        only). Kept as a separate hook so the no-D2H contract test can
        assert the cached path never lands here."""
        return np.asarray(csr.degree)

    def apply_on_neighbors(
        self, apply_fn: Callable, max_degree: Optional[int] = None
    ) -> Iterator[Tuple[int, Any]]:
        """Apply a UDF to each vertex's full windowed neighborhood
        (``SnapshotStream.java:129-181``).

        ``apply_fn(vertex_id, neighbor_ids[D], edge_values[D], valid[D]) ->
        record`` is ``vmap``-ed over vertices. Vertices are processed in
        DEGREE CLASSES (power-of-two buckets): each class materializes
        dense rows only as wide as its own bucket, so a single Zipf hub no
        longer sizes the rows for every vertex — the same skew defense as
        the triangle kernels' orientation trick (``ops/triangles.py``).
        Total dense work is ~sum_v bucket(deg v) <= ~4E. ``max_degree``
        caps the row width instead (documented truncation policy: wider
        neighborhoods are cut off). The UDF sees raw ids and a validity
        mask instead of the reference's Iterable; emission is ascending by
        vertex, as before.
        """
        from ..ops.csr import build_csr, dense_neighbors, dense_neighbors_subset

        @jax.jit
        def _csr(block: EdgeBlock):
            key, nbr, val, mask = expand_direction(block, self.direction)
            return build_csr(key, nbr, val, mask, block.n_vertices)

        def _class_fn(D: int):
            @jax.jit
            def _window(csr, raw, vids):
                nbr_mat, val_mat, valid = dense_neighbors_subset(csr, vids, D)
                return jax.vmap(apply_fn)(raw[vids], raw[nbr_mat], val_mat, valid)

            return _window

        def _capped_fn(D: int):
            @jax.jit
            def _window(csr, raw):
                nbr_mat, val_mat, valid = dense_neighbors(csr, D)
                V = csr.num_vertices
                vids = raw[jnp.arange(V)]
                out = jax.vmap(apply_fn)(vids, raw[nbr_mat], val_mat, valid)
                return out, csr.degree > 0

            return _window

        cache: dict = {}
        for b in self._block_iter_fn():
            csr = _csr(b)
            if max_degree is not None:
                fn = cache.get(("cap", max_degree))
                if fn is None:
                    fn = cache[("cap", max_degree)] = _capped_fn(max_degree)
                result, nonempty = fn(csr, self._raw32())
                yield from self._emit(result, nonempty)
                continue
            deg = self._window_degrees(b, csr)
            active = np.nonzero(deg > 0)[0]
            if active.size == 0:
                continue
            # group active vertices by degree bucket; rows per class are
            # only as wide as that class's bucket
            buckets = np.int64(1) << np.ceil(
                np.log2(np.maximum(deg[active], 1))
            ).astype(np.int64)
            buckets = np.maximum(buckets, 4)
            pieces = []  # (vids, result_tree) per class
            for c in np.unique(buckets):
                vids = active[buckets == c]
                t = len(vids)
                tcap = bucket_capacity(t, 4)
                vids_p = np.concatenate(
                    [vids, np.full(tcap - t, vids[0], vids.dtype)]
                ).astype(np.int32)
                key = ("class", int(c), tcap)
                fn = cache.get(key)
                if fn is None:
                    fn = cache[key] = _class_fn(int(c))
                out = fn(csr, self._raw32(), jnp.asarray(vids_p))
                out_h = jax.tree.map(lambda a: np.asarray(a)[:t], out)
                pieces.append((vids, out_h))
            # merge classes back into ascending-vertex emission order
            all_vids = np.concatenate([p[0] for p in pieces])
            merged = jax.tree.map(
                lambda *leaves: np.concatenate(leaves), *[p[1] for p in pieces]
            )
            order = np.argsort(all_vids, kind="stable")
            yield from self._emit_pairs(
                all_vids[order], jax.tree.map(lambda a: a[order], merged)
            )

    def flat_apply_on_neighbors(
        self,
        apply_fn: Callable,
        max_out,
        max_degree: Optional[int] = None,
    ) -> Iterator[Any]:
        """Apply a 0..n-emission UDF to each vertex's windowed
        neighborhood — the reference's ``Collector``-based ``EdgesApply``
        (``EdgesApply.java:35-47``; ``SnapshotStream.java:129-181``),
        whose UDFs may emit any number of records per vertex (the
        triangle pipeline's ``GenerateCandidateEdges`` emits O(deg^2),
        ``WindowTriangles.java:86-114``).

        The TPU shape of 0..n emission is a fixed per-class output
        bucket plus a validity mask: ``apply_fn(vertex_id,
        neighbor_ids[D], edge_values[D], valid[D]) -> (records, emit[K])``
        where ``records`` is any pytree of arrays with leading dim ``K``
        and ``K = max_out(D)`` (or a constant ``max_out``). ``D`` is the
        vertex's degree-class bucket — a static shape under vmap, so the
        UDF can build index helpers like ``jnp.triu_indices(D, 1)``
        inline. Records with ``emit`` False are dropped.

        Yields the emitted records (not keyed — the UDF includes any key
        it wants, as a reference Collector UDF would) in deterministic
        order: windows in stream order, vertices ascending, emission
        slots ascending. Degree classes and the ``max_degree``
        truncation cap behave exactly as :meth:`apply_on_neighbors`.
        """
        from ..ops.csr import build_csr, dense_neighbors_subset

        kfor = max_out if callable(max_out) else (lambda D: int(max_out))

        @jax.jit
        def _csr(block: EdgeBlock):
            key, nbr, val, mask = expand_direction(block, self.direction)
            return build_csr(key, nbr, val, mask, block.n_vertices)

        def _class_fn(D: int):
            @jax.jit
            def _window(csr, raw, vids):
                nbr_mat, val_mat, valid = dense_neighbors_subset(csr, vids, D)
                return jax.vmap(apply_fn)(
                    raw[vids], raw[nbr_mat], val_mat, valid
                )

            return _window

        cache: dict = {}
        for b in self._block_iter_fn():
            csr = _csr(b)
            deg = self._window_degrees(b, csr)
            active = np.nonzero(deg > 0)[0]
            if active.size == 0:
                continue
            if max_degree is not None:
                buckets = np.full(active.size, max_degree, np.int64)
            else:
                buckets = np.int64(1) << np.ceil(
                    np.log2(np.maximum(deg[active], 1))
                ).astype(np.int64)
                buckets = np.maximum(buckets, 4)
            pieces = []  # (vids, records_tree, emit_mask) per class
            for c in np.unique(buckets):
                vids = active[buckets == c]
                t = len(vids)
                tcap = bucket_capacity(t, 4)
                vids_p = np.concatenate(
                    [vids, np.full(tcap - t, vids[0], vids.dtype)]
                ).astype(np.int32)
                key = ("class", int(c), tcap)
                fn = cache.get(key)
                if fn is None:
                    fn = cache[key] = _class_fn(int(c))
                records, emit = fn(csr, self._raw32(), jnp.asarray(vids_p))
                k_want = kfor(int(c))
                for leaf in jax.tree.leaves(records):
                    got = leaf.shape[1] if leaf.ndim >= 2 else None
                    if got != k_want:
                        raise ValueError(
                            f"apply_fn emitted leading dim {got} for degree "
                            f"class {int(c)}, but max_out({int(c)}) = "
                            f"{k_want}; every record leaf must be [K, ...] "
                            f"with K = max_out(D)"
                        )
                if emit.ndim != 2 or emit.shape[1] != k_want:
                    raise ValueError(
                        f"emit mask shape {emit.shape[1:]} != max_out("
                        f"{int(c)}) = {k_want}"
                    )
                emit_h = np.asarray(emit)[:t]
                rec_h = jax.tree.map(lambda a: np.asarray(a)[:t], records)
                pieces.append((vids, rec_h, emit_h))
            all_vids = np.concatenate([p[0] for p in pieces])
            order = np.argsort(all_vids, kind="stable")
            offsets = np.cumsum([0] + [len(p[0]) for p in pieces])
            for o in order:
                pi = int(np.searchsorted(offsets, o, side="right") - 1)
                row = o - offsets[pi]
                vids, rec_h, emit_h = pieces[pi]
                ks = np.nonzero(emit_h[row])[0]
                for k in ks:
                    yield jax.tree.map(
                        lambda a: a[row, k].item()
                        if a[row, k].ndim == 0 else a[row, k],
                        rec_h,
                    )
