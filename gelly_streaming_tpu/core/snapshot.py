"""SnapshotStream: discretized graph snapshots + neighborhood aggregations.

TPU-native re-design of ``SnapshotStream.java``: the result of
``GraphStream.slice()`` — a stream of discrete graphs, one per tumbling
window, on which per-vertex neighborhood aggregations run. The reference
implements these as Flink ``WindowedStream`` fold/reduce/apply with per-key
iteration (``SnapshotStream.java:61-181``); here each window is one compiled
device step over its EdgeBlock:

- :meth:`fold_neighbors`  -> segmented fold in arrival order (``ops.segment.
  segmented_fold``), the exact ``EdgesFold`` analog.
- :meth:`reduce_on_edges` -> segment reduction: monoid fast path
  (scatter-reduce) for ``"sum"/"min"/"max"``, segmented associative scan for
  arbitrary associative callables (the ``EdgesReduce`` analog).
- :meth:`apply_on_neighbors` -> dense padded neighborhoods + ``vmap``-ed UDF
  (the ``EdgesApply`` analog); the UDF sees the whole (masked) neighborhood
  row at once instead of an Iterable.

Direction semantics match the reference's ``slice(Time, EdgeDirection)``
(``SimpleEdgeStream.java:135-167``): OUT keys by src (neighbor=dst), IN keys
by dst (neighbor=src), ALL keys both directions.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .edgeblock import EdgeBlock, bucket_capacity
from .types import EdgeDirection
from .vertexdict import VertexDict


def expand_direction(
    block: EdgeBlock, direction: EdgeDirection
) -> Tuple[jax.Array, jax.Array, Any, jax.Array]:
    """Return (key, neighbor, val, mask) arrays for the given direction."""
    if direction == EdgeDirection.OUT:
        return block.src, block.dst, block.val, block.mask
    if direction == EdgeDirection.IN:
        return block.dst, block.src, block.val, block.mask
    key = jnp.concatenate([block.src, block.dst])
    nbr = jnp.concatenate([block.dst, block.src])
    val = jax.tree.map(lambda v: jnp.concatenate([v, v]), block.val)
    mask = jnp.concatenate([block.mask, block.mask])
    return key, nbr, val, mask


class SnapshotStream:
    """A stream of discrete graph snapshots (``SnapshotStream.java:46``)."""

    def __init__(
        self,
        block_iter_fn: Callable[[], Iterator[EdgeBlock]],
        direction: EdgeDirection,
        vdict: VertexDict,
        context,
    ):
        self._block_iter_fn = block_iter_fn
        self.direction = direction
        self._vdict = vdict
        self.context = context

    # ------------------------------------------------------------------ #
    def _raw32(self) -> jax.Array:
        return self._vdict.raw_table()

    def _mesh(self):
        """The context mesh when it has a >1-wide edge axis, else None.
        Only the monoid ``reduce_on_edges`` path shards; arrival-order
        folds and whole-neighborhood applies are per-window single-device
        (an arbitrary ``fold_fn`` has no cross-shard merge)."""
        from ..parallel.mesh import EDGE_AXIS

        mesh = getattr(self.context, "mesh", None)
        if mesh is None or EDGE_AXIS not in mesh.shape or mesh.shape[EDGE_AXIS] == 1:
            return None
        return mesh

    def _emit(self, result, nonempty, vdict_size_hint: Optional[int] = None):
        """Yield (raw_vertex_id, record) for each nonempty vertex.

        Batched: one decode for the window's changed set and one host
        download per result leaf (no per-record ``decode_one``)."""
        nonempty_h = np.asarray(nonempty)
        idxs = np.nonzero(nonempty_h)[0]
        if idxs.size == 0:
            return
        raws = self._vdict.decode(idxs).tolist()
        leaves_are_struct = not isinstance(result, (jnp.ndarray, np.ndarray))
        if not leaves_are_struct:
            vals = np.asarray(result)[idxs]
            scalar = vals.ndim == 1
            for i, raw in enumerate(raws):
                v = vals[i]
                yield int(raw), (v.item() if scalar else v)
            return
        sliced = jax.tree.map(lambda a: np.asarray(a)[idxs], result)
        for i, raw in enumerate(raws):
            rec = jax.tree.map(
                lambda a: a[i].item() if a[i].ndim == 0 else a[i], sliced
            )
            yield int(raw), rec

    def _emit_pairs(self, vids: np.ndarray, result_h):
        """Yield (raw_vertex_id, record) for pre-selected vertices whose
        results are already host arrays aligned with ``vids``."""
        raws = self._vdict.decode(vids).tolist()
        leaves_are_struct = not isinstance(result_h, np.ndarray)
        if not leaves_are_struct:
            scalar = result_h.ndim == 1
            for i, raw in enumerate(raws):
                v = result_h[i]
                yield int(raw), (v.item() if scalar else v)
            return
        for i, raw in enumerate(raws):
            rec = jax.tree.map(
                lambda a: a[i].item() if a[i].ndim == 0 else a[i], result_h
            )
            yield int(raw), rec

    # ------------------------------------------------------------------ #
    def fold_neighbors(self, initial_value: Any, fold_fn: Callable) -> Iterator[Tuple[int, Any]]:
        """Per-vertex arrival-order fold over the windowed neighborhood.

        ``fold_fn(accum, vertex_id, neighbor_id, edge_value) -> accum`` — the
        ``EdgesFold.foldEdges`` analog (``SnapshotStream.java:61-86``), traced
        by JAX and scanned over the window's sorted edges. Vertex/neighbor
        ids presented to the UDF are raw ids.
        """
        from ..ops.segment import segmented_fold

        @jax.jit
        def _window(block: EdgeBlock, raw: jax.Array):
            key, nbr, val, mask = expand_direction(block, self.direction)
            return segmented_fold(
                initial_value, fold_fn, key, nbr, val, mask,
                num_segments=block.n_vertices,
                id_of_segment=raw, id_of_neighbor=raw,
            )

        for b in self._block_iter_fn():
            result, nonempty = _window(b, self._raw32())
            yield from self._emit(result, nonempty)

    def reduce_on_edges(self, reduce_fn) -> Iterator[Tuple[int, Any]]:
        """Per-vertex associative reduction of edge values
        (``SnapshotStream.java:100-120``).

        ``reduce_fn`` is either one of ``"sum" | "min" | "max"`` (monoid fast
        path: XLA scatter-reduce, no sort) or an associative callable
        ``(a, b) -> c`` (segmented associative scan).
        """
        from ..ops.segment import segment_reduce, segmented_reduce_generic, segment_count

        if isinstance(reduce_fn, str):
            op = reduce_fn
            mesh = self._mesh()

            if mesh is not None:
                # Distributed snapshot reduce: shard the expanded edge
                # arrays over the mesh edge axis; each shard scatter-reduces
                # into a local V-table and one ICI all-reduce merges them —
                # the keyBy+window funnel as a collective (SURVEY.md §2.6).
                from jax.sharding import PartitionSpec as P

                from ..parallel import comm
                from ..parallel.mesh import EDGE_AXIS

                @jax.jit
                def _window(block: EdgeBlock):
                    key, _nbr, val, mask = expand_direction(block, self.direction)
                    V = block.n_vertices

                    def shard_fn(key, val, mask):
                        out = segment_reduce(val, key, mask, V, op=op)
                        cnt = segment_count(key, mask, V)
                        return (
                            comm.all_reduce(out, EDGE_AXIS, op=op),
                            comm.all_reduce(cnt, EDGE_AXIS),
                        )

                    in_specs = (
                        P(EDGE_AXIS),
                        jax.tree.map(lambda _: P(EDGE_AXIS), val),
                        P(EDGE_AXIS),
                    )
                    out, cnt = comm.shard_map(
                        shard_fn, mesh, in_specs=in_specs, out_specs=(P(), P())
                    )(key, val, mask)
                    return out, cnt > 0

            else:

                @jax.jit
                def _window(block: EdgeBlock):
                    key, _nbr, val, mask = expand_direction(block, self.direction)
                    out = segment_reduce(val, key, mask, block.n_vertices, op=op)
                    cnt = segment_count(key, mask, block.n_vertices)
                    return out, cnt > 0

        else:

            @jax.jit
            def _window(block: EdgeBlock):
                key, _nbr, val, mask = expand_direction(block, self.direction)
                return segmented_reduce_generic(
                    val, key, mask, block.n_vertices, combine=reduce_fn
                )

        for b in self._block_iter_fn():
            result, nonempty = _window(b)
            yield from self._emit(result, nonempty)

    def apply_on_neighbors(
        self, apply_fn: Callable, max_degree: Optional[int] = None
    ) -> Iterator[Tuple[int, Any]]:
        """Apply a UDF to each vertex's full windowed neighborhood
        (``SnapshotStream.java:129-181``).

        ``apply_fn(vertex_id, neighbor_ids[D], edge_values[D], valid[D]) ->
        record`` is ``vmap``-ed over vertices. Vertices are processed in
        DEGREE CLASSES (power-of-two buckets): each class materializes
        dense rows only as wide as its own bucket, so a single Zipf hub no
        longer sizes the rows for every vertex — the same skew defense as
        the triangle kernels' orientation trick (``ops/triangles.py``).
        Total dense work is ~sum_v bucket(deg v) <= ~4E. ``max_degree``
        caps the row width instead (documented truncation policy: wider
        neighborhoods are cut off). The UDF sees raw ids and a validity
        mask instead of the reference's Iterable; emission is ascending by
        vertex, as before.
        """
        from ..ops.csr import build_csr, dense_neighbors, dense_neighbors_subset

        @jax.jit
        def _csr(block: EdgeBlock):
            key, nbr, val, mask = expand_direction(block, self.direction)
            return build_csr(key, nbr, val, mask, block.n_vertices)

        def _class_fn(D: int):
            @jax.jit
            def _window(csr, raw, vids):
                nbr_mat, val_mat, valid = dense_neighbors_subset(csr, vids, D)
                return jax.vmap(apply_fn)(raw[vids], raw[nbr_mat], val_mat, valid)

            return _window

        def _capped_fn(D: int):
            @jax.jit
            def _window(csr, raw):
                nbr_mat, val_mat, valid = dense_neighbors(csr, D)
                V = csr.num_vertices
                vids = raw[jnp.arange(V)]
                out = jax.vmap(apply_fn)(vids, raw[nbr_mat], val_mat, valid)
                return out, csr.degree > 0

            return _window

        cache: dict = {}
        for b in self._block_iter_fn():
            csr = _csr(b)
            if max_degree is not None:
                fn = cache.get(("cap", max_degree))
                if fn is None:
                    fn = cache[("cap", max_degree)] = _capped_fn(max_degree)
                result, nonempty = fn(csr, self._raw32())
                yield from self._emit(result, nonempty)
                continue
            deg = np.asarray(csr.degree)
            active = np.nonzero(deg > 0)[0]
            if active.size == 0:
                continue
            # group active vertices by degree bucket; rows per class are
            # only as wide as that class's bucket
            buckets = np.int64(1) << np.ceil(
                np.log2(np.maximum(deg[active], 1))
            ).astype(np.int64)
            buckets = np.maximum(buckets, 4)
            pieces = []  # (vids, result_tree) per class
            for c in np.unique(buckets):
                vids = active[buckets == c]
                t = len(vids)
                tcap = bucket_capacity(t, 4)
                vids_p = np.concatenate(
                    [vids, np.full(tcap - t, vids[0], vids.dtype)]
                ).astype(np.int32)
                key = ("class", int(c), tcap)
                fn = cache.get(key)
                if fn is None:
                    fn = cache[key] = _class_fn(int(c))
                out = fn(csr, self._raw32(), jnp.asarray(vids_p))
                out_h = jax.tree.map(lambda a: np.asarray(a)[:t], out)
                pieces.append((vids, out_h))
            # merge classes back into ascending-vertex emission order
            all_vids = np.concatenate([p[0] for p in pieces])
            merged = jax.tree.map(
                lambda *leaves: np.concatenate(leaves), *[p[1] for p in pieces]
            )
            order = np.argsort(all_vids, kind="stable")
            yield from self._emit_pairs(
                all_vids[order], jax.tree.map(lambda a: a[order], merged)
            )
