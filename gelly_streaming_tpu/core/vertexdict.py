"""VertexDict: incremental raw-id -> compact-id dictionary (the host keyBy).

The reference relies on Flink's keyed state: ``keyBy(vertex)`` hash-shuffles
records so each operator instance owns a key range, and per-key HashMaps grow
unboundedly inside operators (e.g. degree maps ``SimpleEdgeStream.java:461-478``,
neighborhoods ``:531-560``). On TPU, per-key state must become dense arrays
indexed by a *compact* vertex id, because gathers/scatters over a dense
int32 index space are what the hardware does well.

``VertexDict`` is the host-side component that owns this mapping:

- ``encode(raw_ids)`` maps raw (arbitrary, possibly 64-bit) vertex ids to
  compact int32 indices, assigning fresh indices first-seen-first.
- ``decode(idx)`` maps back for emission.
- ``capacity`` is power-of-two bucketed so device-side vertex tables (labels,
  degrees, ranks) reallocate only O(log V) times as the stream grows.

This replaces both halves of Flink's mechanism: the hash shuffle (compaction
is deterministic on every host, so sharding by ``compact_id % n_shards`` is a
pure function — see ``parallel/``) and the per-key HashMap (dense vectors).
"""

from __future__ import annotations

from typing import Iterable

import numpy as np

from .edgeblock import bucket_capacity


class VertexDict:
    """Incremental bidirectional mapping raw id <-> compact int32 index."""

    def __init__(self, min_capacity: int = 8):
        self._idx_to_raw: list[int] = []
        # batch-lookup index: (sorted raw ids, aligned compact ids) as ONE
        # tuple (numpy fallback path; unused when the native encoder
        # loads). The pair is replaced by a single reference assignment so
        # a concurrent reader (the serving query worker's lookup_batch)
        # always sees a mutually consistent raw/idx pair — two separate
        # attributes could be observed mid-swap with mismatched lengths.
        # The native encoder gets the same guarantee from its own mutex.
        self._index = (np.empty(0, np.int64), np.empty(0, np.int32))
        self._min_capacity = min_capacity
        try:
            from ..native import NativeEncoder

            self._native = NativeEncoder()
        except Exception:
            self._native = None

    def __len__(self) -> int:
        return len(self._idx_to_raw)

    @property
    def capacity(self) -> int:
        """Power-of-two bucketed size for device vertex tables."""
        return bucket_capacity(max(1, len(self._idx_to_raw)), self._min_capacity)

    def encode(self, raw: np.ndarray) -> np.ndarray:
        """Map raw ids to compact indices, assigning new ones first-seen-first.

        Fully vectorized (no per-element Python): known ids resolve by
        binary search into the sorted index; novel ids get sequential
        compact ids in first-appearance order and are merged in. This is
        the host ingest hot path — it must keep up with the device.
        """
        raw = np.asarray(raw, np.int64).ravel()
        n = raw.shape[0]
        if n == 0:
            return np.empty(0, dtype=np.int32)
        if self._native is not None:
            out, novel = self._native.encode(raw)
            if novel.size:
                self._idx_to_raw.extend(novel.tolist())
            return out
        out = np.empty(n, dtype=np.int32)
        sorted_raw, sorted_idx = self._index
        if sorted_raw.size:
            pos = np.searchsorted(sorted_raw, raw)
            pos_c = np.minimum(pos, sorted_raw.size - 1)
            known = sorted_raw[pos_c] == raw
            out[known] = sorted_idx[pos_c[known]]
        else:
            known = np.zeros(n, bool)
        novel = ~known
        if novel.any():
            vals = raw[novel]
            uniq, first_pos = np.unique(vals, return_index=True)
            order = np.argsort(first_pos, kind="stable")
            base = len(self._idx_to_raw)
            id_of_uniq = np.empty(uniq.size, np.int32)
            id_of_uniq[order] = base + np.arange(uniq.size, dtype=np.int32)
            out[novel] = id_of_uniq[np.searchsorted(uniq, vals)]
            self._idx_to_raw.extend(uniq[order].tolist())
            merged_raw = np.concatenate([sorted_raw, uniq])
            merged_idx = np.concatenate([sorted_idx, id_of_uniq])
            o = np.argsort(merged_raw, kind="stable")
            # one atomic reference swap (see __init__)
            self._index = (merged_raw[o], merged_idx[o])
        return out

    def encode_pair(self, src: np.ndarray, dst: np.ndarray):
        """Encode edge endpoint columns in arrival order (src before dst per
        edge — the order the reference's per-record processing would see)
        without materializing the interleaved array. Returns (src_idx,
        dst_idx) int32 arrays."""
        if self._native is not None:
            ia, ib, novel = self._native.encode_pair(
                np.asarray(src, np.int64).ravel(),
                np.asarray(dst, np.int64).ravel(),
            )
            if novel.size:
                self._idx_to_raw.extend(novel.tolist())
            return ia, ib
        both = np.stack(
            [np.asarray(src, np.int64), np.asarray(dst, np.int64)], axis=1
        ).ravel()
        enc = self.encode(both)
        return enc[0::2], enc[1::2]

    def iter_encode_file(self, path: str, chunk_edges: int = 1 << 20):
        """Fused file ingest (native only): yield already-encoded
        ``(src_idx, dst_idx, val|None)`` int32 column chunks, keeping this
        dict's reverse table in sync. Raises without the native encoder —
        callers fall back to ``native.iter_edge_chunks`` + ``encode_pair``.
        """
        if self._native is None:
            raise RuntimeError("native encoder unavailable")
        for src, dst, val, novel in self._native.parse_encode_chunks(
            path, chunk_edges
        ):
            if novel.size:
                self._idx_to_raw.extend(novel.tolist())
            yield src, dst, val

    def encode_one(self, raw: int) -> int:
        return int(self.encode(np.asarray([raw]))[0])

    def lookup(self, raw: int) -> int | None:
        """Query without inserting; None if unseen."""
        if self._native is not None:
            return self._native.lookup(raw)
        sorted_raw, sorted_idx = self._index
        pos = int(np.searchsorted(sorted_raw, raw))
        if pos < sorted_raw.size and sorted_raw[pos] == raw:
            return int(sorted_idx[pos])
        return None

    def lookup_batch(self, raw: np.ndarray) -> np.ndarray:
        """Vectorized :meth:`lookup` (the serving query path): compact
        ids aligned with ``raw``, -1 marking unseen ids. Never inserts.
        Safe to call from a reader thread concurrent with ingest: the
        native encoder serializes table access behind its mutex, and the
        numpy index is read as one consistent snapshot."""
        raw = np.asarray(raw, np.int64).ravel()
        out = np.full(raw.size, -1, np.int32)
        if raw.size == 0:
            return out
        if self._native is not None:
            # the native encoder owns the table (no numpy sorted index
            # is maintained beside it): one C call for the whole batch
            return self._native.lookup_batch(raw)
        sorted_raw, sorted_idx = self._index  # consistent snapshot
        if sorted_raw.size:
            pos = np.searchsorted(sorted_raw, raw)
            pos_c = np.minimum(pos, sorted_raw.size - 1)
            known = sorted_raw[pos_c] == raw
            out[known] = sorted_idx[pos_c[known]]
        return out

    def decode(self, idx: Iterable[int] | np.ndarray) -> np.ndarray:
        rev = self._rev_array()
        return rev[np.asarray(idx, dtype=np.int64)]

    def _rev_array(self) -> np.ndarray:
        """Reverse table as numpy, cached by dict size (converting the
        python list costs ~0.1s/M entries — too much per emission batch)."""
        n = len(self._idx_to_raw)
        cached = getattr(self, "_rev_cache", None)
        if cached is not None and cached.shape[0] == n:
            return cached
        rev = np.asarray(self._idx_to_raw, dtype=np.int64)
        self._rev_cache = rev
        return rev

    def decode_one(self, idx: int) -> int:
        return self._idx_to_raw[int(idx)]

    def raw_ids(self) -> np.ndarray:
        """All raw ids in compact-index order."""
        return np.asarray(self._idx_to_raw, dtype=np.int64)

    def raw_table(self):
        """Device int32 lookup table: compact index -> raw vertex id.

        Lets device-side UDFs observe the same vertex ids the reference's
        UDFs would, while all indexing stays compact int32. Raw ids must fit
        int32; larger ids raise (re-map host-side first). Cached per dict
        size — the table only changes when the dict grows.
        """
        import jax.numpy as jnp

        n = len(self._idx_to_raw)
        cached = getattr(self, "_raw_table_cache", None)
        if cached is not None and cached[0] == n:
            return cached[1]
        raw = self.raw_ids()
        if raw.size and (
            raw.max() > np.iinfo(np.int32).max or raw.min() < np.iinfo(np.int32).min
        ):
            raise ValueError(
                "raw vertex ids exceed int32; re-map ids host-side before streaming"
            )
        padded = np.zeros(self.capacity, dtype=np.int32)
        padded[: raw.size] = raw.astype(np.int32)
        table = jnp.asarray(padded)
        self._raw_table_cache = (n, table)
        return table
