from .types import Edge, EdgeDirection, EventType, Vertex
from .edgeblock import EdgeBlock, bucket_capacity, concat_blocks
from .vertexdict import VertexDict
from .window import CountWindow, EventTimeWindow, Windower, blocks_from_edges
from .stream import GraphStream, SimpleEdgeStream, StreamContext
from .snapshot import SnapshotStream
