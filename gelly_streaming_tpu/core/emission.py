"""EmissionStream: the shared output-side wrapper for all workloads.

The reference's outputs are ordinary DataStreams — per-record, continuously
improving (``README.md:26-32``, ``SimpleEdgeStream.java:562-576``). The
TPU-native emission unit is the *window batch*: one device step produces a
whole window's records at once, and flattening them one Python object at a
time must not dominate a 1M-vertex window (round-1 verdict item #6).

:class:`EmissionStream` is that contract in one place:

- iterating it yields per-record emissions (reference API parity);
- :meth:`batches` yields the per-window groups vectorized (whatever batch
  the producer built — typically lists or lazily-zipped numpy columns) and
  feeds per-window wall time into an optional
  :class:`~gelly_streaming_tpu.utils.profiling.StreamProfiler` — metrics
  stay a stream, per the reference's design stance.

Producers (the property streams on ``SimpleEdgeStream``, the snapshot
aggregations) build batches with batched ``VertexDict.decode`` — never a
per-record ``decode_one`` loop.
"""

from __future__ import annotations

import time
from typing import Callable, Iterable, Iterator, Optional, TypeVar

from ..utils.profiling import StreamProfiler, WindowStats

T = TypeVar("T")


class ColumnBatch:
    """One window's emissions backed by column arrays.

    Iterating yields per-record tuples (API parity); bulk consumers read
    ``.columns`` directly and skip the 4M-tuple object churn of a large
    window entirely."""

    __slots__ = ("columns",)

    def __init__(self, *columns):
        self.columns = columns

    def __len__(self) -> int:
        return len(self.columns[0])

    def __iter__(self):
        return zip(
            *(
                c.tolist() if hasattr(c, "tolist") else c
                for c in self.columns
            )
        )


class RecordColumnBatch:
    """Column-backed batch whose per-record view constructs typed records
    (``Edge``/``Vertex``) on demand.

    Bulk consumers read ``.columns`` and never pay object construction;
    iteration yields the reference-parity record type one at a time
    (round-2 verdict weak #8: ``get_edges``/``get_vertices`` built a
    Python object per record per window unconditionally)."""

    __slots__ = ("ctor", "columns")

    def __init__(self, ctor, *columns):
        self.ctor = ctor
        self.columns = columns

    def __len__(self) -> int:
        return len(self.columns[0])

    def __iter__(self):
        cols = [
            c.tolist() if hasattr(c, "tolist") else c for c in self.columns
        ]
        return (self.ctor(*t) for t in zip(*cols))


class DeviceColumnBatch:
    """A :class:`ColumnBatch` whose columns stay ON DEVICE until first read.

    The remote-TPU tunnel moves ~4-18 MB/s with ~100 ms per round-trip
    (measured round 3), so eagerly downloading every window's emission
    columns caps any property stream at ~1 window/s regardless of device
    rate. Lazy materialization keeps the producer's loop purely async —
    dispatches pipeline, no per-window sync — and only consumers that
    actually read records pay the transfer, proportional to what they read.
    Pipelines that aggregate further on device never download at all.
    """

    __slots__ = ("_thunk", "_cols")

    def __init__(self, thunk: Callable[[], tuple]):
        self._thunk = thunk
        self._cols = None

    @property
    def columns(self) -> tuple:
        if self._cols is None:
            self._cols = tuple(self._thunk())
        return self._cols

    def __len__(self) -> int:
        return len(self.columns[0])

    def __iter__(self):
        return zip(
            *(
                c.tolist() if hasattr(c, "tolist") else c
                for c in self.columns
            )
        )


class LazyListBatch:
    """Base for lazy list-like window emissions: subclasses set
    ``self._items = None`` in ``__init__`` and implement ``_compute() ->
    list``; the list-protocol surface (iterate / len / index / compare /
    repr) and the materialize-once caching live here, so the change-only
    batch types (triangles, degree histograms, ...) cannot drift apart."""

    def _materialize(self) -> list:
        if self._items is None:
            self._items = self._compute()
        return self._items

    def __iter__(self):
        return iter(self._materialize())

    def __len__(self) -> int:
        return len(self._materialize())

    def __getitem__(self, i):
        return self._materialize()[i]

    def __eq__(self, other):
        return self._materialize() == other

    def __repr__(self) -> str:
        return repr(self._materialize())


class LazyRecordBatch:
    """A :class:`RecordColumnBatch` whose columns come from a thunk run on
    first read — the typed-record analog of :class:`DeviceColumnBatch`.
    Producers of device-transformed blocks use it so the per-window
    ``to_host`` download (0.5-3 s through the remote tunnel) happens only
    for windows a consumer actually reads."""

    __slots__ = ("ctor", "_thunk", "_cols")

    def __init__(self, ctor, thunk: Callable[[], tuple]):
        self.ctor = ctor
        self._thunk = thunk
        self._cols = None

    @property
    def columns(self) -> tuple:
        if self._cols is None:
            self._cols = tuple(self._thunk())
        return self._cols

    def __len__(self) -> int:
        return len(self.columns[0])

    def __iter__(self):
        cols = [
            c.tolist() if hasattr(c, "tolist") else c for c in self.columns
        ]
        return (self.ctor(*t) for t in zip(*cols))


class LazyCountRange:
    """``range(start+1, start+n+1)`` where ``start``/``n`` may be device
    scalars, materialized on first read. Lets ``number_of_edges`` chain
    its running total on device (zero per-window D2H at steady state);
    only consumers that read a window's counts pay its sync."""

    __slots__ = ("_start", "_n", "_range")

    def __init__(self, start, n):
        self._start = start
        self._n = n
        self._range = None

    def _materialize(self) -> range:
        if self._range is None:
            s, n = int(self._start), int(self._n)
            self._range = range(s + 1, s + n + 1)
        return self._range

    def __len__(self) -> int:
        return len(self._materialize())

    def __iter__(self):
        return iter(self._materialize())

    def __eq__(self, other):
        r = self._materialize()
        if isinstance(other, range):
            return r == other
        if isinstance(other, LazyCountRange):
            return r == other._materialize()
        try:
            return list(r) == list(other)
        except TypeError:
            return NotImplemented  # builtin-range parity: False, not raise

    def __hash__(self):
        return hash(self._materialize())

    def __repr__(self) -> str:
        return repr(self._materialize())


def iter_unstacked(stacked, n: int):
    """Unstack a superbatch's ``[K, ...]`` per-window outputs into K
    per-window pytrees.

    Each yielded state is a device SLICE of the stacked buffer — one
    cheap async slice dispatch per window, never a host round trip — so
    downstream lazy emission types (:class:`DeviceColumnBatch`,
    ``Components``, ...) keep their contract: only consumers that
    actually read a window's records pay its download, and the stacked
    buffer stays alive exactly as long as some window's emission holds a
    slice of it. This is the output-side half of the superbatch path
    (``SummaryAggregation._superbatch_step`` produces the stack).
    """
    import jax

    for i in range(n):
        yield jax.tree.map(lambda y, i=i: y[i], stacked)


class EmissionStream:
    """Re-iterable stream of emissions with a per-window batch view."""

    def __init__(
        self,
        batch_fn: Callable[[], Iterator[Iterable[T]]],
        profiler: Optional[StreamProfiler] = None,
    ):
        self._batch_fn = batch_fn
        self.profiler = profiler

    def batches(self) -> Iterator[Iterable[T]]:
        """Per-window emission groups (vectorized view).

        With a profiler attached, each window's wall time (including the
        producer's device sync, excluding the consumer's handling) is
        recorded as a :class:`WindowStats`.
        """
        it = self._batch_fn()
        index = 0
        while True:
            t0 = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                return
            if self.profiler is not None:
                edges = len(batch) if hasattr(batch, "__len__") else None
                self.profiler.record(
                    WindowStats(index, time.perf_counter() - t0, edges)
                )
            index += 1
            yield batch

    def __iter__(self) -> Iterator[T]:
        for batch in self.batches():
            yield from batch

    def with_profiler(self, profiler: StreamProfiler) -> "EmissionStream":
        return EmissionStream(self._batch_fn, profiler)
