"""Core value types for the TPU streaming-graph framework.

TPU-native re-design of the reference's Gelly tuple types:

- ``Edge`` mirrors the ``org.apache.flink.graph.Edge`` 3-tuple used throughout
  the reference API (e.g. ``SimpleEdgeStream.java:69``).
- ``EdgeDirection`` mirrors Gelly's ``EdgeDirection`` used by ``slice``
  (``SimpleEdgeStream.java:135-167``).
- ``EventType`` mirrors ``EventType.java:24-27`` (EDGE_ADDITION/EDGE_DELETION),
  the reference's only support for fully-dynamic streams (used by
  ``example/DegreeDistribution.java``).

Unlike the reference (boxed Java tuples flowing one record at a time through
Flink operators), edges here only exist host-side as lightweight tuples for
ingest/emission; on device they are always batched into padded
:class:`~gelly_streaming_tpu.core.edgeblock.EdgeBlock` arrays.
"""

from __future__ import annotations

import enum
from typing import Any, NamedTuple


class EdgeDirection(enum.Enum):
    """Which neighborhood an operation ranges over (cf. Gelly EdgeDirection)."""

    IN = "in"
    OUT = "out"
    ALL = "all"


class EventType(enum.Enum):
    """Edge event kind for fully-dynamic streams (``EventType.java:24-27``)."""

    EDGE_ADDITION = "+"
    EDGE_DELETION = "-"


class Edge(NamedTuple):
    """A single host-side edge record: (src, dst, value).

    Mirrors Gelly's ``Edge<K, EV>``; ``value`` may be ``None`` for unweighted
    graphs (the reference's ``NullValue``).
    """

    src: int
    dst: int
    val: Any = None

    def reverse(self) -> "Edge":
        return Edge(self.dst, self.src, self.val)


class Vertex(NamedTuple):
    """A host-side vertex record (cf. Gelly ``Vertex<K, VV>``)."""

    id: int
    val: Any = None
