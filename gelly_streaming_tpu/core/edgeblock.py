"""EdgeBlock: the device-side unit of streaming graph data.

The reference streams edges one record at a time through Flink operators
(``SimpleEdgeStream.java``). A TPU cannot do per-record control flow: XLA
traces a program once and wants large, static-shaped, batched tensor ops that
tile onto the MXU/VPU. The TPU-native unit is therefore a *padded edge block*:

    src : int32[capacity]   compacted source vertex ids
    dst : int32[capacity]   compacted destination vertex ids
    val : float32[capacity] edge values (zeros for unweighted graphs)
    mask: bool[capacity]    True for real edges, False for padding

``capacity`` is always a power of two (see :func:`bucket_capacity`) so that a
stream of windows with varying edge counts hits only O(log N) distinct jit
signatures instead of recompiling per window — this addresses "hard part #1"
of SURVEY.md §7 (dynamic shapes).

Vertex ids inside a block are *compact* int32 indices produced by
:class:`~gelly_streaming_tpu.core.vertexdict.VertexDict`; raw (possibly
64-bit, sparse) ids never reach the device. ``n_vertices`` rides along as
static metadata so segment reductions know their output size.

Design note: this struct plays the role of Flink's in-flight edge partitions
(the data between the keyBy shuffle and the window fold,
``SummaryBulkAggregation.java:76-80``), but materialized as dense arrays so a
whole window is one compiled device step.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def bucket_capacity(n: int, minimum: int = 8) -> int:
    """Round ``n`` up to the next power of two (>= minimum).

    Capacity bucketing keeps the set of distinct jitted shapes logarithmic in
    the maximum window size, avoiding per-window recompilation.
    """
    cap = minimum
    while cap < n:
        cap *= 2
    return cap


# Shared device buffers for the per-window constants: the mask (True for the
# first n slots) takes only a couple of distinct n values per stream, and
# unweighted streams share one all-zeros val buffer per capacity — reusing
# them removes ~5 MB/window of host->device transfer on the ingest path.
# CAVEAT: these are shared immutable buffers; jitted consumers must never
# donate a block's mask/val argument.
_MASK_CACHE: dict = {}
_ZEROS_CACHE: dict = {}


def _cached_mask(cap: int, n: int):
    key = (cap, n)
    m = _MASK_CACHE.get(key)
    if m is None:
        if len(_MASK_CACHE) > 256:  # odd streams (every window a new n)
            _MASK_CACHE.clear()
        mp = np.zeros(cap, bool)
        mp[:n] = True
        m = jnp.asarray(mp)
        _MASK_CACHE[key] = m
    return m


def _cached_zeros(cap: int, dtype):
    key = (cap, np.dtype(dtype).str)
    z = _ZEROS_CACHE.get(key)
    if z is None:
        z = jnp.zeros(cap, dtype)
        _ZEROS_CACHE[key] = z
    return z


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class EdgeBlock:
    """A padded, masked batch of edges (one stream window or sub-window).

    All arrays share the same leading dimension (the capacity). ``n_vertices``
    is static metadata (the vertex-table capacity this block's compact ids
    index into) so that jit treats it as a compile-time constant.
    """

    src: jax.Array  # int32[capacity]
    dst: jax.Array  # int32[capacity]
    val: jax.Array  # float32[capacity] (or any dtype the stream carries)
    mask: jax.Array  # bool[capacity]
    n_vertices: int = dataclasses.field(metadata=dict(static=True), default=0)

    # ------------------------------------------------------------------ #
    # Introspection
    # ------------------------------------------------------------------ #
    @property
    def capacity(self) -> int:
        return int(self.src.shape[-1])

    def num_edges(self) -> jax.Array:
        """Number of valid (non-padding) edges, as a device scalar."""
        return jnp.sum(self.mask.astype(jnp.int32))

    # ------------------------------------------------------------------ #
    # Construction
    # ------------------------------------------------------------------ #
    @staticmethod
    def from_arrays(
        src: np.ndarray,
        dst: np.ndarray,
        val: Optional[np.ndarray] = None,
        *,
        n_vertices: int,
        capacity: Optional[int] = None,
        val_dtype=jnp.float32,
    ) -> "EdgeBlock":
        """Build a padded block from host arrays of compact int32 ids.

        The mask and (for valueless streams) the val column come from shared
        cached device buffers — see the module-level cache caveat.
        """
        n = int(np.asarray(src).shape[0])
        cap = capacity if capacity is not None else bucket_capacity(n)
        if n > cap:
            raise ValueError(f"{n} edges exceed capacity {cap}")
        if n == cap:
            src_p = np.ascontiguousarray(src, dtype=np.int32)
            dst_p = np.ascontiguousarray(dst, dtype=np.int32)
        else:
            src_p = np.zeros(cap, dtype=np.int32)
            dst_p = np.zeros(cap, dtype=np.int32)
            src_p[:n] = src
            dst_p[:n] = dst
        if val is None:
            val_d = _cached_zeros(cap, val_dtype)
        else:
            if n == cap:
                val_p = np.ascontiguousarray(val, dtype=np.dtype(val_dtype))
            else:
                val_p = np.zeros(cap, dtype=np.dtype(val_dtype))
                val_p[:n] = val
            val_d = jnp.asarray(val_p)
        return EdgeBlock(
            src=jnp.asarray(src_p),
            dst=jnp.asarray(dst_p),
            val=val_d,
            mask=_cached_mask(cap, n),
            n_vertices=int(n_vertices),
        )

    # ------------------------------------------------------------------ #
    # Host-side materialization (for tests / emission)
    # ------------------------------------------------------------------ #
    def to_host(self):
        """Return (src, dst, val) numpy arrays with padding stripped.

        ``val`` may be a pytree of arrays (e.g. after a tuple-valued
        ``map_edges``); masking is applied leaf-wise. Blocks built by the
        Windower carry their pre-padding host columns (``_host_cache``), so
        this is free on the ingest path — the device download only happens
        for blocks produced by device transforms.
        """
        cache = getattr(self, "_host_cache", None)
        if cache is not None:
            return cache
        mask = np.asarray(self.mask)
        val = jax.tree.map(lambda a: np.asarray(a)[mask], self.val)
        return (
            np.asarray(self.src)[mask],
            np.asarray(self.dst)[mask],
            val,
        )

    def with_host_cache(self, src, dst, val, positions=None) -> "EdgeBlock":
        """Attach pre-padding host columns (not part of the pytree: lost
        across jit/tree operations, which is correct — a transformed block
        must re-download).

        ``positions``: device slot index of each cached row. ``None``
        declares PREFIX alignment (cached row i lives in device slot i) —
        only valid when the block's mask is a prefix mask. Producers that
        cache rows of a block with holes in its mask (e.g. ``distinct()``)
        must pass the real slot positions, or consumers that map host rows
        back to device slots (``ExactTriangleCount``) silently misalign.
        """
        object.__setattr__(self, "_host_cache", (src, dst, val))
        object.__setattr__(self, "_host_cache_pos", positions)
        return self

    def with_vertices(self, n_vertices: int) -> "EdgeBlock":
        return dataclasses.replace(self, n_vertices=int(n_vertices))


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class StackedEdgeBlock:
    """K consecutive windows stacked into one ``[K, cap]`` device batch.

    The superbatch execution unit (ISSUE 2): below ~64k-edge windows the
    per-window fixed cost — one host block assembly plus one jitted
    dispatch — dominates the measured latency curve (BENCH_CPU.json:
    714k eps at 1024-edge windows vs 15.5M at 1M). Packing K windows
    into one stacked block lets the engine run the K window steps as a
    single ``lax.scan`` dispatch (``SummaryAggregation._superbatch_step``)
    while each window keeps its own mask row, so per-window emission
    semantics are preserved exactly.

    All rows share one capacity (the bucketed max of the member windows)
    so a stream hits O(log N) x O(distinct K) jit signatures. ``val`` may
    be a pytree with ``[K, cap]``-leading leaves, mirroring EdgeBlock.
    """

    src: jax.Array  # int32[k, capacity]
    dst: jax.Array  # int32[k, capacity]
    val: Any  # [k, capacity] leaves
    mask: jax.Array  # bool[k, capacity]
    n_vertices: int = dataclasses.field(metadata=dict(static=True), default=0)

    @property
    def k(self) -> int:
        return int(self.src.shape[0])

    @property
    def capacity(self) -> int:
        return int(self.src.shape[-1])

    def window(self, i: int) -> EdgeBlock:
        """Device-sliced view of window ``i`` (used by fallbacks/tests;
        the engine's scan consumes the stacked arrays directly)."""
        return EdgeBlock(
            src=self.src[i],
            dst=self.dst[i],
            val=jax.tree.map(lambda v: v[i], self.val),
            mask=self.mask[i],
            n_vertices=self.n_vertices,
        )


def stack_host_cols(
    cols: Sequence, n_vertices: int, *, val_dtype=np.float32,
    capacity: Optional[int] = None,
) -> StackedEdgeBlock:
    """THE host ``[K, cap]`` packer: assemble per-window column triples
    ``(src, dst, val|None)`` of compact int32 ids into one
    :class:`StackedEdgeBlock`, crossing the host->device boundary ONCE
    per plane. Shared by :func:`stack_blocks`' fast path and
    ``SuperbatchGroup.stacked`` so the fill/dtype rules cannot drift:
    the val plane takes the dtype of the first non-None cached column
    (``val_dtype`` only when every window is valueless)."""
    counts = [len(c[0]) for c in cols]
    cap = capacity if capacity is not None else bucket_capacity(max(counts))
    k = len(cols)
    src = np.zeros((k, cap), np.int32)
    dst = np.zeros((k, cap), np.int32)
    mask = np.zeros((k, cap), bool)
    val0 = next((c[2] for c in cols if c[2] is not None), None)
    val = np.zeros((k, cap), val_dtype if val0 is None else val0.dtype)
    for i, (s, d, v) in enumerate(cols):
        n = counts[i]
        src[i, :n] = s
        dst[i, :n] = d
        mask[i, :n] = True
        if v is not None:
            val[i, :n] = v
    return StackedEdgeBlock(
        src=jnp.asarray(src), dst=jnp.asarray(dst),
        val=jnp.asarray(val), mask=jnp.asarray(mask),
        n_vertices=int(n_vertices),
    )


def stack_blocks(
    blocks: Sequence[EdgeBlock], capacity: Optional[int] = None
) -> StackedEdgeBlock:
    """Pack K EdgeBlocks into one :class:`StackedEdgeBlock`.

    Host fast path: when every block carries its pre-padding host cache
    with prefix alignment and a plain ndarray val column (the Windower
    ingest contract), the ``[K, cap]`` arrays are assembled in numpy and
    cross the host->device boundary ONCE — K-fold fewer transfers than K
    separate blocks. Device-transformed blocks (no host cache, or hole-y
    masks / pytree vals) fall back to on-device pad + stack.
    """
    if not blocks:
        raise ValueError("stack_blocks needs at least one block")
    n_vertices = max(b.n_vertices for b in blocks)
    host_fast = all(
        getattr(b, "_host_cache", None) is not None
        and getattr(b, "_host_cache_pos", None) is None
        and isinstance(b._host_cache[2], np.ndarray)
        for b in blocks
    )
    if host_fast:
        return stack_host_cols(
            [b._host_cache for b in blocks], n_vertices, capacity=capacity
        )
    cap = capacity if capacity is not None else bucket_capacity(
        max(b.capacity for b in blocks)
    )

    def pad(a, fill=0):
        short = cap - a.shape[-1]
        if short == 0:
            return a
        return jnp.concatenate(
            [a, jnp.full(a.shape[:-1] + (short,), fill, a.dtype)], axis=-1
        )

    return StackedEdgeBlock(
        src=jnp.stack([pad(b.src) for b in blocks]),
        dst=jnp.stack([pad(b.dst) for b in blocks]),
        val=jax.tree.map(lambda *vs: jnp.stack([pad(v) for v in vs]),
                         *[b.val for b in blocks]),
        mask=jnp.stack([pad(b.mask, False) for b in blocks]),
        n_vertices=n_vertices,
    )


class EdgeAccumulator:
    """Device-resident growing edge list at bucketed capacity.

    The carried-graph workloads (incremental PageRank, streaming GraphSAGE,
    triangles) accumulate every window's edges; this keeps the arrays ON
    DEVICE and appends only the new window via ``dynamic_update_slice``, so
    per-window host->device transfer is O(new edges), not O(total).
    Capacity grows in power-of-two buckets (bounded recompiles downstream).
    """

    def __init__(self, min_capacity: int = 8):
        # callers sharding the columns over a mesh axis pass the axis size
        # so every capacity bucket stays divisible by it
        self.min_capacity = min_capacity
        self.src = jnp.zeros(0, jnp.int32)
        self.dst = jnp.zeros(0, jnp.int32)
        self.n_edges = 0

    def append(self, s: np.ndarray, d: np.ndarray) -> None:
        n_new = len(s)
        total = self.n_edges + n_new
        cap = bucket_capacity(total, minimum=self.min_capacity)
        if cap > self.src.shape[0]:
            pad = jnp.zeros(cap - self.src.shape[0], jnp.int32)
            self.src = jnp.concatenate([self.src, pad])
            self.dst = jnp.concatenate([self.dst, pad])
        if n_new:
            self.src = jax.lax.dynamic_update_slice(
                self.src, jnp.asarray(s, jnp.int32), (self.n_edges,)
            )
            self.dst = jax.lax.dynamic_update_slice(
                self.dst, jnp.asarray(d, jnp.int32), (self.n_edges,)
            )
        self.n_edges = total

    def mask(self) -> jax.Array:
        return jnp.arange(self.src.shape[0]) < self.n_edges

    def state_dict(self) -> dict:
        return {
            "src": np.asarray(self.src)[: self.n_edges],
            "dst": np.asarray(self.dst)[: self.n_edges],
        }

    def load_state_dict(self, d: dict) -> None:
        self.src = jnp.zeros(0, jnp.int32)
        self.dst = jnp.zeros(0, jnp.int32)
        self.n_edges = 0
        self.append(d["src"], d["dst"])


def concat_blocks(blocks: Sequence[EdgeBlock], capacity: Optional[int] = None) -> EdgeBlock:
    """Concatenate blocks into one (host-side; used by window re-bucketing).

    Pytree-valued ``val`` (e.g. after a tuple-valued ``map_edges``) is
    concatenated leaf-wise with dtypes preserved.
    """
    srcs, dsts, vals = [], [], []
    n_vertices = 0
    for b in blocks:
        s, d, v = b.to_host()
        srcs.append(s)
        dsts.append(d)
        vals.append(v)
        n_vertices = max(n_vertices, b.n_vertices)
    if not srcs:
        return EdgeBlock.from_arrays(
            np.zeros(0, np.int32), np.zeros(0, np.int32), None,
            n_vertices=n_vertices, capacity=capacity,
        )
    src = np.concatenate(srcs)
    dst = np.concatenate(dsts)
    val = jax.tree.map(lambda *leaves: np.concatenate(leaves), *vals)
    return from_arrays_tree(src, dst, val, n_vertices=n_vertices, capacity=capacity)


def from_arrays_tree(
    src: np.ndarray,
    dst: np.ndarray,
    val: Any,
    *,
    n_vertices: int,
    capacity: Optional[int] = None,
) -> EdgeBlock:
    """Like :meth:`EdgeBlock.from_arrays` but with a pytree ``val`` whose
    leaf dtypes are preserved (padding with zeros of each leaf's dtype)."""
    n = int(np.asarray(src).shape[0])
    cap = capacity if capacity is not None else bucket_capacity(n)
    if n > cap:
        raise ValueError(f"{n} edges exceed capacity {cap}")

    def pad_leaf(a):
        a = np.asarray(a)
        out = np.zeros((cap,) + a.shape[1:], dtype=a.dtype)
        out[:n] = a
        return jnp.asarray(out)

    src_p = np.zeros(cap, dtype=np.int32)
    dst_p = np.zeros(cap, dtype=np.int32)
    mask_p = np.zeros(cap, dtype=bool)
    src_p[:n] = src
    dst_p[:n] = dst
    mask_p[:n] = True
    val_tree = jax.tree.map(pad_leaf, val) if val is not None else jnp.zeros(cap, jnp.float32)
    return EdgeBlock(
        src=jnp.asarray(src_p),
        dst=jnp.asarray(dst_p),
        val=val_tree,
        mask=jnp.asarray(mask_p),
        n_vertices=int(n_vertices),
    ).with_host_cache(
        np.asarray(src, np.int32), np.asarray(dst, np.int32),
        jax.tree.map(np.asarray, val) if val is not None
        else np.zeros(n, np.float32),
    )
