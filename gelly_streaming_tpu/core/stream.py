"""GraphStream / SimpleEdgeStream: the user-facing streaming-graph API.

TPU-native re-design of the reference's L2 layer (``GraphStream.java:38-141``,
``SimpleEdgeStream.java``). The surface mirrors the reference method-for-method
— properties (``get_vertices/get_edges/get_degrees/...``), transforms
(``map_edges/filter_*/distinct/reverse/undirected/union``), ``aggregate`` and
``slice`` — but the execution model is completely different:

- The reference pushes one boxed record at a time through Flink operators
  with per-key HashMap state. Here, the host discretizes the unbounded edge
  stream into padded :class:`EdgeBlock` windows (``core/window.py``), and
  every operation is a compiled, batched device step over a block.
- Per-record UDFs become vectorized array functions: e.g. ``filter_edges``
  takes ``pred(src, dst, val) -> bool[N]`` evaluated on whole blocks on the
  VPU, replacing ``FilterFunction.filter`` called per edge
  (``SimpleEdgeStream.java:290-293``).
- Keyed state becomes dense vertex tables indexed by compact ids (see
  ``core/vertexdict.py``): the degree streams carry an int32 degree vector
  instead of per-key HashMaps (``SimpleEdgeStream.java:461-478``).

Emission semantics (documented delta, SURVEY.md §7): the reference emits
per-record updates ("continuously improving" streams, ``README.md:26-32``);
here emission is per-block, change-only. With ``CountWindow(1)`` the two are
record-for-record identical — which is how the golden reference tests are
reproduced bit-exactly in ``tests/``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Iterable, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .edgeblock import EdgeBlock
from .types import Edge, EdgeDirection, Vertex
from .vertexdict import VertexDict
from .window import (
    CountWindow,
    EventTimeWindow,
    WindowPolicy,
    Windower,
    is_column_input,
)


class StreamContext:
    """Execution context: mesh + default knobs (the ``env`` analog).

    The reference threads a ``StreamExecutionEnvironment`` through every
    stream (``GraphStream.java:44``); here the context carries the optional
    ``jax.sharding.Mesh`` used by aggregations and any default window policy.
    """

    def __init__(self, mesh=None, default_window: Optional[WindowPolicy] = None):
        self.mesh = mesh
        self.default_window = default_window or CountWindow(1 << 16)


def _raw_table(vdict: VertexDict) -> jax.Array:
    """Cached device lookup table compact->raw (see VertexDict.raw_table)."""
    return vdict.raw_table()


class GraphStream:
    """Abstract supertype declaring the public API (``GraphStream.java:38-141``)."""

    def get_context(self) -> StreamContext:
        raise NotImplementedError

    def get_edges(self) -> Iterator[Edge]:
        raise NotImplementedError

    def get_vertices(self) -> Iterator[Vertex]:
        raise NotImplementedError

    def map_edges(self, fn) -> "GraphStream":
        raise NotImplementedError

    def filter_edges(self, pred) -> "GraphStream":
        raise NotImplementedError

    def filter_vertices(self, pred) -> "GraphStream":
        raise NotImplementedError

    def distinct(self) -> "GraphStream":
        raise NotImplementedError

    def reverse(self) -> "GraphStream":
        raise NotImplementedError

    def undirected(self) -> "GraphStream":
        raise NotImplementedError

    def union(self, other: "GraphStream") -> "GraphStream":
        raise NotImplementedError

    def get_degrees(self) -> Iterator[Tuple[int, int]]:
        raise NotImplementedError

    def get_in_degrees(self) -> Iterator[Tuple[int, int]]:
        raise NotImplementedError

    def get_out_degrees(self) -> Iterator[Tuple[int, int]]:
        raise NotImplementedError

    def number_of_edges(self) -> Iterator[int]:
        raise NotImplementedError

    def number_of_vertices(self) -> Iterator[int]:
        raise NotImplementedError

    def aggregate(self, summary_aggregation) -> Iterator[Any]:
        raise NotImplementedError


class SimpleEdgeStream(GraphStream):
    """The concrete edge-addition stream (``SimpleEdgeStream.java``).

    Parameters
    ----------
    edges:
        Iterable of host edge records ``(src, dst[, val])`` with raw ids, or
        ``None`` when constructing internally from a block iterator.
    window:
        Window policy used to discretize the stream into EdgeBlocks
        (the ingestion/event-time ``timeWindow`` analog). ``CountWindow`` by
        default for determinism.
    context:
        Shared :class:`StreamContext`.
    """

    def __init__(
        self,
        edges: Optional[Iterable[Tuple]] = None,
        window: Optional[WindowPolicy] = None,
        context: Optional[StreamContext] = None,
        vertex_dict: Optional[VertexDict] = None,
        *,
        _blocks: Optional[Callable[[], Iterator[EdgeBlock]]] = None,
        _vdict: Optional[VertexDict] = None,
    ):
        self.context = context or StreamContext()
        self._windower = None  # superbatch ingest fast path (see below)
        self._edges = None
        if _blocks is not None:
            assert _vdict is not None
            self._vdict = _vdict
            self._block_source = _blocks
        else:
            if edges is None:
                raise ValueError("either edges or _blocks must be given")
            policy = window or self.context.default_window
            windower = Windower(policy, vertex_dict)
            self._vdict = windower.vertex_dict
            edges_it = edges
            if is_column_input(edges):
                # numpy fast path: hand the columns straight to the
                # Windower (iter() would hide them behind a generic
                # iterator and fall back to per-record parsing)
                self._block_source = lambda: windower.blocks(edges_it)
            elif callable(getattr(edges, "iter_chunks", None)):
                # chunk-capable source (GeneratorSource): hand the
                # SOURCE to the Windower so its column-chunk fast path
                # applies — iter() would flatten it back to per-record
                # tuples
                self._block_source = lambda: windower.blocks(edges_it)
            else:
                self._block_source = lambda: windower.blocks(iter(edges_it))
            self._windower = windower
            self._edges = edges_it

    # ------------------------------------------------------------------ #
    # Plumbing
    # ------------------------------------------------------------------ #
    def get_context(self) -> StreamContext:
        return self.context

    @property
    def vertex_dict(self) -> VertexDict:
        return self._vdict

    def blocks(self) -> Iterator[EdgeBlock]:
        """The stream's window-block iterator (single use, like a DataStream)."""
        return self._block_source()

    def prefetched(self, depth: int = 2) -> "SimpleEdgeStream":
        """Same stream with host windowing overlapped against device compute
        (a background thread keeps ``depth`` blocks ready — SURVEY.md §7
        host↔device overlap).

        The shared VertexDict may run up to ``depth`` windows ahead of the
        consumer; blocks snapshot their own ``n_vertices`` at creation, so
        consumers sizing state from the block (the aggregation engine, CC,
        degrees) are unaffected — only code reading ``len(vertex_dict)``
        mid-stream observes the lead."""
        from .pipeline import prefetch

        source = self._block_source
        return SimpleEdgeStream(
            context=self.context,
            _blocks=lambda: prefetch(source(), depth),
            _vdict=self._vdict,
        )

    def superbatches(self, k: int):
        """Superbatch ingest: K consecutive windows per
        :class:`~gelly_streaming_tpu.core.window.SuperbatchGroup`.

        Streams built directly from edges route to the Windower's packer
        (zero per-window device work on the count-window column fast
        path); derived/prefetched/block-backed streams fall back to
        packing their block iterator. Single-use like :meth:`blocks`.
        """
        from .window import superbatches_from_blocks

        if self._windower is not None and self._edges is not None:
            return self._windower.superbatches(self._edges, k)
        return superbatches_from_blocks(self.blocks(), k)

    def superbatches_dynamic(self, k_fn, skip: int = 0):
        """Adaptive-K superbatch ingest (``superbatch="auto"``): like
        :meth:`superbatches` but the group size is re-read from
        ``k_fn()`` at every group boundary, so a controller
        (:class:`~gelly_streaming_tpu.control.AutoK`) re-tiles the
        stream mid-run. ``skip`` fast-forwards the first ``skip``
        windows through the packer without surfacing them (checkpoint
        resume). Single-use like :meth:`blocks`."""
        from .window import superbatches_from_blocks_dynamic

        if self._windower is not None and self._edges is not None:
            return self._windower.superbatches_dynamic(
                self._edges, k_fn, skip=skip
            )
        blocks = self.blocks()
        # drain the skip upfront (the shared consume-n idiom): the
        # remaining stream must not pay a per-block wrapper for a skip
        # that ended at item n
        for _ in range(skip):
            if next(blocks, None) is None:
                break
        return superbatches_from_blocks_dynamic(blocks, k_fn)

    def _derive(self, block_fn: Callable[[Iterator[EdgeBlock]], Iterator[EdgeBlock]]) -> "SimpleEdgeStream":
        parent_source = self._block_source
        return SimpleEdgeStream(
            context=self.context,
            _blocks=lambda: block_fn(parent_source()),
            _vdict=self._vdict,
        )

    # ------------------------------------------------------------------ #
    # Transforms (each is a compiled per-block device op)
    # ------------------------------------------------------------------ #
    def map_edges(self, fn: Callable) -> "SimpleEdgeStream":
        """Map edge values: ``fn(src, dst, val) -> new_val`` (vectorized).

        Replaces ``mapEdges``'s per-record MapFunction + the manual
        TypeInformation plumbing (``SimpleEdgeStream.java:217-247``) — output
        type is whatever array pytree ``fn`` returns.
        """
        vdict = self._vdict

        @jax.jit
        def _map(block: EdgeBlock, raw: jax.Array) -> EdgeBlock:
            import dataclasses as dc

            new_val = fn(raw[block.src], raw[block.dst], block.val)
            return dc.replace(block, val=new_val)

        def gen(blocks):
            for b in blocks:
                yield _map(b, _raw_table(vdict))

        return self._derive(gen)

    def filter_edges(self, pred: Callable) -> "SimpleEdgeStream":
        """Keep edges where ``pred(src, dst, val) -> bool[N]`` holds
        (``SimpleEdgeStream.java:290-293``)."""
        vdict = self._vdict

        @jax.jit
        def _filter(block: EdgeBlock, raw: jax.Array) -> EdgeBlock:
            import dataclasses as dc

            keep = pred(raw[block.src], raw[block.dst], block.val)
            return dc.replace(block, mask=block.mask & keep)

        def gen(blocks):
            for b in blocks:
                yield _filter(b, _raw_table(vdict))

        return self._derive(gen)

    def filter_vertices(self, pred: Callable) -> "SimpleEdgeStream":
        """Keep edges whose *both* endpoints satisfy ``pred(vertex_id) ->
        bool`` — the reference applies the vertex filter edge-wise to src and
        trg (``SimpleEdgeStream.java:257-281``)."""
        vdict = self._vdict

        @jax.jit
        def _filter(block: EdgeBlock, raw: jax.Array) -> EdgeBlock:
            import dataclasses as dc

            keep = pred(raw[block.src]) & pred(raw[block.dst])
            return dc.replace(block, mask=block.mask & keep)

        def gen(blocks):
            for b in blocks:
                yield _filter(b, _raw_table(vdict))

        return self._derive(gen)

    def reverse(self) -> "SimpleEdgeStream":
        """Swap src/dst (``SimpleEdgeStream.java:328-337``)."""

        @jax.jit
        def _rev(block: EdgeBlock) -> EdgeBlock:
            import dataclasses as dc

            return dc.replace(block, src=block.dst, dst=block.src)

        return self._derive(lambda blocks: (_rev(b) for b in blocks))

    def undirected(self) -> "SimpleEdgeStream":
        """Emit both directions of every edge
        (``SimpleEdgeStream.java:350-361``). Block capacity doubles."""

        @jax.jit
        def _undir(block: EdgeBlock) -> EdgeBlock:
            return EdgeBlock(
                src=jnp.concatenate([block.src, block.dst]),
                dst=jnp.concatenate([block.dst, block.src]),
                val=jax.tree.map(lambda v: jnp.concatenate([v, v]), block.val),
                mask=jnp.concatenate([block.mask, block.mask]),
                n_vertices=block.n_vertices,
            )

        return self._derive(lambda blocks: (_undir(b) for b in blocks))

    def distinct(self) -> "SimpleEdgeStream":
        """Drop duplicate (src, dst) pairs across the whole stream.

        The reference keeps a per-key neighbor HashSet in keyed state
        (``SimpleEdgeStream.java:301-323``); here the carried set is the
        native first-seen hash map over packed (src<<32|dst) keys — O(new
        keys) per window, memory bounded by the distinct-edge count (the
        same bound as the reference's HashSets), no per-window re-sort.
        Without the native toolchain, a carried sorted array updated by
        merge (searchsorted + insert, no full sort) stands in.
        """

        def gen(blocks):
            from ..native import NativeEncoder
            from ..utils.keyruns import SortedRunSet

            try:
                keyset = NativeEncoder()
            except Exception:
                keyset = None
            # fallback path: LSM sorted-run key set (utils/keyruns.py) —
            # amortized O(N log N) instead of the O(seen) array copy
            # np.insert paid per window (round-2 verdict weak #6)
            seen = SortedRunSet()

            for b in blocks:
                cache = getattr(b, "_host_cache", None)
                if cache is not None:
                    # windower-built block: stripped columns, prefix mask —
                    # no device download needed
                    s_h, d_h, v_h = cache
                    n = len(s_h)
                    mask = np.zeros(b.capacity, dtype=bool)
                    mask[:n] = True
                    src = np.zeros(b.capacity, np.int64)
                    dst = np.zeros(b.capacity, np.int64)
                    src[:n] = s_h
                    dst[:n] = d_h
                else:
                    mask = np.asarray(b.mask)
                    src = np.asarray(b.src).astype(np.int64)
                    dst = np.asarray(b.dst).astype(np.int64)
                key = np.where(mask, (src << 32) | dst, np.int64(-1))
                if keyset is not None:
                    before = len(keyset)
                    idx, _ = keyset.encode(key)
                    novel = idx >= before
                    # first in-window occurrence of each novel key: novel
                    # duplicates share one idx; np.unique keeps the first
                    _, first_pos = np.unique(idx, return_index=True)
                    is_first = np.zeros(idx.shape[0], dtype=bool)
                    is_first[first_pos] = True
                    fresh = mask & novel & is_first
                else:
                    _, first_idx = np.unique(key, return_index=True)
                    is_first = np.zeros(key.shape[0], dtype=bool)
                    is_first[first_idx] = True
                    dup = seen.contains(key) if len(seen) else np.zeros(
                        len(key), bool
                    )
                    fresh = mask & is_first & ~dup
                    new_keys = key[fresh]
                    if new_keys.size:
                        seen.add(np.sort(new_keys))
                import dataclasses as dc

                out = dc.replace(b, mask=jnp.asarray(fresh))
                if cache is not None:
                    keep = fresh[: len(s_h)]
                    out = out.with_host_cache(
                        s_h[keep], d_h[keep],
                        jax.tree.map(lambda a: np.asarray(a)[keep], v_h),
                        # fresh is NOT a prefix mask: record the device
                        # slot of every cached row
                        positions=np.nonzero(keep)[0].astype(np.int32),
                    )
                yield out

        return self._derive(gen)

    def union(self, other: "SimpleEdgeStream") -> "SimpleEdgeStream":
        """Merge two edge streams (``SimpleEdgeStream.java:343-345``).

        If the other stream uses a different VertexDict its blocks are
        re-encoded through this stream's dict so compact ids stay coherent.
        Blocks are pulled round-robin from both sources (streaming unions
        interleave; draining one side first would starve an unbounded other).
        """
        vdict = self._vdict
        self_source = self._block_source
        other_stream = other

        def reencode(b: EdgeBlock) -> EdgeBlock:
            if other_stream._vdict is vdict:
                return b
            s, d, v = b.to_host()
            raw_s = other_stream._vdict.decode(s)
            raw_d = other_stream._vdict.decode(d)
            enc = vdict.encode(np.stack([raw_s, raw_d], axis=1).ravel())
            return EdgeBlock.from_arrays(
                enc[0::2], enc[1::2], v,
                n_vertices=vdict.capacity, capacity=b.capacity,
            )

        def gen():
            a = self_source()
            b = map(reencode, other_stream._block_source())
            for blk in _interleave(a, b):
                yield blk

        return SimpleEdgeStream(context=self.context, _blocks=gen, _vdict=vdict)

    # ------------------------------------------------------------------ #
    # Property streams (continuously improving, per-block change-only)
    # ------------------------------------------------------------------ #
    def get_edges(self) -> "EmissionStream":
        """Edge property stream. LAZY batches: the decode (and, for
        device-transformed blocks, the ``to_host`` download) runs when a
        consumer first reads a window — the producer loop performs zero
        per-window D2H (round-3 verdict #8)."""
        vdict = self._vdict

        def batches():
            for b in self.blocks():
                def thunk(b=b):
                    src, dst, val = b.to_host()
                    return vdict.decode(src), vdict.decode(dst), _host_vals(val)

                yield LazyRecordBatch(
                    lambda s, d, v: Edge(int(s), int(d), v), thunk
                )

        from .emission import EmissionStream, LazyRecordBatch

        return EmissionStream(batches)

    def get_vertices(self) -> "EmissionStream":
        """Distinct vertices, emitted on first appearance
        (``SimpleEdgeStream.java:116-121,181-202``).

        Ingest-path blocks (host columns cached) take a vectorized numpy
        first-occurrence pass; device-transformed blocks keep the seen
        mask ON DEVICE — one dispatch per window, emission packed and
        downloaded lazily (O(window) bytes, only when read) — so neither
        path does per-window D2H in the producer loop.
        """
        vdict = self._vdict

        def batches():
            seen = np.zeros(0, bool)
            seen_dev = None
            for b in self.blocks():
                cache = getattr(b, "_host_cache", None)
                if cache is not None and seen_dev is None:
                    src, dst = cache[0], cache[1]
                    if len(src) == 0:
                        yield []
                        continue
                    if seen.size < b.n_vertices:
                        seen = np.concatenate(
                            [seen, np.zeros(b.n_vertices - seen.size, bool)]
                        )
                    both = np.stack([src, dst], axis=1).ravel()
                    uniq, first = np.unique(both, return_index=True)
                    fresh = ~seen[uniq]
                    new_ids = uniq[fresh]
                    seen[new_ids] = True
                    # first-appearance (arrival) order, as the reference
                    order = np.argsort(first[fresh], kind="stable")
                    raw = vdict.decode(new_ids[order])
                    yield RecordColumnBatch(lambda r: Vertex(int(r), None), raw)
                    continue
                # device path: carry the seen mask on device from the host
                # watermark so far; stays on device for the rest of the run.
                # Capacity growth happens ON device (concat with zeros) —
                # np.asarray(seen_dev) here would be a blocking O(V) D2H in
                # the producer loop at every bucket growth (round-4 advisor)
                if seen_dev is None:
                    base = np.zeros(b.n_vertices, bool)
                    base[: seen.size] = seen
                    seen_dev = jnp.asarray(base)
                elif seen_dev.shape[0] < b.n_vertices:
                    seen_dev = jnp.concatenate([
                        seen_dev,
                        jnp.zeros(b.n_vertices - seen_dev.shape[0], bool),
                    ])
                seen_dev, packed = _first_seen_update(
                    seen_dev, b.src, b.dst, b.mask
                )

                def thunk(packed=packed):
                    h = jax.device_get(packed)
                    k = int(np.count_nonzero(h >= 0))
                    return (vdict.decode(h[:k]),)

                yield LazyRecordBatch(lambda r: Vertex(int(r), None), thunk)

        from .emission import EmissionStream, LazyRecordBatch, RecordColumnBatch

        return EmissionStream(batches)

    def _degree_stream(self, in_: bool, out: bool) -> "EmissionStream":
        """Shared core of the degree streams (``SimpleEdgeStream.java:413-478``).

        Carried device state: an int32 degree vector over compact ids. Per
        block: masked scatter-add of endpoint increments; emit every vertex
        whose degree changed, with its new degree (change-only emission;
        per-record-identical at CountWindow(1)).
        """
        vdict = self._vdict

        def materialize(packed):
            h = jax.device_get(packed)
            k = int(np.count_nonzero(h[0] >= 0))
            return vdict.decode(h[0, :k]), h[1, :k]

        def batches():
            deg = jnp.zeros(0, dtype=jnp.int32)
            for b in self.blocks():
                if b.n_vertices > deg.shape[0]:
                    deg = jnp.concatenate(
                        [deg, jnp.zeros(b.n_vertices - deg.shape[0], jnp.int32)]
                    )
                deg, packed = _degree_update(deg, b, in_=in_, out=out)
                yield DeviceColumnBatch(functools.partial(materialize, packed))
            # one sync for the whole stream: all window dispatches above are
            # async; this makes the producer loop's wall time include the
            # actual device work without a per-window tunnel round-trip
            jax.block_until_ready(deg)

        from .emission import DeviceColumnBatch, EmissionStream

        return EmissionStream(batches)

    def get_degrees(self) -> "EmissionStream":
        return self._degree_stream(in_=True, out=True)

    def get_in_degrees(self) -> "EmissionStream":
        return self._degree_stream(in_=True, out=False)

    def get_out_degrees(self) -> "EmissionStream":
        return self._degree_stream(in_=False, out=True)

    def number_of_vertices(self) -> "EmissionStream":
        """Running distinct-vertex count, one emission per new vertex
        (``SimpleEdgeStream.java:366-383``, change-only via
        ``GlobalAggregateMapper`` ``:562-576``)."""
        from .emission import EmissionStream

        vertices = self.get_vertices()

        def batches():
            count = 0
            for batch in vertices.batches():
                k = len(batch)
                yield range(count + 1, count + k + 1)
                count += k

        return EmissionStream(batches)

    def number_of_edges(self) -> "EmissionStream":
        """Running edge count, one emission per edge
        (``SimpleEdgeStream.java:388-404``).

        Ingest-path blocks count from the cached host columns (free);
        device-transformed blocks chain the running total ON DEVICE and
        emit lazy ranges — the round-3 version downloaded every block's
        mask (a per-window D2H on a stack that otherwise forbids them)."""
        from .emission import EmissionStream, LazyCountRange

        def batches():
            total = 0  # int while counts are host-known; device scalar after
            device_mode = False
            for b in self.blocks():
                cache = getattr(b, "_host_cache", None)
                if cache is not None and not device_mode:
                    n = len(cache[0])
                    yield range(total + 1, total + n + 1)
                    total += n
                    continue
                if not device_mode:
                    total = jnp.int32(total)
                    device_mode = True
                n = _mask_count(b.mask)
                yield LazyCountRange(total, n)
                total = total + n

        return EmissionStream(batches)

    def global_aggregate(
        self,
        update: Callable[[Any, EdgeBlock], Tuple[Any, Any]],
        initial_state: Any,
        emit_change_only: bool = True,
    ) -> Iterator[Any]:
        """Generic carried global aggregate (``SimpleEdgeStream.java:505-519``).

        ``update(state, block) -> (state, emission)``; ``emission`` is
        yielded when it differs from the previous one (change-only).
        """
        state = initial_state
        prev = object()
        for b in self.blocks():
            state, emission = update(state, b)
            if not emit_change_only or not _emission_eq(emission, prev):
                yield emission
                prev = emission

    def vertex_aggregate(
        self, edge_mapper: Callable, vertex_mapper: Callable,
        max_out: int = 1,
    ) -> "EmissionStream":
        """Per-vertex aggregate of the edge stream — the reference's
        second ``aggregate`` overload (``SimpleEdgeStream.java:489-494``:
        ``edges.flatMap(edgeMapper).keyBy(0).map(vertexMapper)``; the
        keyBy only places records, so the composition is record-wise).

        TPU form: per window, ``edge_mapper(src_raw, dst_raw, val) ->
        ((key, value), emit)`` is vmapped over the block's edges —
        ``emit`` is a bool[max_out] mask and each of key/value carries a
        leading ``max_out`` dim, the same fixed-bucket flatMap shape as
        :meth:`SnapshotStream.flat_apply_on_neighbors` (``max_out=1``
        with scalar-shaped outputs covers the common map case) — then
        ``vertex_mapper(key, value) -> record`` vmaps over the emitted
        records. One dispatch per window; lazy per-window batches in
        edge-arrival order (per-record-identical at ``CountWindow(1)``).
        """
        vdict = self._vdict
        import jax

        # jitted ONCE per vertex_aggregate call: EmissionStreams are
        # re-iterable, and a jit defined inside batches() would rebuild
        # (and recompile, ~20-40 s/signature on the tunnel) per iteration
        @jax.jit
        def _window(block: EdgeBlock, raw):
            def per_edge(s, d, v):
                (key, val), emit = edge_mapper(raw[s], raw[d], v)
                key = jnp.atleast_1d(jnp.asarray(key))
                val = jnp.atleast_1d(jnp.asarray(val))
                emit = jnp.atleast_1d(jnp.asarray(emit))
                rec = jax.vmap(vertex_mapper)(key, val)
                return rec, emit

            rec, emit = jax.vmap(per_edge)(
                block.src, block.dst, block.val
            )
            emit = emit & block.mask[:, None]
            return rec, emit

        def _validate(rec, emit):
            if emit.ndim != 2 or emit.shape[1] != max_out:
                raise ValueError(
                    f"edge_mapper emitted {emit.shape[1:]} slots per "
                    f"edge but max_out={max_out}; the emit mask and "
                    "every record leaf must carry a leading "
                    "[max_out] dim (scalars count as max_out=1)"
                )
            for leaf in jax.tree.leaves(rec):
                got = leaf.shape[1] if leaf.ndim >= 2 else None
                if got != max_out:
                    raise ValueError(
                        f"record leaf has slot dim {got} but "
                        f"max_out={max_out}; key/value slots must match "
                        "the emit mask width"
                    )

        def batches():
            from .emission import LazyRecordBatch

            for b in self.blocks():
                rec, emit = _window(b, _raw_table(vdict))
                _validate(rec, emit)
                treedef = jax.tree.structure(rec)

                def thunk(rec=rec, emit=emit):
                    # ONE device round trip for the whole window (the
                    # tunnel charges ~0.5-3 s per transfer, not per byte
                    # class): emit + every leaf in a single device_get
                    em, *flat = jax.device_get(
                        (emit, *jax.tree.leaves(rec))
                    )
                    rows, ks = np.nonzero(np.asarray(em))
                    return tuple(np.asarray(a)[rows, ks] for a in flat)

                yield LazyRecordBatch(
                    lambda *vals, treedef=treedef: jax.tree.unflatten(
                        treedef, list(vals)
                    ),
                    thunk,
                )

        from .emission import EmissionStream

        return EmissionStream(batches)

    # ------------------------------------------------------------------ #
    # Aggregation + windowing entry points
    # ------------------------------------------------------------------ #
    def aggregate(self, summary_aggregation) -> Iterator[Any]:
        """Run a summary aggregation over this stream
        (``SimpleEdgeStream.java:100-102`` -> ``SummaryAggregation.run``)."""
        return summary_aggregation.run(self)

    def build_neighborhood(self, directed: bool = False) -> Iterator[Tuple]:
        """Per-edge neighborhood snapshots (``SimpleEdgeStream.java:531-560``).

        Emits ``(src, trg, neighbors)`` per processed edge — both directions
        when ``directed=False`` (the reference pre-applies ``undirected()``)
        — where ``neighbors`` is the sorted tuple of ``src``'s raw-id
        adjacency *as of that edge's arrival* (inclusive): the reference's
        per-vertex TreeSet state, arrival order preserved. API-parity host
        path; the device triangle pipeline
        (``library/triangles.py:ExactTriangleCount``) never materializes
        these snapshots.
        """
        adj: dict = {}

        def emit(a, b):
            adj.setdefault(a, set()).add(b)
            return (a, b, tuple(sorted(adj[a])))

        for block in self.blocks():
            s, d, _ = block.to_host()
            raw_s = self._vdict.decode(s)
            raw_d = self._vdict.decode(d)
            for a, b in zip(raw_s.tolist(), raw_d.tolist()):
                yield emit(a, b)
                if not directed:
                    yield emit(b, a)

    def slice(
        self,
        window: Optional[WindowPolicy] = None,
        direction: EdgeDirection = EdgeDirection.OUT,
    ):
        """Discretize into a stream of graph snapshots
        (``SimpleEdgeStream.java:135-167``).

        ``window=None`` reuses the stream's own block windows; otherwise the
        blocks are host-side re-discretized — by edge count
        (``CountWindow``) or by event time (``EventTimeWindow``, the
        ``slice(Time, dir)`` analog of ``SimpleEdgeStream.java:135-167``).
        Event-time re-windowing applies ``timestamp_fn`` to the host column
        tuple ``(raw_src, raw_dst, val)`` (vectorized, same contract as the
        array ingest path) and assumes ascending timestamps (the
        reference's ``AscendingTimestampExtractor`` contract); windows may
        span block boundaries.
        """
        from .snapshot import SnapshotStream

        source = self._block_source
        if window is None:
            block_iter_fn = source
        elif isinstance(window, CountWindow):
            block_iter_fn = lambda: _rewindow_count(source(), window.size)
        elif isinstance(window, EventTimeWindow):
            block_iter_fn = lambda: _rewindow_time(
                source(), window, self._vdict
            )
        else:
            raise TypeError(f"unknown window policy {window!r}")
        return SnapshotStream(block_iter_fn, direction, self._vdict, self.context)


# --------------------------------------------------------------------------- #
# Helpers
# --------------------------------------------------------------------------- #
@functools.partial(jax.jit, static_argnames=("in_", "out"))
def _degree_update(deg: jax.Array, block: EdgeBlock, *, in_: bool, out: bool):
    """One window's degree fold + on-device changed-vertex compaction.

    Module-level jit: the executable is shared across streams and
    get_degrees() calls — a per-call closure would recompile per invocation.

    Returns ``(new_deg, packed[2, K])`` with ``K = (in_ + out) *
    block.capacity``: row 0 the changed compact ids (ascending, ``-1``
    padding past the changed count), row 1 their new degrees. The changed
    vertices of a window are exactly its masked endpoints, so they are
    deduped (sort + first-occurrence compact) ON DEVICE and a consumer
    downloads O(window) — never O(vcap) — bytes per window, in ONE
    transfer. The previous design (download the full [vcap] delta vector +
    host ``np.nonzero``) cost ~3 s/window at 2^21 capacity through the
    remote tunnel (round-2 verdict weak #1).
    """
    from ..ops.segment import segment_count

    V = deg.shape[0]
    delta = jnp.zeros_like(deg)
    if out:
        delta = delta + segment_count(block.src, block.mask, V)
    if in_:
        delta = delta + segment_count(block.dst, block.mask, V)
    new_deg = deg + delta

    cands = []
    if out:
        cands.append(jnp.where(block.mask, block.src, V))
    if in_:
        cands.append(jnp.where(block.mask, block.dst, V))
    cand = jnp.concatenate(cands) if len(cands) > 1 else cands[0]
    sorted_c = jnp.sort(cand)
    K = sorted_c.shape[0]
    valid = sorted_c < V
    is_first = valid & jnp.concatenate(
        [jnp.ones(1, bool), sorted_c[1:] != sorted_c[:-1]]
    )
    pos = jnp.cumsum(is_first) - 1  # output slot per first occurrence
    ids = jnp.full(K, -1, sorted_c.dtype)
    ids = ids.at[jnp.where(is_first, pos, K)].set(sorted_c, mode="drop")
    degs = new_deg[jnp.clip(ids, 0, max(V - 1, 0))] if V else jnp.zeros(K, jnp.int32)
    return new_deg, jnp.stack([ids.astype(jnp.int32), degs])
@jax.jit
def _mask_count(mask):
    return mask.sum(dtype=jnp.int32)


@jax.jit
def _first_seen_update(seen, src, dst, mask):
    """One window's first-appearance pass, fully on device: scatter-min
    the arrival position of every masked endpoint, mark vertices not in
    ``seen``, and emit their ids packed in ARRIVAL order (-1 padding past
    the new-vertex count) — the consumer downloads O(window) lazily.
    Module-level jit: shared across streams (same reason as
    :func:`_degree_update`)."""
    V = seen.shape[0]
    E = src.shape[0]
    big = jnp.int32(2 * E)
    # interleaved endpoints, matching the host path's arrival order:
    # src_0, dst_0, src_1, dst_1, ...
    both = jnp.stack([src, dst], axis=1).ravel()
    bm = jnp.stack([mask, mask], axis=1).ravel()
    posv = jnp.full(V, big, jnp.int32).at[
        jnp.where(bm, both, V)
    ].min(jnp.arange(2 * E, dtype=jnp.int32), mode="drop")
    occurred = posv < big
    new = occurred & ~seen
    sortkey = jnp.where(new, posv, big)
    K = min(2 * E, V)  # new vertices per window <= masked endpoints
    order = jnp.argsort(sortkey)[:K]
    ids = jnp.where(sortkey[order] < big, order.astype(jnp.int32), -1)
    return seen | occurred, ids


def _host_vals(val) -> list:
    """Convert a (possibly pytree) value batch to a list of python records."""
    leaves = jax.tree.leaves(val)
    if not leaves:
        return []
    n = leaves[0].shape[0]
    if len(leaves) == 1 and isinstance(val, np.ndarray):
        return [v.item() if np.ndim(v) == 0 else v for v in val]
    structured = [jax.tree.map(lambda a: a[i].item() if np.ndim(a[i]) == 0 else np.asarray(a[i]), val) for i in range(n)]
    return structured


def _interleave(*iters: Iterator) -> Iterator:
    """Round-robin over iterators until all are exhausted."""
    active = list(iters)
    while active:
        nxt = []
        for it in active:
            try:
                yield next(it)
                nxt.append(it)
            except StopIteration:
                pass
        active = nxt


def _emission_eq(a, b) -> bool:
    if a is b:
        return True
    try:
        la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
        if len(la) != len(lb):
            return False
        return all(np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb))
    except Exception:
        return False


def _rewindow_count(blocks: Iterator[EdgeBlock], size: int) -> Iterator[EdgeBlock]:
    """Re-discretize a block stream into count windows of ``size`` edges.

    Pytree-valued ``val`` is sliced leaf-wise (tuple-valued ``map_edges``
    upstream of ``slice()`` is supported). Buffering happens on HOST
    columns: windower-built blocks carry their host cache, so the merge
    is pure numpy — the previous device ``concat_blocks`` + ``to_host``
    per output window cost one 8 MB device download per million edges.
    """
    from .edgeblock import from_arrays_tree

    pend: list = []  # (src, dst, val) host column tuples
    buffered = 0
    n_vertices = 0

    def merged_cols():
        if len(pend) == 1:
            return pend[0]
        s = np.concatenate([p[0] for p in pend])
        d = np.concatenate([p[1] for p in pend])
        v = jax.tree.map(lambda *ls: np.concatenate(ls), *[p[2] for p in pend])
        return s, d, v

    for b in blocks:
        s, d, v = b.to_host()
        if len(s) == 0:
            continue
        n_vertices = max(n_vertices, b.n_vertices)
        pend.append((s, d, v))
        buffered += len(s)
        while buffered >= size:
            s, d, v = merged_cols()
            head_v = jax.tree.map(lambda a: a[:size], v)
            yield from_arrays_tree(
                s[:size], d[:size], head_v, n_vertices=n_vertices
            )
            pend = (
                [(s[size:], d[size:], jax.tree.map(lambda a: a[size:], v))]
                if len(s) > size
                else []
            )
            buffered -= size
    if buffered:
        s, d, v = merged_cols()
        yield from_arrays_tree(s, d, v, n_vertices=n_vertices)


def _rewindow_time(
    blocks: Iterator[EdgeBlock], policy: EventTimeWindow, vdict
) -> Iterator[EdgeBlock]:
    """Re-discretize a block stream into tumbling event-time windows.

    ``policy.timestamp_fn`` is applied to the host column tuple
    ``(raw_src, raw_dst, val)``; an index-based extractor (``lambda e:
    e[2]``) selects the same column it would per-record. Ascending
    timestamps assumed; a window flushes when a later slot appears, so one
    window may assemble from several upstream blocks.
    """
    from .edgeblock import from_arrays_tree

    if policy.timestamp_fn is None:
        raise ValueError(
            "EventTimeWindow requires timestamp_fn — without it the edge "
            "value would silently be read as the event time"
        )
    pend: list = []  # (src, dst, val) column slices of the open window
    slot: Optional[int] = None
    n_vertices = 0

    def flush() -> Optional[EdgeBlock]:
        if not pend:
            return None
        s = np.concatenate([p[0] for p in pend])
        d = np.concatenate([p[1] for p in pend])
        v = jax.tree.map(lambda *leaves: np.concatenate(leaves), *[p[2] for p in pend])
        pend.clear()
        return from_arrays_tree(s, d, v, n_vertices=n_vertices)

    for b in blocks:
        s, d, v = b.to_host()
        n = len(s)
        if n == 0:
            continue
        n_vertices = max(n_vertices, b.n_vertices)
        raw_s = vdict.decode(s)
        raw_d = vdict.decode(d)
        ts = np.asarray(policy.timestamp_fn((raw_s, raw_d, v)), np.float64)
        if ts.shape != (n,):
            raise ValueError(
                "EventTimeWindow.timestamp_fn returned shape "
                f"{ts.shape} re-windowing a block of {n} edges"
            )
        slots = (ts // policy.size).astype(np.int64)
        bounds = np.nonzero(np.diff(slots))[0] + 1
        starts = np.concatenate([[0], bounds])
        ends = np.concatenate([bounds, [n]])
        for a, e in zip(starts, ends):
            run_slot = int(slots[a])
            if slot is not None and run_slot != slot:
                w = flush()
                if w is not None:
                    yield w
            slot = run_slot
            pend.append(
                (s[a:e], d[a:e], jax.tree.map(lambda x: x[a:e], v))
            )
    w = flush()
    if w is not None:
        yield w
