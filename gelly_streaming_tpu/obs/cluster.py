"""Cluster observability: per-shard event shipping + one merged registry.

PR 5 made the system genuinely multi-process, but PR 3's observability
stayed process-local: each worker's registry/span stream died with its
process, and a distributed chaos run's story had to be reconstructed by
hand from per-worker files. This module is the framework-owns-the-
global-view analog for telemetry (the same stance PAPER.md takes for
graph state — operators keep distributed summaries, the framework
merges them):

- :class:`ShardSink` is the per-worker event shipper: a drop-in
  replacement for :class:`~gelly_streaming_tpu.obs.export.JsonlSink`
  that APPENDS each event to its shard's JSONL file the moment it is
  emitted (flushed through the Python buffer, so everything emitted
  before an ``os._exit`` kill survives in the OS page cache — the
  pre-crash evidence the chaos harness reads). Each event is stamped
  with a wall-clock ``ts`` (metric mutations previously carried none)
  so shard streams can be merged into one causal order.
- :class:`ClusterAggregator` tails any number of shard files into ONE
  merged, shard-labeled registry. Merging IS replay: each shard's
  metric events are fed through
  :func:`~gelly_streaming_tpu.obs.export.replay` with ``shard=<id>``
  folded into the labels, so the merged snapshot equals, by
  construction AND by test, the union of per-worker ``replay()``
  results with the shard label attached. Tailing is incremental
  (byte offsets per file, partial trailing lines left for the next
  poll), so one aggregator can follow a LIVE cluster.
- :func:`iter_shard_events` is the batch form: every shard event under
  a directory, shard-stamped and time-ordered — what the merged bench
  artifact (``BENCH_CHAOS_MP_CPU_OBS.jsonl``) and the timeline tool
  (:mod:`~gelly_streaming_tpu.obs.timeline`) both consume.

The merged registry is what the scrape endpoint
(:mod:`~gelly_streaming_tpu.obs.endpoint`) renders for a cluster, and
the prerequisite surface the ROADMAP's self-tuning control plane reads.
"""

from __future__ import annotations

import glob as _glob
import json
import os
import re
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

from .export import replay
from .registry import MetricRegistry

#: shard event file shape: ``events.jsonl`` (single shard, shard "p0")
#: or ``events.p<N>.jsonl``
SHARD_FILE_RE = re.compile(r"^events(?:\.p(\d+))?\.jsonl$")


def shard_events_path(directory: str, shard: int) -> str:
    """The canonical per-shard event file name the chaos workers and
    the aggregator agree on."""
    return os.path.join(directory, f"events.p{int(shard)}.jsonl")


def shard_of(path: str) -> Optional[str]:
    """Shard id for a shard event file name (``"p0"``, ``"p1"``, ...);
    None when the name is not a shard event file."""
    m = SHARD_FILE_RE.match(os.path.basename(path))
    if m is None:
        return None
    return f"p{m.group(1) or 0}"


class ShardSink:
    """Streaming JSONL event sink for one worker/shard.

    Unlike :class:`~gelly_streaming_tpu.obs.export.JsonlSink` (an
    in-memory buffer written on clean exit), every ``emit`` appends one
    line to ``path`` and flushes it — a worker killed with ``os._exit``
    keeps every event it emitted before the kill, which is exactly the
    evidence a crash post-mortem needs. Events are stamped with
    ``ts`` (wall clock, only when absent — span events already carry
    one) and, when ``shard`` is given, a ``shard`` id, so downstream
    merging needs no out-of-band bookkeeping.

    The file opens lazily on the first event and is append-mode: a
    restarted worker pointed at the same path CONTINUES its shard's
    stream rather than truncating its own pre-crash history.
    """

    def __init__(self, path: str, *, shard: Optional[int] = None):
        self.path = path
        self.shard = None if shard is None else f"p{int(shard)}"
        self._lock = threading.Lock()
        self._f = None
        self._count = 0
        self._broken = False

    def emit(self, event: dict) -> None:
        if self._broken:
            return
        e = dict(event)
        if "ts" not in e:
            e["ts"] = time.time()
        if self.shard is not None and "shard" not in e:
            e["shard"] = self.shard
        line = json.dumps(e) + "\n"
        failed = False
        with self._lock:
            if self._broken:
                return
            try:
                if self._f is None:
                    d = os.path.dirname(self.path)
                    if d:
                        os.makedirs(d, exist_ok=True)
                    # graftlint: disable=GL009 (this lock IS the shard file's single-writer serializer; the lazy one-time open and each append must happen under it so interleaved emits cannot tear a JSONL line)
                    self._f = open(self.path, "a")
                self._f.write(line)
                self._f.flush()
                self._count += 1
            except OSError:
                # telemetry must never take the pipeline down: a full
                # disk / revoked fd stops THIS sink (latched, so the
                # failure is one-shot), not the worker emitting into it
                self._broken = True
                failed = True
        if failed:
            from .registry import get_registry

            # counted OUTSIDE the sink lock: the counter's own _emit
            # re-enters every attached sink (including this one, now
            # latched broken) and self._lock is not reentrant
            get_registry().counter(  # graftlint: disable=GL005 (one-shot cold error path — the sink is latched broken above, so this runs at most once per sink lifetime, never per event)
                "obs.swallowed", site="shard_sink"
            ).inc()

    def __len__(self) -> int:
        with self._lock:
            return self._count

    def write(self, path: Optional[str] = None) -> str:
        """JsonlSink-compatible no-op: events are already on disk.
        Returns the path (ignores the override — the stream has one
        home by design)."""
        return self.path

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


# --------------------------------------------------------------------- #
# Reading shard streams back
# --------------------------------------------------------------------- #
def _split_complete_lines(data: str) -> Tuple[List[str], str]:
    """Split buffered data into complete lines plus the partial trailing
    line (a worker killed mid-write, or a tail race with a live writer)
    to carry into the next poll."""
    end = data.rfind("\n")
    if end < 0:
        return [], data
    return data[: end + 1].splitlines(), data[end + 1:]


def discover_shard_files(root: str, recursive: bool = True) -> Dict[str, str]:
    """Map shard id -> path for every shard event file under ``root``.

    Shard ids are the ``p<N>`` from the file name; when ``root`` holds
    several runs (the chaos sweep's per-point directories) the relative
    directory is folded in (``kill_003/p0``) so shards never collide
    across runs.
    """
    if os.path.isfile(root):
        sid = shard_of(root) or "p0"
        return {sid: root}
    pattern = os.path.join(root, "**" if recursive else "", "events*.jsonl")
    out: Dict[str, str] = {}
    for path in sorted(_glob.glob(pattern, recursive=recursive)):
        sid = shard_of(path)
        if sid is None:
            continue
        rel = os.path.relpath(os.path.dirname(path), root)
        if rel not in (".", ""):
            sid = f"{rel.replace(os.sep, '/')}/{sid}"
        out[sid] = path
    return out


def label_shard(event: dict, shard: str) -> dict:
    """The ONE transformation merging applies to a metric event: fold
    the shard id into its labels (span/meta events get a top-level
    ``shard`` tag instead — they are evidence, not registry state).

    ``shard`` is the file-derived id. When it is a run-prefixed form of
    the event's own stamp (``kill_003/p0`` vs a :class:`ShardSink`'s
    ``p0``) the prefixed id wins — that prefix is exactly what keeps
    same-numbered shards from colliding across the runs of a sweep
    directory (:func:`discover_shard_files`'s no-collision promise).
    An event whose stamp names a DIFFERENT shard keeps its own id: the
    input is an already-merged stream, and the per-event stamps are the
    only true ids it has."""
    e = dict(event)
    es = e.get("shard")
    if not es or shard == es or shard.endswith(f"/{es}"):
        sid = shard
    else:
        sid = es
    if e.get("kind") in ("counter", "gauge", "hist"):
        labels = dict(e.get("labels") or {})
        labels.setdefault("shard", sid)
        e["labels"] = labels
    e["shard"] = sid
    return e


class ClusterAggregator:
    """Tail per-shard event streams into one merged, shard-labeled
    registry.

    ``source`` is a directory (shard files discovered by name, re-
    globbed every poll so late-joining workers are picked up), a single
    shard file, or an explicit ``{shard_id: path}`` mapping. Each
    :meth:`poll` consumes newly-appended COMPLETE lines from every
    shard file and replays the metric events into :attr:`registry`
    with ``shard=<id>`` folded into the labels — per-shard event order
    is preserved (replay determinism needs nothing more: shards never
    share an instrument, their label sets differ by construction).

    The merged snapshot therefore equals the union of per-worker
    ``replay()`` results with the shard label attached — the identity
    ``tests/test_obs_cluster.py`` pins against the PR 3 replay
    implementation itself.
    """

    def __init__(
        self,
        source,
        *,
        registry: Optional[MetricRegistry] = None,
        keep_events: int = 4096,
    ):
        self._source = source
        self.registry = registry if registry is not None else MetricRegistry()
        self._offsets: Dict[str, int] = {}
        self._tails: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._keep_events = int(keep_events)
        self._consumed = 0

    # ------------------------------------------------------------------ #
    def _shard_files(self) -> Dict[str, str]:
        if isinstance(self._source, dict):
            return {str(k): v for k, v in self._source.items()}
        return discover_shard_files(self._source)

    def poll(self) -> int:
        """Consume newly-appended events from every shard file; returns
        how many events were merged this poll. Safe against a live
        writer: only complete lines are consumed, and a line that fails
        to parse (a torn write racing the reader) is retried on the
        next poll rather than dropped."""
        merged = 0
        with self._lock:
            for sid, path in sorted(self._shard_files().items()):
                try:
                    # graftlint: disable=GL009 (the aggregator lock serializes the per-file offset/tail cursors with the reads that advance them; polling IS the lock's only workload, there is no other waiter class)
                    with open(path) as f:
                        f.seek(self._offsets.get(path, 0))
                        data = self._tails.get(path, "") + f.read()
                        self._offsets[path] = f.tell()
                except OSError:
                    continue  # not born yet / raced a cleanup: next poll
                lines, self._tails[path] = _split_complete_lines(data)
                batch = []
                for line in lines:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        batch.append(label_shard(json.loads(line), sid))
                    except ValueError:
                        # a torn line mid-file cannot heal (only the
                        # TAIL races a writer); skip it but keep count
                        self._events.append({
                            "kind": "meta", "name": "aggregator.torn_line",
                            "shard": sid,
                        })
                replay(batch, self.registry)
                self._events.extend(batch)
                merged += len(batch)
            self._consumed += merged
            if len(self._events) > self._keep_events:
                del self._events[: len(self._events) - self._keep_events]
        return merged

    # ------------------------------------------------------------------ #
    def snapshot(self) -> dict:
        """Poll, then return the merged registry's snapshot."""
        self.poll()
        return self.registry.snapshot()

    def events(self, last: Optional[int] = None) -> List[dict]:
        """The merged, shard-stamped event stream (bounded by
        ``keep_events``); ``last`` trims to the newest N (0 means
        none — not all; ``evs[-0:]`` would invert the bound)."""
        with self._lock:
            evs = list(self._events)
        if last is None:
            return evs
        return evs[-last:] if last > 0 else []

    @property
    def consumed(self) -> int:
        with self._lock:
            return self._consumed


def iter_shard_events(root, *, order: bool = True) -> Iterator[dict]:
    """Every shard event under ``root`` (directory / file / mapping),
    shard-stamped via :func:`label_shard`. With ``order=True`` events
    are globally sorted by ``ts`` (events without one inherit the last
    seen timestamp in their shard file, preserving in-shard order) —
    the merged stream the chaos bench commits and the timeline tool
    renders."""
    files = (
        {str(k): v for k, v in root.items()} if isinstance(root, dict)
        else discover_shard_files(root)
    )
    out: List[Tuple[float, int, dict]] = []
    seq = 0
    for sid in sorted(files):
        last_ts = 0.0
        try:
            with open(files[sid]) as f:
                raw = f.read()
        except OSError:
            continue
        for line in raw.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                e = label_shard(json.loads(line), sid)
            except ValueError:
                continue  # torn final line of a killed worker
            ts = e.get("ts")
            if isinstance(ts, (int, float)):
                last_ts = float(ts)
            else:
                e["ts"] = last_ts
            out.append((float(e["ts"]), seq, e))
            seq += 1
    if order:
        out.sort(key=lambda t: (t[0], t[1]))
    for _, _, e in out:
        yield e


def iter_trace_events(root, trace_id: str,
                      *, order: bool = True) -> Iterator[dict]:
    """One trace's events across every shard under ``root`` — the
    span/metric events stamped with ``trace == trace_id``, shard-
    stamped and ``ts``-ordered. This is the cross-process join the
    trace surface stands on: a client process's batch span, a dead
    primary's partial decode/admit spans, and the promoted standby's
    answer spans all carry the same trace id, so this filter over the
    merged stream IS the causal story (rendered by
    ``obs.timeline --trace <id>``, served by the endpoint's
    ``/trace/<id>``)."""
    for e in iter_shard_events(root, order=order):
        if e.get("trace") == trace_id:
            yield e
