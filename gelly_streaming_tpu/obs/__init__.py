"""Unified observability: metric registry + pipeline spans + exporters.

The reference has no metrics layer at all (one example prints
``getNetRuntime()``); SURVEY.md §5 directs building per-window timing
from day one while keeping the reference's design stance that metrics
are ordinary OUTPUT STREAMS, never a side server. After the serving
(PR 1) and superbatch (PR 2) layers, telemetry lived in two
disconnected ad-hoc modules; this package is the one coherent layer the
ROADMAP follow-ons (auto-K from measured window cost, multi-host
fan-out) read from:

- :mod:`registry` — process-wide thread-safe counters/gauges/bounded
  histograms; :func:`~gelly_streaming_tpu.obs.registry.nearest_rank`
  is THE shared percentile rule.
- :mod:`trace` — ``span("pack")`` structured spans, nested per thread,
  near-zero when disabled, optional ``jax.profiler`` annotation.
- :mod:`export` — JSONL event log (replayable:
  :func:`~gelly_streaming_tpu.obs.export.replay` reconstructs an
  identical registry), Prometheus text renderer, periodic snapshots
  composable with any emission stream.
- :mod:`cluster` — the multi-process plane (ISSUE 7): per-shard
  streaming :class:`~gelly_streaming_tpu.obs.cluster.ShardSink` event
  shipping merged by
  :class:`~gelly_streaming_tpu.obs.cluster.ClusterAggregator` into one
  shard-labeled registry (snapshot == union of per-worker replays).
- :mod:`endpoint` — stdlib HTTP scrape surface (``/metrics`` /
  ``/healthz`` / ``/events``) over any registry or aggregator.
- :mod:`flight` — crash flight recorder: a bounded ring of the last N
  events, atomically dumped on worker death / fault kills / supervisor
  restarts and collected into failure reports.
- :mod:`timeline` — ``python -m gelly_streaming_tpu.obs.timeline
  <dir>`` merges a run's shard logs + flight dumps into one ordered
  story.

Usage::

    from gelly_streaming_tpu import obs

    obs.enable()                      # spans + hot-path gauges on
    sink = obs.JsonlSink("run.jsonl")
    obs.attach_sink(sink)             # event log: spans + metric events
    ... run the pipeline ...
    obs.get_registry().snapshot()     # plain-dict metrics
    sink.write()                      # span/metric evidence to disk
    obs.detach_sink(sink); obs.disable()

Instrumented stages (all gated on ``obs.enable()`` except the serving
stats, which are part of the serving API and always on):
``window.pack`` / ``window.superbatch_pack`` / ``window.stack`` host
packing, ``engine.dispatch`` / ``engine.superbatch_dispatch`` device
dispatch (+ ``engine.donated_dispatches`` counter),
``pipeline.queue_depth`` / ``producer_blocked_s`` / ``consumer_idle_s``
prefetch coupling, ``checkpoint.barrier`` / ``barrier_wait`` /
``serialize``, and the ``serving.*`` admission/batch/drain surface.

Resilience events (PR 4) are ALWAYS on — a restart or a rejected
checkpoint is operational truth, not optional telemetry:
``resilience.restarts{kind}`` / ``recovery_seconds`` /
``deduped_windows`` / ``backoff_s`` / ``poison_windows`` /
``ckpt_rejected`` / ``fault_injected{site}``,
``pipeline.producer_leaked`` / ``pipeline.stalls``,
``source.reconnects`` / ``source.malformed_lines``, and
``serving.shed{cls}`` / ``retries`` / ``deadline_expired`` /
``worker_stalls`` (see ``gelly_streaming_tpu/resilience/__init__.py``).
"""

from .registry import (
    Counter,
    Gauge,
    Histogram,
    MetricRegistry,
    format_key,
    get_registry,
    nearest_rank,
    set_registry,
)
from .trace import (
    NOOP_SPAN,
    Span,
    TraceContext,
    activate,
    current_context,
    current_span,
    disable,
    enable,
    enabled,
    new_trace_id,
    next_sid,
    on,
    record_span,
    span,
)
from . import trace as _trace
from .export import (
    JsonlSink,
    prometheus_text,
    read_jsonl,
    replay,
    snapshot_stream,
    write_jsonl,
)
from .cluster import (
    ClusterAggregator,
    ShardSink,
    iter_shard_events,
    iter_trace_events,
    shard_events_path,
)
from .flight import FlightRecorder, read_dump
from . import flight as _flight


def __getattr__(name: str):
    # MetricsEndpoint is lazy on purpose: hot-path modules import this
    # package for get_registry/trace, and the endpoint's http.server /
    # socketserver chain is startup cost no obs-disabled run should pay
    # for a scrape surface it never starts (cluster/flight stay eager —
    # they ARE the always-on sink path).
    if name == "MetricsEndpoint":
        from .endpoint import MetricsEndpoint

        return MetricsEndpoint
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def attach_sink(sink) -> None:
    """Attach one sink to BOTH event sources: finished spans (tracer)
    and metric mutations (the global registry). One call gives one
    unified chronological event log."""
    _trace.add_sink(sink)
    get_registry().add_sink(sink)


def detach_sink(sink) -> None:
    _trace.remove_sink(sink)
    get_registry().remove_sink(sink)


def reset() -> None:
    """Test/bench hygiene: disable tracing, drop all tracer sinks,
    uninstall any flight recorder, and install a fresh global
    registry."""
    disable()
    _flight.uninstall()
    for s in _trace.sinks():
        _trace.remove_sink(s)
    set_registry(None)


__all__ = [
    "ClusterAggregator",
    "Counter",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "JsonlSink",
    "MetricRegistry",
    "MetricsEndpoint",
    "ShardSink",
    "NOOP_SPAN",
    "Span",
    "TraceContext",
    "activate",
    "attach_sink",
    "current_context",
    "current_span",
    "detach_sink",
    "disable",
    "enable",
    "enabled",
    "format_key",
    "get_registry",
    "iter_shard_events",
    "iter_trace_events",
    "nearest_rank",
    "new_trace_id",
    "next_sid",
    "on",
    "prometheus_text",
    "record_span",
    "read_dump",
    "read_jsonl",
    "replay",
    "reset",
    "set_registry",
    "shard_events_path",
    "snapshot_stream",
    "span",
    "write_jsonl",
]
