"""Stdlib HTTP scrape endpoint: /metrics, /healthz, /events.

PR 3 deliberately shipped the Prometheus renderer WITHOUT a server
(metrics are streams, the reference's stance); the ROADMAP's serving
tier then asked for the renderer "exposed as a scrape endpoint" — the
first network-facing surface of the repo. This module is that surface,
kept as thin as the stance allows: a ``ThreadingHTTPServer`` on a
daemon thread that renders EXISTING state on demand. Nothing is pushed,
buffered, or aggregated here; a scrape is a read.

Routes:

- ``GET /metrics`` — :func:`~gelly_streaming_tpu.obs.export.prometheus_text`
  over the endpoint's registry. With an attached
  :class:`~gelly_streaming_tpu.obs.cluster.ClusterAggregator` the
  aggregator is polled first, so a scrape of a cluster driver always
  renders the freshest merged, shard-labeled view.
- ``GET /healthz`` — JSON liveness: ``{"ok": true, "uptime_s": ...}``
  plus whatever the ``health`` callable reports (the serving tier wires
  worker liveness, pending depth, and promotion state in).
- ``GET /events`` — the newest N merged events as JSON lines (aggregator
  or flight-recorder tail), ``?n=`` bounded; the quick look a human
  takes before reaching for the timeline tool.
- ``GET /trace/<id>`` — the newest events of ONE trace as ndjson (the
  merged tail filtered to ``trace == id``): paste a latency exemplar's
  trace id and read that query's causal path live, without waiting for
  the committed logs.

Attachment points: :meth:`MetricsEndpoint.for_server` wires a
``StreamServer`` or ``FailoverServer`` (their ``metrics_endpoint()``
methods call it); the chaos driver passes an aggregator. ``port=0``
binds an ephemeral port (tests; the bound port is ``endpoint.port``).

``python -m gelly_streaming_tpu.obs.endpoint --smoke`` is the CI gate:
it populates a registry, scrapes ``/metrics`` + ``/healthz`` over real
HTTP, and diffs the scrape against the registry's own render.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from .export import prometheus_text
from .registry import MetricRegistry, get_registry


def _query_n(query: str) -> Optional[int]:
    """The ``?n=`` tail bound shared by /events and /trace/<id>;
    None (the endpoint default) when absent or non-numeric."""
    n = None
    for part in query.split("&"):
        if part.startswith("n="):
            try:
                n = int(part[2:])
            except ValueError:
                n = None
    return n


class MetricsEndpoint:
    """One scrape endpoint over a registry (default: the process-wide
    one), an optional cluster aggregator, and an optional health
    callable. Start with :meth:`start`, stop with :meth:`close`;
    usable as a context manager."""

    def __init__(
        self,
        registry: Optional[MetricRegistry] = None,
        *,
        aggregator=None,
        health: Optional[Callable[[], dict]] = None,
        events: Optional[Callable[[int], list]] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        events_tail: int = 128,
    ):
        self._registry = registry
        self.aggregator = aggregator
        self._health = health
        self._events = events
        self.host = host
        self._port = int(port)
        self.events_tail = int(events_tail)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._t0 = time.monotonic()

    # ------------------------------------------------------------------ #
    @property
    def registry(self) -> MetricRegistry:
        if self.aggregator is not None:
            return self.aggregator.registry
        return self._registry if self._registry is not None \
            else get_registry()

    @property
    def port(self) -> int:
        return self._port

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self._port}"

    # ------------------------------------------------------------------ #
    # Route payloads (also the testable non-HTTP surface)
    # ------------------------------------------------------------------ #
    def render_metrics(self) -> str:
        if self.aggregator is not None:
            self.aggregator.poll()
        return prometheus_text(self.registry)

    def render_healthz(self) -> dict:
        doc = {"ok": True, "uptime_s": round(time.monotonic() - self._t0, 3)}
        if self.aggregator is not None:
            doc["shards_consumed_events"] = self.aggregator.consumed
        if self._health is not None:
            try:
                extra = self._health() or {}
            except Exception:
                get_registry().counter(
                    "obs.swallowed", site="endpoint_health"
                ).inc()
                extra = {"ok": False, "error": "health callable raised"}
            doc.update(extra)
        return doc

    def render_events(self, n: Optional[int] = None) -> list:
        n = self.events_tail if n is None else max(0, int(n))
        if self._events is not None:
            return list(self._events(n))
        if self.aggregator is not None:
            self.aggregator.poll()
            return self.aggregator.events(last=n)
        return []

    def render_trace(self, trace_id: str,
                     n: Optional[int] = None) -> list:
        """The newest events of ONE trace (``/trace/<id>``): the
        merged event tail filtered to ``trace == trace_id``. Served
        from the aggregator's bounded event window (or the ``events``
        callable's tail), so it is the LIVE tail of a trace, not an
        archival lookup — the full story belongs to
        ``obs.timeline --trace`` over the committed logs."""
        n = self.events_tail if n is None else max(0, int(n))
        if self._events is not None:
            # ask the callable for its whole available tail; the trace
            # filter below does the narrowing
            evs = list(self._events(1 << 20))
        elif self.aggregator is not None:
            self.aggregator.poll()
            evs = self.aggregator.events()
        else:
            return []
        hits = [e for e in evs if e.get("trace") == trace_id]
        return hits[-n:] if n > 0 else []

    # ------------------------------------------------------------------ #
    def start(self) -> "MetricsEndpoint":
        endpoint = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # scrapes are not operator news
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 (http.server API name)
                path, _, query = self.path.partition("?")
                try:
                    if path == "/metrics":
                        self._send(
                            200,
                            endpoint.render_metrics().encode(),
                            "text/plain; version=0.0.4",
                        )
                    elif path == "/healthz":
                        doc = endpoint.render_healthz()
                        self._send(
                            200 if doc.get("ok") else 503,
                            (json.dumps(doc) + "\n").encode(),
                            "application/json",
                        )
                    elif path == "/events":
                        body = "".join(
                            json.dumps(e) + "\n"
                            for e in endpoint.render_events(
                                _query_n(query))
                        ).encode()
                        self._send(200, body, "application/x-ndjson")
                    elif path.startswith("/trace/"):
                        trace_id = path[len("/trace/"):]
                        body = "".join(
                            json.dumps(e) + "\n"
                            for e in endpoint.render_trace(
                                trace_id, _query_n(query))
                        ).encode()
                        self._send(200, body, "application/x-ndjson")
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except BrokenPipeError:
                    pass  # client hung up mid-scrape; its loss
                except Exception:
                    # a scrape must never take the server thread down;
                    # count it and report the failure to the client
                    get_registry().counter(
                        "obs.swallowed", site="endpoint_request"
                    ).inc()
                    try:
                        self._send(
                            500, b"internal error\n", "text/plain"
                        )
                    except OSError:
                        pass  # the connection is already gone

        self._httpd = ThreadingHTTPServer((self.host, self._port), Handler)
        self._httpd.daemon_threads = True
        self._port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="obs-metrics-endpoint",
            daemon=True,
        )
        self._thread.start()
        return self

    def __enter__(self) -> "MetricsEndpoint":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None

    # ------------------------------------------------------------------ #
    @classmethod
    def for_server(cls, server, **kw) -> "MetricsEndpoint":
        """An endpoint wired to a serving replica set: ``/healthz``
        reports worker liveness, pending depth, ingest state, and (for
        a :class:`~gelly_streaming_tpu.serving.failover.FailoverServer`)
        the replica ROLE (``primary``/``standby``), promotion state,
        and heartbeat age — the fields an external probe needs to tell
        a healthy standby takeover from a wedged primary (alive thread,
        stale beat); ``ok`` is False once no replica can answer.
        Accepts a ``StreamServer`` or ``FailoverServer``."""

        def health() -> dict:
            # active_nowait, not active: the locked property waits out
            # an in-flight promote() (up to its in-flight grace), and a
            # health probe stalling mid-failover reads as an outage
            active = getattr(server, "active_nowait", server)
            doc = {
                "worker_alive": bool(active.worker_alive()),
                "ingest_finished": bool(active.ingest_finished()),
                "pending": len(getattr(active, "_pending", ())),
            }
            role = getattr(server, "role", None)
            if role is not None:
                doc["role"] = str(role)
            promoted = getattr(server, "promoted", None)
            if promoted is not None:
                doc["promoted"] = bool(promoted)
            beat = getattr(server, "heartbeat_age_s", None)
            if beat is not None:
                age = beat()
                if age is not None:
                    doc["heartbeat_age_s"] = round(age, 4)
            started = active._worker_thread is not None
            doc["ok"] = bool(active.worker_alive() or not started)
            return doc

        return cls(health=health, **kw)


# --------------------------------------------------------------------- #
# CI smoke: scrape a live endpoint and diff it against the registry
# --------------------------------------------------------------------- #
def smoke(verbose: bool = True) -> bool:
    """Start an endpoint over a seeded registry, scrape ``/metrics`` +
    ``/healthz`` + ``/events`` over real HTTP, and verify the scrape
    equals the registry's own render. Returns True on success (the CI
    step exits nonzero otherwise)."""
    from urllib.request import urlopen

    reg = MetricRegistry()
    reg.counter("smoke.requests", route="a").inc(3)
    reg.counter("smoke.requests", route="b").inc(2)
    reg.gauge("smoke.depth").set(7)
    h = reg.histogram("smoke.latency_seconds")
    for v in (0.01, 0.02, 0.03, 0.5):
        h.observe(v)

    say = print if verbose else (lambda *a, **k: None)
    with MetricsEndpoint(reg) as ep:
        body = urlopen(f"{ep.url}/metrics", timeout=10).read().decode()
        want = prometheus_text(reg)
        if body != want:
            say("SMOKE FAIL: /metrics scrape differs from "
                "prometheus_text(registry):")
            say(f"--- scraped ---\n{body}\n--- rendered ---\n{want}")
            return False
        if "smoke_latency_seconds_count" not in body:
            say("SMOKE FAIL: summary series missing from /metrics")
            return False
        hz = json.loads(
            urlopen(f"{ep.url}/healthz", timeout=10).read().decode()
        )
        if hz.get("ok") is not True or "uptime_s" not in hz:
            say(f"SMOKE FAIL: /healthz unhealthy: {hz}")
            return False
        ev = urlopen(f"{ep.url}/events?n=5", timeout=10).read().decode()
        if ev.strip():
            for line in ev.strip().splitlines():
                json.loads(line)
    say(f"SMOKE OK: /metrics ({len(body.splitlines())} lines) == "
        f"registry render; /healthz ok (uptime {hz['uptime_s']}s)")
    return True


if __name__ == "__main__":
    import sys

    if "--smoke" in sys.argv:
        sys.exit(0 if smoke() else 1)
    # default: serve the process-wide registry until interrupted
    ep = MetricsEndpoint(port=int(sys.argv[1]) if len(sys.argv) > 1 else 0)
    ep.start()
    print(f"serving {ep.url}/metrics (/healthz, /events); Ctrl-C stops")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        ep.close()
