"""Process-wide metric registry: counters, gauges, bounded histograms.

The reference's design stance is that metrics are ordinary output
streams (``README.md:26-32``; ``utils/profiling.py`` docstring); this
registry keeps it. Instruments are plain mutable cells — there is no
metrics server, no pull endpoint, no wire protocol. Everything an
instrument does is observable two ways, both streams:

- :meth:`MetricRegistry.snapshot` returns a plain dict (compose it with
  any emission iterator via :func:`~gelly_streaming_tpu.obs.export.snapshot_stream`);
- every mutation can be mirrored to attached sinks as one event dict
  (:meth:`MetricRegistry.add_sink`), which makes the registry itself
  REPLAYABLE: feeding the event log back through
  :func:`~gelly_streaming_tpu.obs.export.replay` reconstructs an
  identical registry — the property the serving bench's honesty check
  relies on (a reported p99 must be reproducible from its own log).

Thread-safety: instrument creation is serialized by the registry lock;
each instrument carries its own lock so hot-path mutations on different
instruments never contend. Event emission happens INSIDE the instrument
lock, so the event log's order equals the mutation order per instrument
and replay is deterministic (the histogram's bounded-sample eviction is
a pure function of the observation sequence).

:func:`nearest_rank` is THE percentile rule for the repo — the one
previously duplicated between ``StreamProfiler.latency_percentile`` and
``serving/stats._pct`` (ISSUE 3 satellite); both now call here.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

#: default bounded-histogram sample cap (drop-oldest-half on overflow),
#: matching the serving tier's historical ``ServingStats.MAX_SAMPLES``
DEFAULT_MAX_SAMPLES = 1 << 16

#: percentiles rendered into snapshots / Prometheus summaries
SNAPSHOT_QUANTILES = (50.0, 90.0, 95.0, 99.0)


def nearest_rank(sorted_xs: Sequence[float], q: float) -> float:
    """Nearest-rank percentile over an ALREADY-SORTED sample sequence.

    ``q`` in [0, 100]; empty input returns 0.0. This is the single
    shared implementation of the rule both the window profiler and the
    serving stats used to carry privately: index ``round(q/100*(n-1))``,
    clamped to the valid range.
    """
    n = len(sorted_xs)
    if not n:
        return 0.0
    k = min(n - 1, max(0, int(round(q / 100 * (n - 1)))))
    return sorted_xs[k]


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


def format_key(name: str, labels: dict) -> str:
    """Stable string form for snapshot keys: ``name`` or
    ``name{k=v,...}`` with labels sorted."""
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


class _Instrument:
    """Shared shape: name + labels + own lock + emitting registry."""

    __slots__ = ("name", "labels", "_lock", "_registry")
    kind = "instrument"

    def __init__(self, name: str, labels: dict, registry: "MetricRegistry"):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self._registry = registry

    def key(self) -> str:
        return format_key(self.name, self.labels)


class Counter(_Instrument):
    """Monotonically-increasing value (float increments allowed, so a
    counter can accumulate seconds as naturally as event counts)."""

    __slots__ = ("value",)
    kind = "counter"

    def __init__(self, name, labels, registry):
        super().__init__(name, labels, registry)
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n
            self._registry._emit(self, n)


class Gauge(_Instrument):
    """Last-write-wins value (queue depth, pending admissions, ...)."""

    __slots__ = ("value",)
    kind = "gauge"

    def __init__(self, name, labels, registry):
        super().__init__(name, labels, registry)
        self.value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)
            self._registry._emit(self, self.value)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n
            self._registry._emit(self, self.value)


class Histogram(_Instrument):
    """Bounded-sample histogram with exact lifetime count/sum/min/max.

    Samples are capped at ``max_samples``; on overflow the OLDEST HALF
    drops (the historical ``ServingStats`` policy), so percentiles
    describe the recent window while count/sum/min/max stay exact over
    the full lifetime. Eviction is deterministic in the observation
    sequence — replaying the same observations reconstructs the same
    sample list, hence identical percentiles.

    EXEMPLARS (ISSUE 9): ``observe(v, exemplar=trace_id)`` attaches a
    trace id to the observation; the histogram keeps the
    :data:`MAX_EXEMPLARS` LARGEST exemplar-carrying observations, so a
    p99 bucket in a latency histogram links to a concrete trace a human
    can pull up with ``obs.timeline --trace <id>``. Selection is
    deterministic in the observation sequence (stable sort, first-seen
    wins ties), so replay reconstructs identical exemplars.
    """

    #: how many largest exemplar-carrying observations are retained
    MAX_EXEMPLARS = 4

    __slots__ = ("max_samples", "count", "sum", "min", "max", "_samples",
                 "_exemplars")
    kind = "hist"

    def __init__(self, name, labels, registry,
                 max_samples: int = DEFAULT_MAX_SAMPLES):
        super().__init__(name, labels, registry)
        self.max_samples = int(max_samples)
        self.count = 0
        self.sum = 0.0
        self.min = 0.0
        self.max = 0.0
        self._samples: List[float] = []
        self._exemplars: List[Tuple[float, str]] = []

    def observe(self, v: float, exemplar: Optional[str] = None) -> None:
        v = float(v)
        with self._lock:
            if len(self._samples) >= self.max_samples:
                del self._samples[: self.max_samples // 2]
            self._samples.append(v)
            if self.count == 0:
                self.min = self.max = v
            else:
                if v < self.min:
                    self.min = v
                if v > self.max:
                    self.max = v
            self.count += 1
            self.sum += v
            if exemplar is not None:
                ex = self._exemplars
                ex.append((v, str(exemplar)))
                # stable sort, largest first: equal values keep their
                # arrival order, so eviction is a pure function of the
                # observation sequence (the replay-identity contract)
                ex.sort(key=lambda p: -p[0])
                del ex[self.MAX_EXEMPLARS:]
            self._registry._emit(self, v, ex=exemplar)

    def exemplars(self) -> List[Tuple[float, str]]:
        """``(value, trace_id)`` pairs for the largest exemplar-carrying
        observations, largest first (copy, taken under the lock)."""
        with self._lock:
            return list(self._exemplars)

    def samples(self) -> List[float]:
        """Copy of the bounded sample window (taken under the lock)."""
        with self._lock:
            return list(self._samples)

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile over the bounded sample window. The
        sort happens OUTSIDE the lock on a copy — percentile reads must
        never stall a hot-path ``observe`` (the serving tier's tail
        latency must not be injected by the act of measuring it)."""
        xs = self.samples()
        xs.sort()
        return nearest_rank(xs, q)

    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0


class MetricRegistry:
    """Get-or-create instrument store. One process-wide default lives in
    this module (:func:`get_registry`); private registries are cheap and
    used where isolation matters (each ``ServingStats`` owns one so two
    servers never blend their counts)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, tuple], _Instrument] = {}
        self._sinks: list = []

    # -- instrument access --------------------------------------------- #
    def _get(self, cls, name: str, labels: dict, **kw) -> _Instrument:
        key = (name, _label_key(labels))
        m = self._metrics.get(key)
        if m is not None:
            if not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}"
                )
            return m
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = self._metrics[key] = cls(name, labels, self, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}"
                )
            return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str,
                  max_samples: int = DEFAULT_MAX_SAMPLES,
                  **labels) -> Histogram:
        return self._get(Histogram, name, labels, max_samples=max_samples)

    def find(self, name: str) -> List[Tuple[dict, _Instrument]]:
        """All ``(labels, instrument)`` pairs registered under ``name``,
        label-sorted (stable iteration for snapshot/export)."""
        with self._lock:
            hits = [
                (dict(lk), m)
                for (n, lk), m in self._metrics.items()
                if n == name
            ]
        hits.sort(key=lambda p: _label_key(p[0]))
        return hits

    def instruments(self) -> List[_Instrument]:
        with self._lock:
            ms = list(self._metrics.values())
        ms.sort(key=lambda m: (m.name, _label_key(m.labels)))
        return ms

    # -- event mirroring ----------------------------------------------- #
    def add_sink(self, sink) -> None:
        """Mirror every mutation to ``sink.emit(event_dict)``. With no
        sinks attached (the default) mutation cost is the instrument
        lock + one arithmetic op — nothing is allocated per event."""
        with self._lock:
            if sink not in self._sinks:
                self._sinks.append(sink)

    def remove_sink(self, sink) -> None:
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)

    def _emit(self, instrument: _Instrument, value: float,
              ex: Optional[str] = None) -> None:
        if not self._sinks:
            return
        event = {
            "kind": instrument.kind,
            "name": instrument.name,
            "v": value,
        }
        if instrument.labels:
            event["labels"] = instrument.labels
        if (instrument.kind == "hist"
                and instrument.max_samples != DEFAULT_MAX_SAMPLES):
            event["max_samples"] = instrument.max_samples
        if ex is not None:
            # the exemplar trace id rides the event, so replay()
            # reconstructs identical exemplar state from the log
            event["ex"] = ex
        for s in self._sinks:
            s.emit(event)

    # -- read side ------------------------------------------------------ #
    def snapshot(self) -> dict:
        """Plain-dict export of every instrument::

            {"counters": {...}, "gauges": {...},
             "histograms": {key: {"count", "sum", "min", "max", "mean",
                                  "p50", "p90", "p95", "p99"}}}
        """
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for m in self.instruments():
            if isinstance(m, Counter):
                out["counters"][m.key()] = m.value
            elif isinstance(m, Gauge):
                out["gauges"][m.key()] = m.value
            else:
                xs = m.samples()
                xs.sort()
                doc = {
                    "count": m.count,
                    "sum": m.sum,
                    "min": m.min,
                    "max": m.max,
                    "mean": m.mean(),
                    **{
                        f"p{q:g}": nearest_rank(xs, q)
                        for q in SNAPSHOT_QUANTILES
                    },
                }
                exemplars = m.exemplars()
                if exemplars:
                    doc["exemplars"] = [
                        {"v": v, "trace": t} for v, t in exemplars
                    ]
                out["histograms"][m.key()] = doc
        return out

    def stream(self) -> Iterator[dict]:
        """Unbounded snapshot stream (pull-based, like every emission
        iterator in this repo): each ``next()`` yields :meth:`snapshot`."""
        while True:
            yield self.snapshot()


_GLOBAL = MetricRegistry()
_GLOBAL_LOCK = threading.Lock()


def get_registry() -> MetricRegistry:
    """The process-wide registry framework instrumentation writes to."""
    return _GLOBAL


def set_registry(registry: Optional[MetricRegistry]) -> MetricRegistry:
    """Swap the process-wide registry (None installs a fresh one);
    returns the registry now installed. Tests use this to isolate."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        _GLOBAL = registry if registry is not None else MetricRegistry()
        return _GLOBAL
