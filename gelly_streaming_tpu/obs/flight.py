"""Crash flight recorder: a bounded ring of the last N events per worker.

A killed worker's full event log tells the whole story — but only if it
made it to disk, and only if someone goes digging. The flight recorder
is the black box: a small in-memory ring of the most recent events and
spans, dumped ATOMICALLY (checksummed container + temp-and-replace, the
:mod:`~gelly_streaming_tpu.resilience.integrity` commit discipline) at
the moment of death — a supervisor restart, a ``FaultPlan`` kill firing
``os._exit``, a serving worker thread dying — so every failure report
carries the last seconds of telemetry that led up to it.

Wiring mirrors :mod:`~gelly_streaming_tpu.resilience.faults`: construct
a :class:`FlightRecorder` and :func:`install` it; installation attaches
it as a sink on BOTH event sources (tracer + global registry), and the
crash sites (``Supervisor``, ``faults.fire``'s kill path,
``StreamServer``'s worker, ``ClusterSupervisor`` via its workers' dump
files) call :func:`dump_installed` — a no-op costing one module
attribute check when nothing is installed.

ZERO DISABLED OVERHEAD is contractual (graftlint GL005 covers this
module): the recorder is attached as an always-on sink — resilience
counters fire with obs disabled — so the RING WRITE ITSELF gates on
``obs.enable()``. Disabled runs pay one flag check per event and
allocate nothing.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import List, Optional

from . import trace as _trace

#: default ring capacity — small on purpose: the black box holds the
#: last seconds before death, not the flight
DEFAULT_CAPACITY = 256


class FlightRecorder:
    """Bounded event ring + atomic crash dumps.

    ``path`` is the dump base name: the first :meth:`dump` commits
    there, later dumps (one per restart, say) commit to ``path.2``,
    ``path.3``, ... so no black box overwrites an earlier one.
    ``shard`` tags dumps (and ring events at dump time) with the
    worker's shard id for cluster-level collection.
    """

    def __init__(
        self,
        path: Optional[str] = None,
        *,
        capacity: int = DEFAULT_CAPACITY,
        shard: Optional[int] = None,
    ):
        self.path = path
        self.capacity = int(capacity)
        self.shard = None if shard is None else int(shard)
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._dumps = 0

    # -- sink side ------------------------------------------------------ #
    def emit(self, event: dict) -> None:
        """Record one event. Gated on ``obs.enable()`` — the recorder
        rides the always-on sink path, so this check IS the disabled-
        mode zero-cost bound (see module doc / GL005)."""
        if _trace.on():
            with self._lock:
                self._ring.append(event)

    def note(self, name: str, **attrs) -> None:
        """Record a marker event directly (bypasses the registry; still
        gated — markers are telemetry, not operational state)."""
        if _trace.on():
            e = {"kind": "note", "name": name, "ts": time.time()}
            if attrs:
                e["attrs"] = attrs
            with self._lock:
                self._ring.append(e)

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- crash side ----------------------------------------------------- #
    def dump(self, reason: str, path: Optional[str] = None,
             **extra) -> Optional[str]:
        """Atomically commit the ring as a checksummed dump file;
        returns the path (None when no path is configured). Safe at the
        worst moment: the write is temp-and-replace in the target
        directory, the payload is CRC-framed, and any failure to commit
        is swallowed WITH a registry count — a dying worker must never
        die twice in its own post-mortem."""
        from ..resilience import integrity as _integrity

        with self._lock:
            events = list(self._ring)
            self._dumps += 1
            n = self._dumps
        out = path or self.path
        if out is None:
            return None
        if n > 1:
            out = f"{out}.{n}"
        doc = {
            "kind": "flight",
            "reason": reason,
            "ts": time.time(),
            "pid": os.getpid(),
            "shard": self.shard,
            "n_events": len(events),
            "events": events,
        }
        if extra:
            doc["attrs"] = extra
        try:
            data = _integrity.wrap_checksummed(
                json.dumps(doc).encode("utf-8")
            )
            d = os.path.dirname(out)
            if d:
                os.makedirs(d, exist_ok=True)
            tmp = out + ".tmp"
            with open(tmp, "wb") as f:
                f.write(data)
            _integrity.replace_atomic(tmp, out)
        except Exception:
            from .registry import get_registry

            # crash-path best effort: the death being recorded matters
            # more than the recording; count the loss so it is visible
            get_registry().counter(
                "obs.swallowed", site="flight_dump"
            ).inc()
            return None
        return out


def read_dump(path: str) -> dict:
    """Load and validate one dump file (checksummed container). The
    container self-describes and the reader holds it to that (GL011
    symmetry with :meth:`FlightRecorder.dump`): ``kind`` must be
    ``"flight"`` and ``n_events`` must match the shipped ring — a
    CRC-valid file that is not a flight dump is rejected rather than
    mis-parsed into an empty black box."""
    from ..resilience import integrity as _integrity

    with open(path, "rb") as f:
        data = f.read()
    doc = json.loads(
        _integrity.unwrap_checksummed(data, origin=f"flight dump {path}")
    )
    if doc.get("kind") != "flight":
        raise ValueError(
            f"{path}: not a flight dump (kind={doc.get('kind')!r})")
    events = doc.get("events")
    if not isinstance(events, list) or doc.get("n_events") != len(events):
        raise ValueError(
            f"{path}: inconsistent flight dump "
            f"(n_events does not match the shipped ring)")
    return doc


def find_dumps(directory: str) -> List[str]:
    """Every flight dump under ``directory`` (non-recursive), oldest
    first — the collection pass ``ClusterSupervisor`` runs over its
    workers' black boxes."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    hits = [
        os.path.join(directory, n)
        for n in names
        if "flight" in n and not n.endswith(".tmp")
        and os.path.isfile(os.path.join(directory, n))
    ]
    hits.sort(key=lambda p: (os.path.getmtime(p), p))
    return hits


# --------------------------------------------------------------------- #
# Global installation (one cheap check at the crash sites)
# --------------------------------------------------------------------- #
_RECORDER: Optional[FlightRecorder] = None
_LOCK = threading.Lock()


def installed() -> Optional[FlightRecorder]:
    return _RECORDER


def install(recorder: Optional[FlightRecorder]) -> Optional[FlightRecorder]:
    """Install ``recorder`` as THE process flight recorder and attach it
    to both event sources (tracer + global registry); returns it. A
    previously installed recorder is detached first. ``None``
    uninstalls."""
    global _RECORDER
    from .registry import get_registry

    with _LOCK:
        if _RECORDER is not None:
            _trace.remove_sink(_RECORDER)
            get_registry().remove_sink(_RECORDER)
        _RECORDER = recorder
        if recorder is not None:
            _trace.add_sink(recorder)
            get_registry().add_sink(recorder)
        return recorder


def uninstall() -> None:
    install(None)


def dump_installed(reason: str, path: Optional[str] = None,
                   **extra) -> Optional[str]:
    """Dump the installed recorder (no-op when none is installed) —
    the one-liner every crash site calls."""
    rec = _RECORDER
    if rec is None:
        return None
    return rec.dump(reason, path=path, **extra)
