"""Lightweight structured spans over the pipeline's host-side stages.

``span("pack")`` wraps a stage; nested spans form a tree via a
thread-local stack (each pipeline thread — windower, prefetch producer,
serving worker — gets its own lineage). A finished span becomes ONE
event dict pushed to the attached sinks and one observation in the
global registry's ``trace.span_seconds{span=...}`` histogram, so span
timing shows up in the same snapshot/Prometheus surface as every other
metric. Optionally (``enable(jax_annotations=True)``) each span also
opens a ``jax.profiler.TraceAnnotation`` so host stages line up against
device ops in TensorBoard traces.

CROSS-PROCESS TRACES (ISSUE 9): a :class:`TraceContext` carries a trace
id + a parent span id across threads, futures, and the RPC wire. The
query client mints one per batch (``TraceContext()``), injects it into
the frame body (:meth:`TraceContext.to_wire`), and the serving path
extracts it (:meth:`TraceContext.from_wire`) and stamps every stage
span with the trace id — so one query's causal path across client,
primary, and promoted standby joins on ``trace`` in the merged shard
event stream. Propagation is EXPLICIT where threads change hands: the
context is thread-local only for same-thread nesting
(:func:`activate`); code that hops threads (future callbacks, the
serving worker's drained entries) carries the context object itself and
emits via :func:`record_span`, which synthesizes a finished-span event
without touching any thread's span stack. Span ids are process-local
(the merged stream disambiguates by ``shard``); the trace id is the one
globally meaningful join key.

DISABLED COST IS THE DESIGN CONSTRAINT: instrumentation is threaded
through per-window hot paths (``core/window.py`` pack,
``aggregate/summary.py`` dispatch, ``core/pipeline.py`` prefetch), so
``span()`` with tracing off must be near-free. The disabled path is one
attribute check and returns a SHARED no-op singleton — no object, no
dict, no clock read is allocated or taken (the zero-allocation property
``tests/test_obs.py`` pins). Hot sites that would pay even for building
an attrs dict guard on :func:`on` first.

Timing semantics: spans measure HOST wall time between ``__enter__`` and
``__exit__``. Around an async device dispatch that is enqueue time, not
compute time — the same contract as ``SummaryAggregation.sync()``
documents for throughput measurement.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Optional


class _Config:
    __slots__ = ("enabled", "annotate_jax", "registry_spans")

    def __init__(self):
        self.enabled = False
        self.annotate_jax = False
        self.registry_spans = True


_CFG = _Config()
_SINKS: list = []
_LOCAL = threading.local()
_IDS = itertools.count(1)


def on() -> bool:
    """True when tracing is enabled (the hot-path guard)."""
    return _CFG.enabled


enabled = on  # alias; both read naturally at call sites


def enable(*, jax_annotations: bool = False,
           registry_spans: bool = True) -> None:
    """Turn span recording on.

    ``jax_annotations`` additionally opens a
    ``jax.profiler.TraceAnnotation`` per span (device-trace alignment;
    requires jax, imported lazily). ``registry_spans`` mirrors span
    durations into the global registry's ``trace.span_seconds``
    histogram (on by default — it is what makes span timing visible to
    the Prometheus/snapshot exporters).
    """
    _CFG.annotate_jax = bool(jax_annotations)
    _CFG.registry_spans = bool(registry_spans)
    _CFG.enabled = True


def disable() -> None:
    _CFG.enabled = False
    _CFG.annotate_jax = False


def add_sink(sink) -> None:
    """Attach a span-event sink (``sink.emit(event_dict)``)."""
    if sink not in _SINKS:
        _SINKS.append(sink)


def remove_sink(sink) -> None:
    if sink in _SINKS:
        _SINKS.remove(sink)


def sinks() -> list:
    return list(_SINKS)


# --------------------------------------------------------------------- #
# Trace context (cross-thread / cross-process propagation)
# --------------------------------------------------------------------- #
def new_trace_id() -> str:
    """A fresh 16-hex-char trace id (random, so ids minted by many
    client processes never collide — the property the merged cluster
    stream depends on; span SIDS stay per-process counters)."""
    return os.urandom(8).hex()


def next_sid() -> int:
    """Reserve one span id from the process counter — for call sites
    that must name a span's id BEFORE the span's event is emitted (the
    RPC client advertises its batch-root sid on the wire so server-side
    spans can parent to it)."""
    return next(_IDS)


class TraceContext:
    """One query batch's identity across threads and processes.

    ``trace_id`` is the global join key (minted once, client-side);
    ``parent_sid`` is the span id server/child spans parent to —
    typically the minting side's root span, whose id is reserved with
    :func:`next_sid` so it can travel before the root span finishes.

    The context is a plain carryable object: store it on a batch, a
    future, or a pending-queue entry and every hop keeps the trace —
    that explicit handoff is the design (thread-locals silently drop
    context at thread boundaries; queues and executors cross them
    constantly in the serving tier).
    """

    __slots__ = ("trace_id", "parent_sid")

    def __init__(self, trace_id: Optional[str] = None,
                 parent_sid: Optional[int] = None):
        self.trace_id = trace_id if trace_id else new_trace_id()
        self.parent_sid = parent_sid

    def __repr__(self) -> str:
        return f"TraceContext({self.trace_id!r}, {self.parent_sid!r})"

    # -- wire codec ---------------------------------------------------- #
    def to_wire(self) -> dict:
        """The compact frame-body form (``{"t": ..., "s": ...}``)."""
        doc = {"t": self.trace_id}
        if self.parent_sid is not None:
            doc["s"] = int(self.parent_sid)
        return doc

    @classmethod
    def from_wire(cls, doc) -> Optional["TraceContext"]:
        """Rebuild a context from a frame body. TOLERANT by contract:
        a missing/garbage ``tc`` field is an untraced batch, never a
        request error — tracing must not change the wire's accept set."""
        if not isinstance(doc, dict):
            return None
        tid = doc.get("t")
        if not isinstance(tid, str) or not tid:
            return None
        sid = doc.get("s")
        return cls(tid, int(sid) if isinstance(sid, int) else None)


def current_context() -> Optional[TraceContext]:
    """The context active on THIS thread (None outside any activation)."""
    return getattr(_LOCAL, "ctx", None)


class _Activation:
    """``with activate(ctx):`` — scoped thread-local context install."""

    __slots__ = ("ctx", "prev")

    def __init__(self, ctx: Optional[TraceContext]):
        self.ctx = ctx
        self.prev = None

    def __enter__(self) -> Optional[TraceContext]:
        self.prev = getattr(_LOCAL, "ctx", None)
        _LOCAL.ctx = self.ctx
        return self.ctx

    def __exit__(self, *exc) -> bool:
        _LOCAL.ctx = self.prev
        return False


def activate(ctx: Optional[TraceContext]) -> _Activation:
    """Install ``ctx`` as this thread's current context for the block:
    spans opened inside are stamped with its trace id (and root spans
    parent to its ``parent_sid``). This is the explicit cross-thread
    handoff — a worker thread activates the context it was HANDED, it
    never inherits one implicitly."""
    return _Activation(ctx)


def record_span(
    name: str,
    dur_s: float,
    *,
    trace_id: Optional[str] = None,
    parent: Optional[int] = None,
    sid: Optional[int] = None,
    attrs: Optional[dict] = None,
    ts: Optional[float] = None,
) -> Optional[int]:
    """Emit one already-finished span event without entering the
    thread's span stack — the async/cross-thread form of ``span()``
    (future callbacks and drained-queue settles know their duration
    only after the fact, on a thread that never opened the span).

    Returns the span's sid (pass ``sid=`` to emit under a pre-reserved
    id from :func:`next_sid`), or None when tracing is disabled — the
    disabled path is one flag check, nothing allocated."""
    if not _CFG.enabled:
        return None
    span_id = next(_IDS) if sid is None else int(sid)
    event = {
        "kind": "span",
        "name": name,
        "ts": time.time() if ts is None else ts,
        "dur_s": float(dur_s),
        "sid": span_id,
        "depth": 0,
    }
    if trace_id:
        event["trace"] = trace_id
    if parent is not None:
        event["parent"] = parent
    if attrs:
        event["attrs"] = attrs
    for s in _SINKS:
        s.emit(event)
    if _CFG.registry_spans:
        from .registry import get_registry

        get_registry().histogram(
            "trace.span_seconds", span=name
        ).observe(float(dur_s))
    return span_id


class _NoopSpan:
    """The disabled-mode singleton: every method is a no-op, entering
    returns the singleton itself. ``recording`` lets call sites skip
    building expensive attributes."""

    __slots__ = ()
    recording = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


NOOP_SPAN = _NoopSpan()


class Span:
    """One recorded stage. Use via ``with span("pack", {...}):``."""

    __slots__ = ("name", "attrs", "sid", "parent", "depth", "t0",
                 "dur_s", "_ann", "ctx")
    recording = True

    def __init__(self, name: str, attrs: Optional[dict] = None):
        self.name = name
        self.attrs = attrs
        self.sid = 0
        self.parent = None
        self.depth = 0
        self.t0 = 0.0
        self.dur_s = 0.0
        self._ann = None
        self.ctx = None

    def set(self, **attrs) -> "Span":
        """Attach attributes after entry (lets call sites add values
        computed inside the span without paying for them when tracing
        is off — guard on ``.recording``)."""
        if self.attrs is None:
            self.attrs = {}
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        stack = getattr(_LOCAL, "stack", None)
        if stack is None:
            stack = _LOCAL.stack = []
        self.sid = next(_IDS)
        self.depth = len(stack)
        self.ctx = getattr(_LOCAL, "ctx", None)
        if stack:
            self.parent = stack[-1].sid
        elif self.ctx is not None:
            # a root span under an activated context parents to the
            # context's (possibly remote) span id — the cross-process
            # link the timeline joins on
            self.parent = self.ctx.parent_sid
        else:
            self.parent = None
        stack.append(self)
        if _CFG.annotate_jax:
            try:
                import jax

                self._ann = jax.profiler.TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        self.dur_s = time.perf_counter() - self.t0
        if self._ann is not None:
            try:
                self._ann.__exit__(*exc)
            # graftlint: disable=GL003 (span teardown must never raise, and the obs layer cannot count into the registry it feeds — a sink mirroring events back through a span would recurse)
            except Exception:
                pass
        stack = getattr(_LOCAL, "stack", None)
        if stack and stack[-1] is self:
            stack.pop()
        elif stack and self in stack:  # mis-nested exit: drop through it
            del stack[stack.index(self):]
        event = {
            "kind": "span",
            "name": self.name,
            "ts": time.time(),
            "dur_s": self.dur_s,
            "sid": self.sid,
            "depth": self.depth,
        }
        if self.parent is not None:
            event["parent"] = self.parent
        if self.ctx is not None:
            event["trace"] = self.ctx.trace_id
        if self.attrs:
            event["attrs"] = self.attrs
        for s in _SINKS:
            s.emit(event)
        if _CFG.registry_spans:
            from .registry import get_registry

            get_registry().histogram(
                "trace.span_seconds", span=self.name
            ).observe(self.dur_s)
        return False


def span(name: str, attrs: Optional[dict] = None):
    """A context manager timing one named stage (no-op when disabled).

    ``attrs`` is an optional plain dict of span attributes (window
    index, superbatch K, block edges, ...). Truly hot call sites guard
    with :func:`on` before building the dict; everywhere else the dict
    literal's cost is negligible next to the stage it measures.
    """
    if not _CFG.enabled:
        return NOOP_SPAN
    return Span(name, attrs)


def current_span() -> Optional[Span]:
    """The innermost open span on this thread (None outside any span)."""
    stack = getattr(_LOCAL, "stack", None)
    return stack[-1] if stack else None
