"""Lightweight structured spans over the pipeline's host-side stages.

``span("pack")`` wraps a stage; nested spans form a tree via a
thread-local stack (each pipeline thread — windower, prefetch producer,
serving worker — gets its own lineage). A finished span becomes ONE
event dict pushed to the attached sinks and one observation in the
global registry's ``trace.span_seconds{span=...}`` histogram, so span
timing shows up in the same snapshot/Prometheus surface as every other
metric. Optionally (``enable(jax_annotations=True)``) each span also
opens a ``jax.profiler.TraceAnnotation`` so host stages line up against
device ops in TensorBoard traces.

DISABLED COST IS THE DESIGN CONSTRAINT: instrumentation is threaded
through per-window hot paths (``core/window.py`` pack,
``aggregate/summary.py`` dispatch, ``core/pipeline.py`` prefetch), so
``span()`` with tracing off must be near-free. The disabled path is one
attribute check and returns a SHARED no-op singleton — no object, no
dict, no clock read is allocated or taken (the zero-allocation property
``tests/test_obs.py`` pins). Hot sites that would pay even for building
an attrs dict guard on :func:`on` first.

Timing semantics: spans measure HOST wall time between ``__enter__`` and
``__exit__``. Around an async device dispatch that is enqueue time, not
compute time — the same contract as ``SummaryAggregation.sync()``
documents for throughput measurement.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Optional


class _Config:
    __slots__ = ("enabled", "annotate_jax", "registry_spans")

    def __init__(self):
        self.enabled = False
        self.annotate_jax = False
        self.registry_spans = True


_CFG = _Config()
_SINKS: list = []
_LOCAL = threading.local()
_IDS = itertools.count(1)


def on() -> bool:
    """True when tracing is enabled (the hot-path guard)."""
    return _CFG.enabled


enabled = on  # alias; both read naturally at call sites


def enable(*, jax_annotations: bool = False,
           registry_spans: bool = True) -> None:
    """Turn span recording on.

    ``jax_annotations`` additionally opens a
    ``jax.profiler.TraceAnnotation`` per span (device-trace alignment;
    requires jax, imported lazily). ``registry_spans`` mirrors span
    durations into the global registry's ``trace.span_seconds``
    histogram (on by default — it is what makes span timing visible to
    the Prometheus/snapshot exporters).
    """
    _CFG.annotate_jax = bool(jax_annotations)
    _CFG.registry_spans = bool(registry_spans)
    _CFG.enabled = True


def disable() -> None:
    _CFG.enabled = False
    _CFG.annotate_jax = False


def add_sink(sink) -> None:
    """Attach a span-event sink (``sink.emit(event_dict)``)."""
    if sink not in _SINKS:
        _SINKS.append(sink)


def remove_sink(sink) -> None:
    if sink in _SINKS:
        _SINKS.remove(sink)


def sinks() -> list:
    return list(_SINKS)


class _NoopSpan:
    """The disabled-mode singleton: every method is a no-op, entering
    returns the singleton itself. ``recording`` lets call sites skip
    building expensive attributes."""

    __slots__ = ()
    recording = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


NOOP_SPAN = _NoopSpan()


class Span:
    """One recorded stage. Use via ``with span("pack", {...}):``."""

    __slots__ = ("name", "attrs", "sid", "parent", "depth", "t0",
                 "dur_s", "_ann")
    recording = True

    def __init__(self, name: str, attrs: Optional[dict] = None):
        self.name = name
        self.attrs = attrs
        self.sid = 0
        self.parent = None
        self.depth = 0
        self.t0 = 0.0
        self.dur_s = 0.0
        self._ann = None

    def set(self, **attrs) -> "Span":
        """Attach attributes after entry (lets call sites add values
        computed inside the span without paying for them when tracing
        is off — guard on ``.recording``)."""
        if self.attrs is None:
            self.attrs = {}
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        stack = getattr(_LOCAL, "stack", None)
        if stack is None:
            stack = _LOCAL.stack = []
        self.sid = next(_IDS)
        self.depth = len(stack)
        self.parent = stack[-1].sid if stack else None
        stack.append(self)
        if _CFG.annotate_jax:
            try:
                import jax

                self._ann = jax.profiler.TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        self.dur_s = time.perf_counter() - self.t0
        if self._ann is not None:
            try:
                self._ann.__exit__(*exc)
            # graftlint: disable=GL003 (span teardown must never raise, and the obs layer cannot count into the registry it feeds — a sink mirroring events back through a span would recurse)
            except Exception:
                pass
        stack = getattr(_LOCAL, "stack", None)
        if stack and stack[-1] is self:
            stack.pop()
        elif stack and self in stack:  # mis-nested exit: drop through it
            del stack[stack.index(self):]
        event = {
            "kind": "span",
            "name": self.name,
            "ts": time.time(),
            "dur_s": self.dur_s,
            "sid": self.sid,
            "depth": self.depth,
        }
        if self.parent is not None:
            event["parent"] = self.parent
        if self.attrs:
            event["attrs"] = self.attrs
        for s in _SINKS:
            s.emit(event)
        if _CFG.registry_spans:
            from .registry import get_registry

            get_registry().histogram(
                "trace.span_seconds", span=self.name
            ).observe(self.dur_s)
        return False


def span(name: str, attrs: Optional[dict] = None):
    """A context manager timing one named stage (no-op when disabled).

    ``attrs`` is an optional plain dict of span attributes (window
    index, superbatch K, block edges, ...). Truly hot call sites guard
    with :func:`on` before building the dict; everywhere else the dict
    literal's cost is negligible next to the stage it measures.
    """
    if not _CFG.enabled:
        return NOOP_SPAN
    return Span(name, attrs)


def current_span() -> Optional[Span]:
    """The innermost open span on this thread (None outside any span)."""
    stack = getattr(_LOCAL, "stack", None)
    return stack[-1] if stack else None
