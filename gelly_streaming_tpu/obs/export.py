"""Exporters that keep metrics as streams (the reference's stance).

Three surfaces, all derived from the same event/registry state:

- :class:`JsonlSink` + :func:`read_jsonl` — an append-only JSONL event
  log. Attached to a registry (and/or the tracer) it records every
  metric mutation and finished span; :func:`replay` feeds the metric
  events back through a fresh :class:`~gelly_streaming_tpu.obs.registry.MetricRegistry`
  and reconstructs IDENTICAL state (bounded-histogram eviction is
  deterministic in the observation sequence), which is how bench
  artifacts prove their reported stats match their own logs.
- :func:`prometheus_text` — the standard text exposition format
  (counters, gauges, histogram summaries with nearest-rank quantiles),
  for anyone pointing a scraper at a file or a debug endpoint. It is a
  RENDERER only; no server ships here.
- :func:`snapshot_stream` — composes a periodic registry snapshot onto
  any emission iterator: yields ``(item, snapshot_or_None)`` pairs with
  a snapshot every ``every`` items, so a metrics stream rides along any
  per-window result stream exactly like the profiler's ``profiled()``.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Iterable, Iterator, List, Optional, Tuple

from .registry import (
    DEFAULT_MAX_SAMPLES,
    MetricRegistry,
    SNAPSHOT_QUANTILES,
)


class JsonlSink:
    """In-memory event buffer with a JSONL writer.

    ``emit`` is what registries/tracers call per event: one lock + one
    list append — cheap enough to leave attached during measured runs
    (the overhead guard in ``tests/test_obs.py`` covers it). ``write``
    flushes the buffer to ``path`` (one JSON object per line).
    """

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._lock = threading.Lock()
        self._events: List[dict] = []

    def emit(self, event: dict) -> None:
        with self._lock:
            self._events.append(event)

    @property
    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def write(self, path: Optional[str] = None) -> str:
        """Flush buffered events to ``path`` (or the constructor path)."""
        out = path or self.path
        if out is None:
            raise ValueError("JsonlSink has no path; pass one to write()")
        events = self.events
        with open(out, "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
        return out


def write_jsonl(events: Iterable[dict], path: str) -> str:
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    return path


def read_jsonl(path: str) -> List[dict]:
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                events.append(json.loads(line))
    return events


def replay(events: Iterable[dict],
           registry: Optional[MetricRegistry] = None) -> MetricRegistry:
    """Apply a metric event log to a (fresh by default) registry.

    Counter events re-increment, gauge events re-set, histogram events
    re-observe — in log order, so the bounded sample window (and hence
    every percentile) comes out identical to the live registry the log
    was recorded from. Span events and unknown kinds are skipped (they
    are evidence, not state).
    """
    reg = registry if registry is not None else MetricRegistry()
    for e in events:
        kind = e.get("kind")
        labels = e.get("labels") or {}
        if kind == "counter":
            reg.counter(e["name"], **labels).inc(e["v"])
        elif kind == "gauge":
            reg.gauge(e["name"], **labels).set(e["v"])
        elif kind == "hist":
            reg.histogram(
                e["name"],
                max_samples=e.get("max_samples", DEFAULT_MAX_SAMPLES),
                **labels,
            ).observe(e["v"], exemplar=e.get("ex"))
        # spans / meta: evidence only, not registry state
    return reg


# --------------------------------------------------------------------- #
# Prometheus text exposition
# --------------------------------------------------------------------- #
def _prom_name(name: str) -> str:
    out = []
    for ch in name:
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    s = "".join(out)
    if s and s[0].isdigit():
        s = "_" + s
    return s


def _prom_labels(labels: dict, extra: Optional[dict] = None) -> str:
    items = dict(labels)
    if extra:
        items.update(extra)
    if not items:
        return ""
    inner = ",".join(
        f'{_prom_name(str(k))}="{v}"' for k, v in sorted(items.items())
    )
    return "{" + inner + "}"


def prometheus_text(registry: Optional[MetricRegistry] = None) -> str:
    """Render a registry in the Prometheus text exposition format.

    Counters/gauges map directly; histograms render as summaries —
    nearest-rank quantiles over the bounded sample window plus exact
    lifetime ``_sum``/``_count`` — because the bounded-sample design
    has true quantiles, not pre-binned buckets.
    """
    from .registry import get_registry, nearest_rank

    reg = registry if registry is not None else get_registry()
    lines: List[str] = []
    typed: set = set()
    for m in reg.instruments():
        pname = _prom_name(m.name)
        if m.kind == "counter":
            if pname not in typed:
                lines.append(f"# TYPE {pname} counter")
                typed.add(pname)
            lines.append(f"{pname}{_prom_labels(m.labels)} {m.value:g}")
        elif m.kind == "gauge":
            if pname not in typed:
                lines.append(f"# TYPE {pname} gauge")
                typed.add(pname)
            lines.append(f"{pname}{_prom_labels(m.labels)} {m.value:g}")
        else:
            if pname not in typed:
                lines.append(f"# TYPE {pname} summary")
                typed.add(pname)
            xs = m.samples()
            xs.sort()
            for q in SNAPSHOT_QUANTILES:
                ql = _prom_labels(m.labels, {"quantile": f"{q / 100:g}"})
                lines.append(f"{pname}{ql} {nearest_rank(xs, q):g}")
            lines.append(f"{pname}_sum{_prom_labels(m.labels)} {m.sum:g}")
            lines.append(
                f"{pname}_count{_prom_labels(m.labels)} {m.count}"
            )
    return "\n".join(lines) + ("\n" if lines else "")


# --------------------------------------------------------------------- #
# Periodic snapshots as a stream
# --------------------------------------------------------------------- #
def snapshot_stream(
    iterator: Iterable[Any],
    every: int = 1,
    registry: Optional[MetricRegistry] = None,
) -> Iterator[Tuple[Any, Optional[dict]]]:
    """Yield ``(item, snapshot|None)`` per upstream item, with a registry
    snapshot attached to every ``every``-th item. Each item is forwarded
    the moment it arrives (no buffering — a live stream stays live);
    callers that need end-of-stream metrics take one more
    ``registry.snapshot()`` after the loop. Composable with any emission
    iterator — the metrics ride the stream they measure::

        for comps, metrics in snapshot_stream(agg.run(stream), every=8):
            ...
    """
    from .registry import get_registry

    if every < 1:
        raise ValueError(f"every must be >= 1, got {every}")
    reg = registry if registry is not None else get_registry()
    for i, item in enumerate(iter(iterator), 1):
        yield item, (reg.snapshot() if i % every == 0 else None)
