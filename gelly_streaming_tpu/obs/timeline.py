"""Causal timeline: merge shard event logs into one ordered story.

A distributed chaos run leaves N per-worker event files plus flight
dumps in a directory; reconstructing "what actually happened" — who was
killed when, which epochs committed, where the rendezvous fell back,
when the standby was promoted — has so far meant hand-interleaving
JSONL files. This tool does the interleave::

    python -m gelly_streaming_tpu.obs.timeline <dir>        # the story
    python -m gelly_streaming_tpu.obs.timeline <dir> --all  # every event

It merges every shard event stream under the directory (via
:func:`~gelly_streaming_tpu.obs.cluster.iter_shard_events` — shard-
stamped, ``ts``-ordered) plus any flight-recorder dumps, and renders
one line per event of interest with a run-relative timestamp::

    +0.412s  [kill_003/p1] KILL     resilience.fault_injected{site=chaos.window}
    +0.907s  [kill_003/p0] COMMIT   resilience.coord_commits
    ...

The default view filters to the COORDINATION story (kills, restarts,
epoch commits / selections / fallbacks / torn epochs, checkpoint
rejections, promotions, worker deaths, flight dumps); ``--all`` renders
every event including spans and plain metric mutations.

``--trace <id>`` (ISSUE 9) renders ONE query's causal path instead:
every event stamped with that trace id — the client's batch root +
retry/resubmit spans and each replica's decode/admit/dispatch/reply
spans, across processes, in one ``ts``-ordered story. Trace ids come
from latency-histogram exemplars, the ``/trace/<id>`` endpoint, or any
span line in ``--all`` output.

``--since <ts>`` / ``--until <ts>`` window the merged stream before
rendering (the chaos OBS logs run to thousands of events; a kill
point's neighborhood should not need grep). Values are absolute unix
timestamps, or run-relative seconds when prefixed with ``+``
(``--since +12 --until +14`` shows the two seconds after +12s, in the
same clock the rendered ``+...s`` column uses).
"""

from __future__ import annotations

import os
import sys
from typing import Iterable, List, Optional

from .cluster import iter_shard_events

#: event name -> the tag the story renders it under; this is the
#: vocabulary of the repo's coordination/failure events (resilience +
#: serving layers — all always-on, so every run has them)
STORY = {
    "resilience.fault_injected": "KILL",
    "resilience.restarts": "RESTART",
    "resilience.cluster_restarts": "RESTART*",
    "resilience.recovery_seconds": "RECOVERED",
    "resilience.coord_commits": "COMMIT",
    "resilience.epoch_selected": "SELECT",
    "resilience.epoch_fallbacks": "FALLBACK",
    "resilience.epoch_torn": "TORN",
    "resilience.epoch_incomplete": "INCOMPLETE",
    "resilience.ckpt_rejected": "REJECTED",
    "resilience.deduped_windows": "DEDUP",
    "resilience.poison_windows": "POISON",
    "serving.failover": "PROMOTE",
    "serving.failover_requeued": "REQUEUE",
    "serving.failover_expired": "EXPIRED",
    "serving.worker_deaths": "DEATH",
    "serving.promotion_seconds": "PROMOTED",
    # the RPC story (PR 8): connection lifecycle + heartbeat-lease
    # failover, so a cross-process serving kill renders as one causal
    # line sequence — CONNECT, DISCONNECT (the kill), LEASE-LAPSE,
    # PROMOTE/PROMOTED — alongside the black-box and death lines above
    "rpc.connects": "CONNECT",
    "rpc.disconnects": "DISCONNECT",
    "rpc.malformed": "MALFORMED",
    "serving.lease_lapse": "LEASE-LAPSE",
    # the sharded-ingest story (ISSUE 11): reconnects, malformed wire
    # frames, and the backpressure lifecycle — a reader blocked past
    # the stall threshold on a full shard queue (recv stopped, TCP
    # pushing back on the producer) and its later resume render as an
    # INGEST-STALL / INGEST-RESUME pair alongside the rest
    "source.reconnects": "RECONNECT",
    "source.malformed_frames": "MALFORMED",
    "source.backpressure_stalls": "INGEST-STALL",
    "source.backpressure_resumes": "INGEST-RESUME",
    # the sharded-serving story (ISSUE 12): the router's cross-shard
    # merge refreshes (one per shard snapshot-version bump, not per
    # query), merge failures, per-shard fan-out errors (the signal a
    # single shard's outage leaves while the other shards keep
    # answering), and hot-key cache invalidations — so a shard kill
    # under a router renders as DISCONNECT / SHARD-ERROR / LEASE-LAPSE
    # / PROMOTE with the router's own lines interleaved
    "router.pulls": "CC-PULL",
    "router.pull_errors": "PULL-ERROR",
    "router.shard_errors": "SHARD-ERROR",
    "router.cache_invalidations": "CACHE-INVAL",
    # the delta-pull story (ISSUE 17): pull protocol v2 — each
    # incremental refresh (DELTA-PULL, O(changed rows) over the wire),
    # each honest degrade to a full table (FULL-FALLBACK{reason}: stale
    # ring, restarted store, v1 peer), and each malformed pull frame
    # the router rejected — so a churn run renders the protocol's
    # actual full/delta cadence next to the CC-PULL lines above
    "router.delta_pulls": "DELTA-PULL",
    "router.full_fallbacks": "FULL-FALLBACK",
    "router.pull_malformed": "PULL-MALFORMED",
    # the self-tuning story (ISSUE 15): every control-plane decision —
    # superbatch K, prefetch depth, admission limit — logs one
    # control.retune{knob,from,to,signal} event, so a knob move renders
    # in causal order next to the COMMIT/PROMOTE lines it reacted to
    "control.retune": "RETUNE",
    # the transport-fabric story (ISSUE 16): every cross-process
    # exchange (fabric.exchange{backend,tag}), every election proposal
    # (fabric.elect{backend,tag,won}) and every cadence agreement the
    # coordinated layer acts on (fabric.agree{backend,epoch,k}) renders
    # labeled with its backend + tag, in causal order next to the
    # COMMIT/SELECT/RETUNE lines it synchronizes
    "fabric.exchange": "EXCHANGE",
    "fabric.elect": "ELECT",
    "fabric.agree": "AGREE",
    # the event-time story (ISSUE 18): the merged watermark's advances,
    # each pane the clock closed, each retraction of an expired pane
    # out of the live summaries, and every record dropped past the
    # lateness allowance — so a sliding-window chaos run renders as
    # WATERMARK / PANE-CLOSE / KILL / RESTART / PANE-CLOSE (the replay)
    # / RETRACT in causal order, late drops counted, never silent
    # the elastic-resharding story (ISSUE 19): the split plan's
    # one-winner agreement (AGREE-SPLIT), the parent shard observing a
    # plan that names it (SPLIT), and every epoch adoption — routers
    # growing a shard client, replicas re-stamping their reply frames
    # (ADOPT) — so a storm run renders KILL / PROMOTE / SPLIT / ADOPT /
    # RETUNE in the causal order the proof claims
    "reshard.agree": "AGREE-SPLIT",
    "reshard.split": "SPLIT",
    "reshard.adopt": "ADOPT",
    "eventtime.watermark_advance": "WATERMARK",
    "eventtime.pane_close": "PANE-CLOSE",
    "eventtime.retract": "RETRACT",
    "eventtime.late_dropped": "LATE-DROP",
    # the transaction story (ISSUE 20): each snapshot-pinned
    # transaction's begin, every read answered AT a pinned version,
    # and every honest expiry — the ring slid
    # (txn.snapshot_expired{reason}), a promoted standby's mirror
    # missing the pin (txn.failover_expired), or a txn-unaware peer
    # detected from its reply stamp — so a storm run renders
    # TXN-BEGIN / TXN-READ / KILL / PROMOTE / TXN-READ (the survivor
    # answering the same pin) or TXN-EXPIRED, never a silently
    # fresher answer
    "txn.begin": "TXN-BEGIN",
    "txn.pinned_reads": "TXN-READ",
    "txn.snapshot_expired": "TXN-EXPIRED",
    "txn.failover_expired": "TXN-EXPIRED",
    "txn.unaware_peer": "TXN-EXPIRED",
    "flight": "BLACKBOX",
}


def load_run(root: str) -> List[dict]:
    """Every shard event under ``root`` plus one synthetic event per
    flight dump (kind ``flight``, carrying the dump's reason/shard),
    globally ``ts``-ordered."""
    from . import flight as _flight

    events = list(iter_shard_events(root))
    if os.path.isdir(root):
        dump_paths = []
        for dirpath, _dirnames, _filenames in os.walk(root):
            dump_paths.extend(_flight.find_dumps(dirpath))
        for p in sorted(set(dump_paths)):
            try:
                doc = _flight.read_dump(p)
            except Exception:
                # a torn dump is itself evidence; surface it as such
                events.append({
                    "kind": "flight", "name": "flight",
                    "ts": os.path.getmtime(p),
                    "attrs": {"path": os.path.relpath(p, root),
                              "unreadable": True},
                })
                continue
            events.append({
                "kind": "flight",
                "name": "flight",
                "ts": doc.get("ts", os.path.getmtime(p)),
                "shard": (
                    f"p{doc['shard']}" if doc.get("shard") is not None
                    else None
                ),
                "attrs": {
                    "reason": doc.get("reason"),
                    "n_events": doc.get("n_events"),
                    # the dying worker's pid joins the BLACKBOX line to
                    # the supervisor's restart/kill lines for the same
                    # process — and the dump's own attrs (error reprs,
                    # kill sites) ride along instead of staying buried
                    # in the container
                    "pid": doc.get("pid"),
                    "path": os.path.relpath(p, root),
                    **(doc.get("attrs") or {}),
                },
            })
    events.sort(key=lambda e: float(e.get("ts") or 0.0))
    return events


def run_t0(events: Iterable[dict]) -> float:
    """The run's earliest real timestamp (0.0 when none) — the zero
    point of the rendered ``+...s`` column and of relative ``--since``/
    ``--until`` values."""
    stamps = [
        float(e["ts"]) for e in events
        if isinstance(e.get("ts"), (int, float)) and e["ts"]
    ]
    return min(stamps) if stamps else 0.0


def filter_events(
    events: Iterable[dict],
    *,
    since: Optional[float] = None,
    until: Optional[float] = None,
    trace: Optional[str] = None,
) -> List[dict]:
    """Window/trace filter over a merged event stream (the pure core of
    the ``--since``/``--until``/``--trace`` CLI flags). ``since``/
    ``until`` are ABSOLUTE timestamps (the CLI resolves ``+N``
    relative forms against :func:`run_t0` first); bounds are inclusive.
    ``trace`` keeps only events stamped with that trace id."""
    out = []
    for e in events:
        if trace is not None and e.get("trace") != trace:
            continue
        ts = e.get("ts")
        ts = float(ts) if isinstance(ts, (int, float)) else 0.0
        if since is not None and ts < since:
            continue
        if until is not None and ts > until:
            continue
        out.append(e)
    return out


def _fmt_labels(e: dict) -> str:
    labels = dict(e.get("labels") or {})
    labels.pop("shard", None)  # already the line's [shard] column
    body = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{{{body}}}" if body else ""


def render(events: Iterable[dict], *, all_events: bool = False,
           t0: Optional[float] = None) -> List[str]:
    """Format merged events as timeline lines (the CLI's output, and
    the programmatic surface tests pin)."""
    events = list(events)
    if t0 is None:
        t0 = run_t0(events)
    lines = []
    for e in events:
        name = e.get("name", "")
        kind = e.get("kind", "")
        tag = STORY.get(name) or (STORY.get("flight") if kind == "flight"
                                  else None)
        if tag is None and not all_events:
            continue
        ts = float(e.get("ts") or 0.0)
        shard = e.get("shard") or "-"
        head = f"+{max(0.0, ts - t0):8.3f}s  [{shard:>12}] " \
               f"{tag or kind.upper():<10} {name}{_fmt_labels(e)}"
        detail = []
        if kind == "hist" and "v" in e:
            detail.append(f"v={e['v']:.4g}")
        elif kind in ("counter", "gauge") and "v" in e:
            detail.append(f"v={e['v']:g}")
        elif kind == "span":
            detail.append(f"dur={e.get('dur_s', 0):.4g}s")
        attrs = e.get("attrs")
        if attrs:
            detail.append(
                " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
            )
        if detail:
            head += "  " + " ".join(detail)
        lines.append(head)
    return lines


def _take_value(argv: List[str], flag: str) -> Optional[str]:
    """Pop ``--flag value`` (or ``--flag=value``) out of argv."""
    for i, a in enumerate(argv):
        if a == flag:
            if i + 1 >= len(argv):
                raise SystemExit(f"{flag} needs a value")
            value = argv[i + 1]
            del argv[i:i + 2]
            return value
        if a.startswith(flag + "="):
            del argv[i]
            return a[len(flag) + 1:]
    return None


def _resolve_ts(raw: Optional[str], t0: float, flag: str
                ) -> Optional[float]:
    """``+N`` is run-relative seconds; anything else an absolute
    timestamp."""
    if raw is None:
        return None
    try:
        if raw.startswith("+"):
            return t0 + float(raw[1:])
        return float(raw)
    except ValueError:
        raise SystemExit(f"{flag} wants a number, got {raw!r}") from None


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    trace = _take_value(argv, "--trace")
    since_raw = _take_value(argv, "--since")
    until_raw = _take_value(argv, "--until")
    all_events = "--all" in argv
    roots = [a for a in argv if not a.startswith("--")]
    if not roots:
        print(
            "usage: python -m gelly_streaming_tpu.obs.timeline "
            "<run-dir|events.jsonl> [--all] [--trace <id>] "
            "[--since <ts|+s>] [--until <ts|+s>]",
            file=sys.stderr,
        )
        return 2
    rc = 0
    for root in roots:
        events = load_run(root)
        # offsets stay anchored to the RUN's start even when a window
        # or trace filter narrows the view — the +N column must mean
        # the same instant with and without filters
        t0 = run_t0(events)
        shown_events = filter_events(
            events,
            since=_resolve_ts(since_raw, t0, "--since"),
            until=_resolve_ts(until_raw, t0, "--until"),
            trace=trace,
        )
        # a trace view IS the story: render every one of its events
        lines = render(shown_events, all_events=all_events or
                       trace is not None, t0=t0)
        if not lines:
            print(f"{root}: no events", file=sys.stderr)
            rc = 1
            continue
        shown = (f"trace {trace}" if trace is not None
                 else "all" if all_events else "story")
        print(f"# {root}: {len(events)} events, {len(lines)} shown "
              f"({shown})")
        for line in lines:
            print(line)
    return rc


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # `... | head` closed the pipe: normal CLI lifecycle, not an
        # error (devnull dup avoids the interpreter's own flush noise)
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
