"""BASELINE corpus registry, loaders, and surrogate synthesis.

The measurement matrix in ``BASELINE.md`` names three corpora: the SNAP
LiveJournal edge list (streaming CC at scale), the SNAP twitter-ego
combined edge list, and MovieLens ratings (the weighted-matching workload —
the reference's matching example reads the same dataset,
``example/CentralizedWeightedMatching.java:41-44``). This module gives each
a loader over the native chunked parser, plus an RMAT surrogate generator
for hermetic environments (no network egress): ``ensure_corpus`` returns
the real file when present under ``$GELLY_DATA`` / ``./data`` and otherwise
synthesizes (once, cached) a surrogate with the same format and a
documented scale, so benchmarks always run file-first — the point is
timing the *system* path (file -> windower -> dict -> device), never a
pre-staged array.

Surrogates are R-MAT graphs (Graph500 parameters a=.57 b=.19 c=.19 d=.05):
power-law degrees, community structure, and raw 64-bit-id sparsity — the
properties that stress parsing, vertex compaction, and skew handling the
way the real corpora do.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Tuple

import numpy as np

from . import native
from .core.stream import SimpleEdgeStream
from .core.vertexdict import VertexDict
from .core.window import CountWindow, EventTimeWindow, WindowPolicy, Windower


@dataclasses.dataclass(frozen=True)
class CorpusSpec:
    name: str
    filename: str  # conventional filename under the data dir
    url: str  # provenance (documentation only; never fetched)
    n_edges: int  # published size of the real corpus
    n_vertices: int
    weighted: bool = False
    # surrogate scale: edges/vertices for the synthesized stand-in
    surrogate_edges: int = 1 << 24
    surrogate_vscale: int = 1 << 21


CORPORA = {
    "livejournal": CorpusSpec(
        name="livejournal",
        filename="soc-LiveJournal1.txt",
        url="https://snap.stanford.edu/data/soc-LiveJournal1.html",
        n_edges=68_993_773,
        n_vertices=4_847_571,
        surrogate_edges=1 << 24,
        surrogate_vscale=1 << 21,
    ),
    # north-star scale (BASELINE.md: ">=100M streamed edges, 100M-edge
    # windows"): a scale-23 R-MAT surrogate roughly 2x the real
    # LiveJournal's edge count — no real corpus by this name exists, so
    # this always synthesizes
    "livejournal-xl": CorpusSpec(
        name="livejournal-xl",
        filename="soc-LiveJournal1-xl.txt",
        url="https://snap.stanford.edu/data/soc-LiveJournal1.html",
        n_edges=1 << 27,
        n_vertices=1 << 23,
        surrogate_edges=1 << 27,
        surrogate_vscale=1 << 23,
    ),
    "twitter-ego": CorpusSpec(
        name="twitter-ego",
        filename="twitter_combined.txt",
        url="https://snap.stanford.edu/data/ego-Twitter.html",
        n_edges=2_420_766,
        n_vertices=81_306,
        surrogate_edges=1 << 21,
        surrogate_vscale=1 << 17,
    ),
    "movielens-100k": CorpusSpec(
        name="movielens-100k",
        filename="u.data",
        url="https://grouplens.org/datasets/movielens/100k/",
        n_edges=100_000,
        n_vertices=943 + 1682,
        weighted=True,
        surrogate_edges=100_000,
        surrogate_vscale=1 << 11,
    ),
}

# MovieLens rates (user, item) pairs whose id ranges overlap; loaders offset
# item ids into a disjoint range so the bipartite structure survives the
# shared vertex-id space (the reference's preprocessed movielens file has
# the same property).
MOVIELENS_ITEM_OFFSET = 1 << 20


def data_dirs() -> list:
    dirs = []
    env = os.environ.get("GELLY_DATA")
    if env:
        dirs.append(env)
    dirs.append(os.path.join(os.getcwd(), "data"))
    dirs.append("/tmp/gelly_data")
    return dirs


def locate(name: str) -> Optional[str]:
    """Path of the real corpus file if present under a data dir."""
    spec = CORPORA[name]
    for d in data_dirs():
        p = os.path.join(d, spec.filename)
        if os.path.exists(p):
            return p
    return None


# --------------------------------------------------------------------- #
# Surrogate synthesis (R-MAT)
# --------------------------------------------------------------------- #
def rmat_edges(
    n_edges: int,
    scale: int,
    seed: int = 0,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized R-MAT: ``n_edges`` edges over ``2**scale`` vertices.

    One pass per address bit; each pass picks the quadrant for every edge
    at once (no per-edge recursion).
    """
    rng = np.random.default_rng(seed)
    src = np.zeros(n_edges, np.int64)
    dst = np.zeros(n_edges, np.int64)
    for _ in range(scale):
        r = rng.random(n_edges)
        src_bit = r >= (a + b)
        dst_bit = (r >= a) & (r < a + b) | (r >= a + b + c)
        src = (src << 1) | src_bit
        dst = (dst << 1) | dst_bit
    return src, dst


def synthesize(
    name: str, path: str, seed: int = 0, chunk: int = 1 << 22
) -> str:
    """Write the surrogate corpus for ``name`` to ``path`` (SNAP format:
    '#' header + tab-separated edges; MovieLens adds a rating column)."""
    spec = CORPORA[name]
    scale = int(spec.surrogate_vscale).bit_length() - 1
    with open(path, "w") as f:
        f.write(
            f"# surrogate for {spec.name} ({spec.url})\n"
            f"# R-MAT scale={scale} edges={spec.surrogate_edges}\n"
        )
    rng = np.random.default_rng(seed + 1)
    for start in range(0, spec.surrogate_edges, chunk):
        n = min(chunk, spec.surrogate_edges - start)
        src, dst = rmat_edges(n, scale, seed=seed + start)
        if spec.weighted:
            # ratings column: integer 1..5, appended text-side
            # raw (user, item, rating) rows like the real u.data; loaders
            # apply MOVIELENS_ITEM_OFFSET, so the file itself stays raw
            w = rng.integers(1, 6, n)
            with open(path, "a") as f:
                for s, d, r in zip(src.tolist(), dst.tolist(), w.tolist()):
                    f.write(f"{s}\t{d}\t{r}\n")
        else:
            native.write_edge_file(path, src, dst, append=True)
    return path


def ensure_corpus(name: str) -> Tuple[str, bool]:
    """(path, is_real): the real corpus if present, else the cached
    surrogate (synthesized on first use)."""
    real = locate(name)
    if real is not None:
        return real, True
    cache_dir = "/tmp/gelly_data"
    os.makedirs(cache_dir, exist_ok=True)
    spec = CORPORA[name]
    path = os.path.join(
        cache_dir, f"surrogate_{name}_{spec.surrogate_edges}.txt"
    )
    if not os.path.exists(path):
        synthesize(name, path)
    return path, False


# --------------------------------------------------------------------- #
# Identity vertex mapping (dense-integer corpora)
# --------------------------------------------------------------------- #
class IdentityDict:
    """VertexDict stand-in for corpora whose ids are already dense small
    integers (LiveJournal, most SNAP graphs): compact id == raw id, so the
    encode stage of ingest disappears.

    This mirrors the reference, which also uses raw ``Long`` ids directly
    as keys (``summaries/DisjointSet.java:30``) — no compaction exists
    there either. Emission correctness does not depend on this mapping:
    workloads track which vertices actually appeared (e.g. the label
    table's ``touched`` mask), so id-space gaps never show up as phantom
    vertices.
    """

    def __init__(self, id_bound: int):
        self.id_bound = int(id_bound)
        self._observed = 0  # max encoded id + 1

    def __len__(self) -> int:
        """Number of ids actually observed (max + 1), NOT the declared
        bound: consumers that treat ``len(vdict)`` as the seen-vertex
        count (IncrementalPageRank's teleport mass) would otherwise
        spread rank over the whole declared id space (round-2 advisor
        finding)."""
        return self._observed

    @property
    def capacity(self) -> int:
        from .core.edgeblock import bucket_capacity

        return bucket_capacity(max(1, self.id_bound))

    def observe(self, max_id: int) -> None:
        """Advance the observed-id watermark (the single implementation of
        the ``len()`` semantics — encode and the parser fast path both
        route through here)."""
        if max_id >= self._observed:
            self._observed = max_id + 1

    def encode(self, raw):
        a = np.asarray(raw)
        if a.size:
            hi = int(a.max())
            if int(a.min()) < 0 or hi >= self.id_bound:
                raise ValueError(
                    f"raw id outside [0, {self.id_bound}) — not a dense-id "
                    "corpus; use VertexDict"
                )
            self.observe(hi)
        return a if a.dtype == np.int32 else a.astype(np.int32)

    def encode_pair(self, src, dst):
        return self.encode(src), self.encode(dst)

    def decode(self, idx):
        return np.asarray(idx, np.int64)

    def decode_one(self, idx: int) -> int:
        return int(idx)

    def lookup(self, raw: int):
        return int(raw) if 0 <= int(raw) < self.id_bound else None

    def lookup_batch(self, raw) -> np.ndarray:
        """Vectorized :meth:`lookup` (the serving query path): compact
        ids, -1 for ids outside the declared bound."""
        a = np.asarray(raw, np.int64).ravel()
        return np.where(
            (a >= 0) & (a < self.id_bound), a, -1
        ).astype(np.int32)

    def raw_ids(self) -> np.ndarray:
        """Ids observed so far (the checkpoint surface): restoring these
        through ``encode`` reproduces the watermark instead of resetting
        ``len()`` to the whole declared bound."""
        return np.arange(self._observed, dtype=np.int64)

    def raw_table(self):
        import jax.numpy as jnp

        return jnp.arange(self.capacity, dtype=jnp.int32)


# --------------------------------------------------------------------- #
# Binary edge cache (the Arrow/Kafka-style ingest format)
# --------------------------------------------------------------------- #
_BIN_MAGIC = b"GELLYB1\x00"


def binary_cache(path: str, bin_path: Optional[str] = None, arrays=None) -> str:
    """Convert a text edge list to the packed binary format (one-time);
    returns the binary path. Layout: magic, int64 n, uint8 has_val, then
    src int32[n], dst int32[n], and val float32[n] when present — the
    shape a production ingest bus (Kafka/Arrow) would deliver, letting the
    bench separate text-parse cost from the streaming system itself.

    ``arrays=(src, dst, val|None)`` skips re-parsing when the caller
    already holds the parsed columns."""
    if bin_path is None:
        bin_path = path + ".gbin"
    # freshness by source size+mtime sidecar, not mtime ORDER: a restored
    # or copied corpus file can carry any mtime and would silently serve
    # a stale cache (round-2 advisor finding; same fix as the .so build)
    st = os.stat(path)
    stamp = f"{st.st_size}:{int(st.st_mtime_ns)}"
    sidecar = bin_path + ".src"
    if os.path.exists(bin_path):
        try:
            with open(sidecar) as f:
                if f.read().strip() == stamp:
                    return bin_path
        except OSError:
            pass
    src, dst, val = arrays if arrays is not None else native.parse_edge_file(path)
    if src.size and (
        max(src.max(), dst.max()) > np.iinfo(np.int32).max or min(src.min(), dst.min()) < 0
    ):
        raise ValueError("binary cache requires non-negative int32 ids")
    with open(bin_path + ".tmp", "wb") as f:
        f.write(_BIN_MAGIC)
        np.asarray([len(src)], np.int64).tofile(f)
        np.asarray([0 if val is None else 1], np.uint8).tofile(f)
        src.astype(np.int32).tofile(f)
        dst.astype(np.int32).tofile(f)
        if val is not None:
            val.astype(np.float32).tofile(f)
    os.replace(bin_path + ".tmp", bin_path)
    with open(sidecar, "w") as f:
        f.write(stamp)
    return bin_path


def iter_binary_chunks(bin_path: str, chunk_edges: int = 1 << 21):
    """Yield (src, dst, val|None) int32/float32 column chunks from a
    :func:`binary_cache` file via memmap views (zero-copy)."""
    with open(bin_path, "rb") as f:
        if f.read(8) != _BIN_MAGIC:
            raise IOError(f"{bin_path}: not a gelly binary edge file")
        n = int(np.fromfile(f, np.int64, 1)[0])
        has_val = bool(np.fromfile(f, np.uint8, 1)[0])
        base = f.tell()
    mm = np.memmap(bin_path, mode="r", dtype=np.uint8)
    src = mm[base : base + 4 * n].view(np.int32)
    dst = mm[base + 4 * n : base + 8 * n].view(np.int32)
    val = mm[base + 8 * n : base + 12 * n].view(np.float32) if has_val else None
    for a in range(0, n, chunk_edges):
        b = min(a + chunk_edges, n)
        yield src[a:b], dst[a:b], None if val is None else val[a:b]


# --------------------------------------------------------------------- #
# File -> stream
# --------------------------------------------------------------------- #
class _ValuePacker:
    """Packed value columns for the device-encode path (round-4 verdict
    missing #6): a value-CONSUMING workload previously paid the full
    per-window float32 upload (4 B/edge — one third of the H2D budget on
    top of the mandatory 8 B/edge id columns).

    Real weighted corpora overwhelmingly carry LOW-CARDINALITY values
    (MovieLens ratings: 10 distinct; small integer weights), so the host
    keeps a sorted dictionary of distinct float32 values beside the
    parser and ships uint8 codes (1 B/edge; uint16 above 255 distinct) +
    a tiny LUT that re-uploads only when it changes; the device widens
    with one gather. The TOP code of each width (255 / 65535) is
    reserved: it always decodes to 0.0, preserving the padded-slot
    val==0 invariant every other ingest path guarantees (aggregations
    that scatter-add values without re-masking rely on it). LOSSLESS by
    construction — any window that would exceed 65535 distinct values,
    or contains NaN (unorderable, so the sorted-dictionary probe cannot
    code it), permanently escalates the stream to the raw float32 path.
    """

    __slots__ = ("table", "mode", "_lut_dev", "_lut_stale")

    def __init__(self):
        self.table = np.zeros(0, np.float32)
        self.mode = "u8"  # "u8" | "u16" | "f32"
        self._lut_dev = None
        self._lut_stale = True

    def _probe(self, v):
        codes = np.searchsorted(self.table, v)
        np.minimum(codes, max(len(self.table) - 1, 0), out=codes)
        miss = (
            np.zeros(len(v), bool) if len(self.table) == 0
            else self.table[codes] != v
        )
        if len(self.table) == 0:
            miss[:] = True
        return codes, miss

    def pack(self, v: np.ndarray):
        """-> (codes uint8/uint16, lut jnp or None) or None once
        escalated to raw f32."""
        import jax.numpy as jnp

        if self.mode == "f32":
            return None
        v = np.ascontiguousarray(v, np.float32)
        codes, miss = self._probe(v)
        if miss.any():
            if np.isnan(v).any():
                self.mode = "f32"
                return None
            self.table = np.union1d(self.table, np.unique(v[miss])).astype(
                np.float32
            )
            if len(self.table) > 65535:  # top u16 code reserved for pads
                self.mode = "f32"
                return None
            if len(self.table) > 255 and self.mode == "u8":
                self.mode = "u16"
            self._lut_stale = True
            codes, miss = self._probe(v)
            assert not miss.any()
        dt = np.uint8 if self.mode == "u8" else np.uint16
        if self._lut_stale:
            pad = 256 if self.mode == "u8" else 65536
            lut = np.zeros(pad, np.float32)
            lut[: len(self.table)] = self.table
            self._lut_dev = jnp.asarray(lut)
            self._lut_stale = False
        return codes.astype(dt), self._lut_dev


def _decode_vals(lut, codes):
    return lut[codes]


_decode_vals_jit = None


def _device_encoded_blocks(path, is_binary, policy, vdict, chunk_edges,
                           drop_values=False):
    """Window blocks whose vertex mapping runs ON DEVICE: host work is
    slicing raw columns and device puts; the compaction is the carried
    device hash table (``ops/device_dict.py``). ``policy`` is a
    CountWindow (fixed ``size`` slices) or an EventTimeWindow (ascending
    timestamps from ``timestamp_fn`` over the column tuple — same
    contract as the Windower's array fast path; window boundaries are
    runs of equal time slot, so block capacities bucket by observed
    window size).

    With a declared ``id_bound`` the table covers the id space and every
    window is one unconditional encode dispatch. WITHOUT a bound (general
    arbitrary-id streams) the host tracks the EXACT distinct-id count of
    the raw stream as it parses (``native.NoveltyBitmap`` — first-seen
    distinctness is precisely the device table's count) and grows the
    device table by pure padding BEFORE any window could overflow it.
    Either way the pipeline performs zero device->host reads: a single
    scalar fetch through the remote-TPU tunnel measures ~0.5-3 s (round
    3), which is why no "read the count back" design can work. The
    device-side sticky ``probe`` field still detects a (bug-only)
    overflow at the next natural sync.
    """
    import jax.numpy as jnp

    from .core.edgeblock import EdgeBlock, _cached_mask, _cached_zeros
    from .core.edgeblock import bucket_capacity as bcap

    growth = getattr(vdict, "id_bound", 1) == 0
    if growth:
        if getattr(vdict, "_novelty", None) is None:
            # owned by the dict: novelty state must live exactly as long
            # as the table it mirrors (stream re-iteration reuses both)
            vdict._novelty = native.NoveltyBitmap()
            vdict._novel_seen = 0
        novelty = vdict._novelty

    packer = _ValuePacker()

    def build(si, di, v, n):
        cap = bcap(n)
        if cap != n:
            si = jnp.pad(si, (0, cap - n))
            di = jnp.pad(di, (0, cap - n))
        if v is None or drop_values:
            # value-ignoring workloads (CC, degrees, triangles) on
            # weighted corpora: skip the per-window float32 H2D entirely
            # (ROADMAP #4); the cached zero column is one device constant
            val = _cached_zeros(cap, jnp.float32)
        else:
            packed = packer.pack(v)
            if packed is None:  # high-cardinality / NaN: raw f32 column
                vp = np.zeros(cap, np.float32)
                vp[:n] = v
                val = jnp.asarray(vp)
            else:
                codes, lut = packed
                # pads take the reserved top code, which decodes to 0.0
                # (the padded-val invariant; code 0 would decode to the
                # smallest DISTINCT VALUE and silently weight vertex 0)
                cp = np.full(cap, np.iinfo(codes.dtype).max, codes.dtype)
                cp[:n] = codes
                global _decode_vals_jit
                if _decode_vals_jit is None:
                    import jax

                    _decode_vals_jit = jax.jit(_decode_vals)
                val = _decode_vals_jit(lut, jnp.asarray(cp))
        return EdgeBlock(
            src=si, dst=di, val=val,
            mask=_cached_mask(cap, n), n_vertices=vdict.capacity,
        )

    def emit(s, d, v):
        if growth:
            vdict.ensure_capacity_host(vdict._novel_seen)
            si, di = vdict.encode_pair_spec(s, d)
        else:
            si, di = vdict.encode_pair(s, d)
        return build(si, di, v, len(s))

    read_chunk = (
        policy.size if isinstance(policy, CountWindow) else chunk_edges
    )
    src = (
        iter_binary_chunks(path, read_chunk)
        if is_binary
        else native.iter_edge_chunks_i32(
            path, chunk_edges, id_bound=getattr(vdict, "id_bound", 0)
        )
    )
    if not isinstance(policy, CountWindow):
        yield from _event_time_device_blocks(src, policy, vdict, growth, emit)
        return
    size = policy.size
    pend, have = [], 0
    for s, d, v in src:
        s, d = np.asarray(s), np.asarray(d)
        if growth:
            vdict._novel_seen += novelty.novel2(s, d)
        pend.append((s, d, v))
        have += len(s)
        while have >= size:
            if len(pend) == 1:
                cs, cd, cv = pend[0]
            else:
                cs = np.concatenate([p[0] for p in pend])
                cd = np.concatenate([p[1] for p in pend])
                cv = (
                    np.concatenate(
                        [
                            np.zeros(len(p[0]), np.float32) if p[2] is None
                            else np.asarray(p[2], np.float32)
                            for p in pend
                        ]
                    )
                    if any(p[2] is not None for p in pend)
                    else None
                )
            yield emit(
                cs[:size], cd[:size], None if cv is None else cv[:size]
            )
            pend = [(cs[size:], cd[size:], None if cv is None else cv[size:])]
            have -= size
    if have:
        cs, cd, cv = pend[0] if len(pend) == 1 else (
            np.concatenate([p[0] for p in pend]),
            np.concatenate([p[1] for p in pend]),
            (
                np.concatenate(
                    [
                        np.zeros(len(p[0]), np.float32) if p[2] is None
                        else np.asarray(p[2], np.float32)
                        for p in pend
                    ]
                )
                if any(p[2] is not None for p in pend)
                else None
            ),
        )
        if len(cs):
            yield emit(cs, cd, cv)


def _event_time_device_blocks(src, policy, vdict, growth, emit):
    """Event-time windowing for the device-encode path: the shared
    chunked slot-run splitter (``core.window.iter_time_slot_runs`` — ONE
    implementation of the boundary semantics with the host Windower),
    with novelty tracking applied per raw chunk on the way in."""
    from .core.window import iter_time_slot_runs

    novelty = getattr(vdict, "_novelty", None)

    def tracked(chunks):
        for s, d, v in chunks:
            s, d = np.asarray(s), np.asarray(d)
            if growth:
                vdict._novel_seen += novelty.novel2(s, d)
            yield s, d, v

    for _slot, s, d, v in iter_time_slot_runs(
        tracked(src), policy, val_dtype=np.float32
    ):
        yield emit(s, d, v)


def stream_file(
    path: str,
    window: Optional[WindowPolicy] = None,
    *,
    vertex_dict: Optional[VertexDict] = None,
    chunk_edges: int = 1 << 21,
    prefetch_depth: int = 0,
    min_vertex_capacity: int = 0,
    device_encode: bool = False,
    dense_ids: bool = True,
    drop_values: bool = False,
) -> SimpleEdgeStream:
    """A :class:`SimpleEdgeStream` over an edge file, chunk-parsed natively.

    The returned stream re-reads the file on every iteration (streams are
    lazily re-iterable). ``prefetch_depth > 0`` overlaps parse/window/encode
    against device compute on a background thread; as with
    ``SimpleEdgeStream.prefetched``, the shared vertex dict (including
    ``IdentityDict``'s observed-id watermark) may then run up to ``depth``
    windows ahead of the consumer — only mid-stream ``len(vertex_dict)``
    readers observe the lead. ``min_vertex_capacity``
    pre-sizes the vertex table (e.g. from the corpus spec) so carried device
    state compiles once instead of once per capacity-growth bucket.

    ``device_encode=True`` moves vertex compaction onto the device
    (``ops/device_dict.py``). With ``dense_ids=True`` (default)
    ``min_vertex_capacity`` is also the declared raw-id bound — the table
    covers the id space and never grows. ``dense_ids=False`` is the
    GENERAL arbitrary-id path: ids may be any non-negative int32, the
    table grows proactively from exact host-side novelty tracking (see
    :func:`_device_encoded_blocks`), and ``min_vertex_capacity`` is
    only a pre-sizing hint. Ids beyond int32 need the host ``VertexDict``.
    ``drop_values=True`` skips the per-window value-column upload for
    value-ignoring workloads on weighted corpora (device-encode only).
    """
    policy = window or CountWindow(1 << 20)
    is_binary = path.endswith(".gbin")
    if device_encode:
        # vertex compaction as device state: one encode dispatch per
        # window, no host hash work (ROADMAP #1)
        if not isinstance(policy, (CountWindow, EventTimeWindow)):
            raise ValueError(
                "device_encode supports CountWindow / EventTimeWindow"
            )
        if vertex_dict is not None:
            raise ValueError(
                "device_encode builds its own DeviceVertexDict; a supplied "
                "vertex_dict would be silently ignored"
            )
        from .ops.device_dict import DeviceVertexDict

        vd = DeviceVertexDict(
            min_capacity=max(min_vertex_capacity, 1 << 10),
            id_bound=min_vertex_capacity if dense_ids else 0,
        )

        def device_source():
            it = _device_encoded_blocks(
                path, is_binary, policy, vd, chunk_edges,
                drop_values=drop_values,
            )
            if prefetch_depth > 0:
                from .core.pipeline import prefetch

                return prefetch(it, prefetch_depth)
            return it

        return SimpleEdgeStream(_blocks=device_source, _vdict=vd)
    if vertex_dict is None and min_vertex_capacity > 0:
        vertex_dict = VertexDict(min_capacity=min_vertex_capacity)
    windower = Windower(policy, vertex_dict)

    def block_source():
        vd = windower.vertex_dict
        identity = isinstance(vd, IdentityDict)
        if is_binary:
            raw_chunks = iter_binary_chunks(path, chunk_edges)
            if identity:
                chunks = (
                    (vd.encode(s), vd.encode(d), v) for s, d, v in raw_chunks
                )
            else:
                chunks = (
                    (*vd.encode_pair(s, d), v) for s, d, v in raw_chunks
                )
            pairs = windower.blocks_from_chunks(chunks, encoded=True)
        elif identity:
            # the i32 parser already bound-checks against the id space, so
            # the columns pass through with no further validation/convert;
            # only the observed-id watermark (len(vdict)) needs updating
            def _tracked(chunks, vd=vd):
                for s, d, v in chunks:
                    if len(s):
                        vd.observe(int(max(int(s.max()), int(d.max()))))
                    yield s, d, v

            chunks = _tracked(native.iter_edge_chunks_i32(
                path, chunk_edges, id_bound=vd.id_bound
            ))
            pairs = windower.blocks_from_chunks(chunks, encoded=True)
        elif getattr(vd, "_native", None) is not None:
            # fused native ingest: parse+encode in one C pass per chunk
            chunks = vd.iter_encode_file(path, chunk_edges)
            pairs = windower.blocks_from_chunks(chunks, encoded=True)
        else:
            pairs = windower.blocks_from_chunks(
                native.iter_edge_chunks(path, chunk_edges)
            )
        it = (info_block[1] for info_block in pairs)
        if prefetch_depth > 0:
            from .core.pipeline import prefetch

            return prefetch(it, prefetch_depth)
        return it

    return SimpleEdgeStream(
        _blocks=block_source, _vdict=windower.vertex_dict
    )


def load_movielens(path: str) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(user, item, rating) columns from a MovieLens ``u.data``-format file
    (user \\t item \\t rating \\t timestamp); item ids offset into a
    disjoint range (``MOVIELENS_ITEM_OFFSET``)."""
    src, dst, val = native.parse_edge_file(path)
    if val is None:
        val = np.ones(len(src))
    return src, dst + MOVIELENS_ITEM_OFFSET, val
