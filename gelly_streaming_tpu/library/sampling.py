"""Sampling-based streaming triangle estimators (Buriol et al. style).

TPU-native re-design of the reference's two estimator examples:

- ``example/BroadcastTriangleCount.java:62-174``: every subtask holds
  ``samples/parallelism`` reservoir states; each state keeps one sampled
  edge (coin-flip 1/i replacement), a uniformly-drawn third vertex, and
  found-flags for the two closing edges; the estimate is
  ``(1/samples) * Σbeta * edgeCount * (V-2)``.
- ``example/IncidenceSamplingTriangleCount.java:61-242``: identical
  estimator; a parallelism-1 mapper owns the coin flips and routes only
  sampled/incident edges to the keyed samplers.

The two differ only in Flink *routing* (broadcast replication vs targeted
keyed messages), which has no TPU meaning — sample states are a ``[k]``
vector replicated on device either way. Both classes share one kernel: a
``lax.scan`` over the window's edges whose per-step body updates all ``k``
reservoir states as dense vector ops (the per-edge sequential semantics of
the reference, vectorized across samples). RNG is `jax.random` with a
carried key — deterministic per seed, the moral equivalent of the
incidence variant's seeded ``Random(0xDEADBEEF)``
(``IncidenceSamplingTriangleCount.java:78``).

Estimates use RAW vertex ids (no VertexDict): like the reference, the
third vertex is drawn from a caller-supplied id space ``[0, vertex_count)``.
"""

from __future__ import annotations

import functools
from typing import Iterable, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.window import CountWindow, WindowPolicy, Windower


def init_sampler_state(n_samples: int):
    return {
        "src": jnp.full(n_samples, -1, jnp.int32),
        "trg": jnp.full(n_samples, -1, jnp.int32),
        "third": jnp.full(n_samples, -1, jnp.int32),
        "src_found": jnp.zeros(n_samples, bool),
        "trg_found": jnp.zeros(n_samples, bool),
    }


@functools.partial(jax.jit, static_argnums=(5,))
def _window_scan(state, edge_count, key, edges, mask, vertex_count: int):
    """Fold one window of edges through all reservoir states.

    ``edges``: (src[E], dst[E]) int32 raw ids; ``edge_count``: edges seen
    before this window. Returns (state, new_edge_count, new_key, beta_sum).
    """
    k = state["src"].shape[0]

    def step(carry, x):
        st, m, key = carry
        s, d, valid = x
        m1 = m + valid.astype(jnp.int32)
        key, k_coin, k_third = jax.random.split(key, 3)
        # coin-flip 1/m per sample: replace the reservoir edge
        coin = jax.random.uniform(k_coin, (k,)) < (1.0 / m1.astype(jnp.float32))
        resample = valid & coin
        # third vertex uniform over [0, V) \ {s, d}
        u1 = jnp.minimum(s, d)
        u2 = jnp.maximum(s, d)
        distinct = u1 != u2
        n_valid = vertex_count - 1 - distinct.astype(jnp.int32)
        r = jax.random.uniform(k_third, (k,))
        c0 = jnp.minimum(
            (r * n_valid.astype(jnp.float32)).astype(jnp.int32), n_valid - 1
        )
        c1 = c0 + (c0 >= u1)
        c = c1 + ((c1 >= u2) & distinct)
        st = {
            "src": jnp.where(resample, s, st["src"]),
            "trg": jnp.where(resample, d, st["trg"]),
            "third": jnp.where(resample, c, st["third"]),
            "src_found": jnp.where(resample, False, st["src_found"]),
            "trg_found": jnp.where(resample, False, st["trg_found"]),
        }
        # closing-edge checks (undirected match, reference :108-121)
        hit_src = ((s == st["src"]) & (d == st["third"])) | (
            (s == st["third"]) & (d == st["src"])
        )
        hit_trg = ((s == st["trg"]) & (d == st["third"])) | (
            (s == st["third"]) & (d == st["trg"])
        )
        st["src_found"] = st["src_found"] | (valid & hit_src)
        st["trg_found"] = st["trg_found"] | (valid & hit_trg)
        return (st, m1, key), None

    (state, edge_count, key), _ = jax.lax.scan(
        step, (state, edge_count, key), (edges[0], edges[1], mask)
    )
    beta_sum = (state["src_found"] & state["trg_found"]).sum()
    return state, edge_count, key, beta_sum


#: largest vertex_count whose canonical pair key (u*V+v) fits int32
_PACK_LIMIT = 46340


@functools.partial(jax.jit, static_argnums=(5,))
def _window_vectorized(
    state, edge_count, key, edges, mask, vertex_count: int, table=None
):
    """Distribution-equivalent vectorized window update (no per-edge scan).

    Reservoir identity: after the window, each sample kept its carried
    edge with probability m/N (m edges before, N after), else it holds a
    uniformly-selected window edge — so the final position is drawn
    DIRECTLY instead of simulating E sequential coin flips (round-1 weak
    item: a 1M-edge window was a 1M-step scan doing O(k) work per step).
    The closing-edge flags likewise collapse to last-occurrence queries:
    a flag sets iff the (endpoint, third) pair occurs in the window at a
    position strictly after the sample's selection (any position for
    carried samples) — answered by binary search over the window's
    canonical pairs sorted with their positions. O(E log E + k log E)
    total, fully parallel. Same estimator distribution; a different RNG
    stream than the scan path (both deterministic per seed).
    """
    s, d = edges
    if table is not None:
        # compact block ids -> raw ids ON DEVICE (no host round trip)
        s = table[s]
        d = table[d]
    E = s.shape[0]
    k = state["src"].shape[0]
    mi = mask.astype(jnp.int32)
    n_valid = mi.sum()
    m0 = edge_count
    N = m0 + n_valid
    key, k_keep, k_sel, k_third = jax.random.split(key, 4)
    u = jax.random.uniform(k_keep, (k,))
    keep = (u < m0.astype(jnp.float32) / jnp.maximum(N, 1).astype(jnp.float32)) | (
        n_valid == 0
    )
    # selected window position, uniform over [0, n_valid)
    r = jax.random.uniform(k_sel, (k,))
    p = jnp.minimum(
        (r * n_valid.astype(jnp.float32)).astype(jnp.int32),
        jnp.maximum(n_valid - 1, 0),
    )
    cum = jnp.cumsum(mi) - 1  # window position of each valid slot
    slot = jnp.clip(jnp.searchsorted(cum, p), 0, E - 1)
    es, ed = s[slot], d[slot]
    # third vertex uniform over [0, V) \ {es, ed} (same formula as the scan)
    u1 = jnp.minimum(es, ed)
    u2 = jnp.maximum(es, ed)
    distinct = u1 != u2
    nv = vertex_count - 1 - distinct.astype(jnp.int32)
    rt = jax.random.uniform(k_third, (k,))
    c0 = jnp.minimum((rt * nv.astype(jnp.float32)).astype(jnp.int32), nv - 1)
    c1 = c0 + (c0 >= u1)
    c = c1 + ((c1 >= u2) & distinct)
    state = {
        "src": jnp.where(keep, state["src"], es),
        "trg": jnp.where(keep, state["trg"], ed),
        "third": jnp.where(keep, state["third"], c),
        "src_found": jnp.where(keep, state["src_found"], False),
        "trg_found": jnp.where(keep, state["trg_found"], False),
    }
    sel_pos = jnp.where(keep, -1, p)
    # last-occurrence window position per canonical pair
    big = jnp.iinfo(jnp.int32).max
    ck = jnp.where(
        mask, jnp.minimum(s, d) * vertex_count + jnp.maximum(s, d), big
    )
    pos = jnp.where(mask, cum, -1)
    sk, sp = jax.lax.sort((ck, pos), num_keys=2)

    def last_pos_of(a, b):
        q = jnp.minimum(a, b) * vertex_count + jnp.maximum(a, b)
        right = jnp.searchsorted(sk, q, side="right") - 1
        rc = jnp.clip(right, 0, E - 1)
        ok = (right >= 0) & (sk[rc] == q)
        return jnp.where(ok, sp[rc], -1)

    state["src_found"] = state["src_found"] | (
        last_pos_of(state["src"], state["third"]) > sel_pos
    )
    state["trg_found"] = state["trg_found"] | (
        last_pos_of(state["trg"], state["third"]) > sel_pos
    )
    beta_sum = (state["src_found"] & state["trg_found"]).sum()
    return state, N, key, beta_sum


class BroadcastTriangleCount:
    """Global triangle-count estimate from k reservoir samples.

    ``run(edges)`` yields ``(edge_count, estimate)`` per window when the
    estimate changed (the reference's change-only emission,
    ``BroadcastTriangleCount.java:163-170``). Defaults mirror the
    reference's CLI defaults (``:216-217``).
    """

    def __init__(
        self,
        vertex_count: int = 1000,
        samples: int = 10000,
        window: Optional[WindowPolicy] = None,
        seed: int = 0,
    ):
        if vertex_count < 3:
            raise ValueError("need at least 3 vertices to form a triangle")
        self.vertex_count = vertex_count
        self.samples = samples
        self.window = window or CountWindow(1 << 14)
        self._key = jax.random.PRNGKey(seed)
        self._state = init_sampler_state(samples)
        self._edge_count = jnp.int32(0)
        self._previous = 0  # the reference never emits the initial 0

    def state_dict(self) -> dict:
        """Checkpoint surface (``aggregate/checkpoint.py:save_workload``)."""
        import jax as _jax

        return {
            "state": _jax.tree.map(np.asarray, self._state),
            "edge_count": int(self._edge_count),
            "key": np.asarray(self._key),
            "previous": self._previous,
        }

    def load_state_dict(self, d: dict) -> None:
        self._state = jax.tree.map(jnp.asarray, d["state"])
        self._edge_count = jnp.int32(d["edge_count"])
        self._key = jnp.asarray(d["key"])
        self._previous = d["previous"]

    def run(self, edges: Iterable[Tuple]) -> Iterator[Tuple[int, int]]:
        windower = Windower(self.window)
        # the vectorized window update needs the canonical pair key to fit
        # int32; enormous id spaces fall back to the sequential scan
        vectorized = self.vertex_count <= _PACK_LIMIT
        host_edge_count = int(self._edge_count)
        for block in windower.blocks(edges):
            if vectorized:
                # one dispatch per window: compact->raw mapping happens on
                # device via the dict's cached raw table; the only per-
                # window host sync is reading beta_sum for the change-only
                # emission decision
                self._state, self._edge_count, self._key, beta_sum = (
                    _window_vectorized(
                        self._state, self._edge_count, self._key,
                        (block.src, block.dst), block.mask,
                        self.vertex_count,
                        table=windower.vertex_dict.raw_table(),
                    )
                )
            else:
                s = jnp.asarray(
                    windower.vertex_dict.decode(
                        np.asarray(block.src)
                    ).astype(np.int32)
                )
                d = jnp.asarray(
                    windower.vertex_dict.decode(
                        np.asarray(block.dst)
                    ).astype(np.int32)
                )
                self._state, self._edge_count, self._key, beta_sum = (
                    _window_scan(
                        self._state, self._edge_count, self._key, (s, d),
                        block.mask, self.vertex_count,
                    )
                )
            cache = getattr(block, "_host_cache", None)
            host_edge_count += (
                len(cache[0]) if cache is not None
                else int(np.asarray(block.mask).sum())
            )
            beta = int(beta_sum)
            self._last_beta = beta
            estimate = int(
                (1.0 / self.samples)
                * beta
                * host_edge_count
                * (self.vertex_count - 2)
            )
            if estimate != self._previous:
                self._previous = estimate
                yield host_edge_count, estimate

    def run_estimates(self, edges: Iterable[Tuple]):
        """``run()`` with typed emissions: yields the
        :class:`~gelly_streaming_tpu.utils.types.TriangleEstimate` partial
        behind each change-only emission — the record the reference's
        samplers route to their collector (``util/TriangleEstimate.java``,
        ``BroadcastTriangleCount.java:150-170``). ``source`` is 0: the
        vectorized estimator is one logical subtask."""
        from ..utils.types import TriangleEstimate

        for edge_count, _ in self.run(edges):
            yield TriangleEstimate(
                source=0, edge_count=edge_count,
                beta=getattr(self, "_last_beta", 0),
            )

    def sampled_edges(self) -> list:
        """The current reservoir as typed
        :class:`~gelly_streaming_tpu.utils.types.SampledEdge` records
        (``util/SampledEdge.java``): one per occupied sample instance.
        ``resample`` is False — the vectorized reservoir replaces edges in
        place rather than routing resample messages between subtasks."""
        from ..core.types import Edge
        from ..utils.types import SampledEdge

        src = np.asarray(self._state["src"])
        trg = np.asarray(self._state["trg"])
        n = int(self._edge_count)
        return [
            SampledEdge(
                subtask=0, instance=int(i), edge=Edge(int(s), int(t), None),
                edge_count=n, resample=False,
            )
            for i, (s, t) in enumerate(zip(src.tolist(), trg.tolist()))
            if s >= 0
        ]


class IncidenceSamplingTriangleCount(BroadcastTriangleCount):
    """Incidence-routed flavor (``IncidenceSamplingTriangleCount.java``).

    The reference version differs from the broadcast one only in HOW edges
    reach the sample states (centralized coin flips + keyed routing of
    sampled/incident edges instead of broadcast) — a Flink network
    optimization with no device analog; the estimator itself, and hence
    this implementation, is identical.
    """
