"""Streaming weighted matching (1/6-approximation, McGregor-style).

TPU-native placement decision, same as the reference's: this algorithm is
inherently sequential — one global matching updated per edge — and the
reference runs it as a parallelism-1 flatMap
(``example/CentralizedWeightedMatching.java:56-108``). SURVEY.md §7 keeps it
host-resident; there is no batched/device formulation that preserves the
per-edge replace-iff ``w > 2·Σw(collisions)`` semantics.

One improvement over the reference: collisions are found through an
endpoint -> matched-edge index (each vertex is in at most one matched edge),
so each arrival is O(1) instead of the reference's linear scan over the
whole matching (``:80-88``).
"""

from __future__ import annotations

import enum
from typing import Iterable, Iterator, NamedTuple, Tuple

from ..core.types import Edge


class MatchingEventType(enum.Enum):
    """``util/MatchingEvent.java:26`` Type {ADD, REMOVE}."""

    ADD = "add"
    REMOVE = "remove"


class MatchingEvent(NamedTuple):
    """``util/MatchingEvent.java:24-42``."""

    type: MatchingEventType
    edge: Edge


class CentralizedWeightedMatching:
    """Maintain a weighted matching over the edge stream.

    ``run(edges)`` consumes ``(src, dst, weight)`` records (or a
    ``SimpleEdgeStream``) and yields :class:`MatchingEvent`s: a new edge
    replaces its colliding matched edges iff its weight exceeds twice their
    weight sum (the 1/6-approximation rule, ``:95-107``).
    """

    def __init__(self):
        self._by_vertex: dict = {}  # vertex -> matched Edge

    def run(self, edges) -> Iterator[MatchingEvent]:
        for s, d, w in _records(edges):
            edge = Edge(s, d, w)
            collisions = {
                id(e): e
                for e in (self._by_vertex.get(s), self._by_vertex.get(d))
                if e is not None
            }.values()
            if w > 2 * sum(e.val for e in collisions):
                for e in collisions:
                    self._by_vertex.pop(e.src, None)
                    self._by_vertex.pop(e.dst, None)  # same key for self-loops
                    yield MatchingEvent(MatchingEventType.REMOVE, e)
                self._by_vertex[s] = edge
                self._by_vertex[d] = edge
                yield MatchingEvent(MatchingEventType.ADD, edge)

    def state_dict(self) -> dict:
        """Checkpoint surface (``aggregate/checkpoint.py:save_workload``)."""
        return {"by_vertex": dict(self._by_vertex)}

    def load_state_dict(self, d: dict) -> None:
        self._by_vertex = dict(d["by_vertex"])

    def matching(self) -> set:
        """The current matched edge set."""
        return {e for e in self._by_vertex.values()}

    def total_weight(self) -> float:
        return sum(e.val for e in self.matching())


def _records(edges) -> Iterable[Tuple]:
    if hasattr(edges, "get_edges"):  # SimpleEdgeStream
        for e in edges.get_edges():
            yield (e.src, e.dst, e.val)
    else:
        for s, d, w, *_ in edges:
            yield (s, d, w)
