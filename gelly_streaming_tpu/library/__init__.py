from .connected_components import ConnectedComponents, ConnectedComponentsTree
from .bipartiteness import BipartitenessCheck
from .spanner import DeviceSpanner, Spanner
from .triangles import ExactTriangleCount, WindowTriangles
from .degrees import DegreeDistribution
from .sampling import BroadcastTriangleCount, IncidenceSamplingTriangleCount
from .matching import (
    CentralizedWeightedMatching,
    MatchingEvent,
    MatchingEventType,
)
from .iterative_cc import IterativeConnectedComponents
from .pagerank import IncrementalPageRank
