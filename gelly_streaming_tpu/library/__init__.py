from .connected_components import ConnectedComponents, ConnectedComponentsTree
from .bipartiteness import BipartitenessCheck
from .spanner import Spanner
from .triangles import ExactTriangleCount, WindowTriangles
