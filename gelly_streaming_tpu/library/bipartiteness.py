"""Streaming bipartiteness check via the signed double cover.

Replaces ``library/BipartitenessCheck.java:39-133`` + its ``Candidates``
merge machinery with CC over the signed double cover (see
``summaries/candidates.py``): bipartite iff no vertex's (+) and (-) cover
nodes share a component.

Three carries (``carry=`` option, default ``auto`` — the same auto rule
as CC: the host union-find where the native toolchain runs on a CPU
backend, the device forest where an accelerator is attached):

- **Host cover union-find** (auto default on a CPU backend with the
  native toolchain): the CC host carry applied to the double cover —
  every window's edges expand to cover edges ((u,+)~(v,-), (u,-)~(v,+))
  and fold through the SAME native ``CompactUnionFind`` over 2*vcap
  cover ids (one ``cuf_fold_group`` call per superbatch group), with a
  device pointer-forest mirror and the odd-cycle latch checked on host
  from each window's touched delta (both cover nodes of every endpoint
  are touched, so sibling-root equality over the delta witnesses every
  new conflict). Union-find is control flow, not math — the P6
  placement rationale, same as CC.
- **Cover forest** (auto default with an accelerator attached): the
  round-5 window-local treatment — a pointer forest over the 2*vcap
  cover ids updated by window-sized kernels, with the odd-cycle latch
  computed in-step from the touched lanes' sibling roots and carried on
  device (zero mid-stream D2H; the cover component containing a
  conflict is sign-symmetric, so touched lanes alone witness every new
  conflict). Per-window cost scales with the window, not the vertex
  space — the same redesign that took CC from 0.45x to 2.4x the
  compiled baseline on the CPU bracket.
- **Dense cover labels**: the full-table fixpoint + pointer-graph
  combine, used under a sharded mesh and for device-transformed streams
  (the windowed carries' touched set is host-computed). Downgrade is
  one canonicalization; checkpoints share one format (flat cover
  labels + touched), so the carries are cross-restorable.

``superbatch=K`` fuses K windows into one group fold on every carry
(the ISSUE 14 ``GroupFoldable`` declaration): the host carry folds the
group's cover edges in ONE native call with one batched mirror commit
(the CC ``_host_group`` shape — the CPU fast path), the forest carry
runs the group-local fused cover scan (the accelerator shape — on CPU
its group-sized carried label table costs more than it saves), and
dense mode scans the group through the generic engine.

Emission reproduces the reference's ``(true,{...})`` / ``(false,{})``
output format in every carry.
"""

from __future__ import annotations

from typing import Any, Iterator, Optional

import jax.numpy as jnp
import numpy as np

from ..aggregate.summary import SummaryBulkAggregation
from ..obs import trace as _trace
from ..summaries.candidates import (
    Candidates,
    cover_fold,
    cover_forest_superbatch,
    cover_forest_window,
    cover_grow,
    cover_grow_forest,
    init_cover,
)
from ..summaries.candidates import _shift_cover_labels
from ..summaries.forest import (
    MirrorReplay,
    TouchLog,
    WindowPrep,
    mirror_update,
    resolve_flat,
    resolve_flat_host,
)
from ..summaries.groupfold import drive_group_folded
from ..summaries.labels import label_combine
from .connected_components import _auto_carry


def _cover_cols(src: np.ndarray, dst: np.ndarray, vcap: int):
    """Expand one window's base edge columns to the signed-cover edge
    columns ((u,+)~(v,-) and (u,-)~(v,+)) for the host union-find."""
    s = np.asarray(src, np.int32)
    d = np.asarray(dst, np.int32)
    return (
        np.concatenate([s, s + vcap]),
        np.concatenate([d + vcap, d]),
    )


def _delta_conflict(t: np.ndarray, r: np.ndarray, vcap: int) -> bool:
    """Odd-cycle check over ONE window's union-find touched delta
    ``(ids, roots)``: does any base endpoint's sibling share its root?
    Complete for NEW conflicts because both cover nodes of every window
    endpoint are touched (the cover fold adds both edges) and a
    conflict's merged component is sign-symmetric — its window-touched
    members witness it."""
    base = t[t < vcap]
    if not len(base):
        return False
    order = np.argsort(t)
    ts, rs = t[order], r[order]
    rb = rs[np.searchsorted(ts, base)]
    rn = rs[np.searchsorted(ts, base + vcap)]
    return bool(np.any(rb == rn))


class BipartitenessCheck(SummaryBulkAggregation):
    """Single-pass bipartiteness (``library/BipartitenessCheck.java``)."""

    def __init__(self, *args, carry: str = "auto", **kwargs):
        super().__init__(*args, **kwargs)
        if carry not in ("auto", "forest", "host", "dense"):
            raise ValueError(
                f"carry must be auto/forest/host/dense, got {carry!r}"
            )
        self.carry = carry
        self._bp_mode = None  # None | "forest" | "host" | "dense"
        self._canon = None    # cover forest int32[2*vcap] (device mirror)
        self._failed = None   # odd-cycle latch: device bool (forest) /
        #                       host bool (host carry)
        self._log = None      # host TouchLog over BASE ids
        self._prep = None     # WindowPrep scratch (forest carry)
        self._uf = None       # native CompactUnionFind over cover ids

    @classmethod
    def sliding(cls, size: int, slide=None, **kwargs):
        """The EVENT-TIME shape of this workload: bipartiteness over a
        sliding window, the odd-cycle latch RE-RESOLVED when panes
        expire (ISSUE 18) — a configured
        :class:`~gelly_streaming_tpu.eventtime.SlidingGraphAggregator`
        restricted to the cover summary. ``size``/``slide`` are event
        time units; extra kwargs pass through (``allowed_lateness``,
        ``nshards``, ``commit_dir``, ...)."""
        from ..eventtime import SlidingGraphAggregator

        return SlidingGraphAggregator(
            size, slide, summaries=("bipartite",), **kwargs
        )

    # ---- dense-engine hooks (mesh / device-transformed fallback) ---- #
    def initial_state(self, vcap: int):
        return init_cover(max(1, vcap))

    def grow_state(self, state, old_vcap: int, new_vcap: int):
        return cover_grow(state, old_vcap, new_vcap)

    def update(self, state, src, dst, val, mask):
        vcap = state["labels"].shape[0] // 2
        return cover_fold(state, src, dst, mask, vcap)

    def combine(self, a, b):
        return label_combine(a, b)

    def infer_vcap(self, state) -> int:
        # the cover table has 2*vcap rows
        return state["labels"].shape[0] // 2

    def transform(self, state, vdict) -> Candidates:
        return Candidates.from_cover(state, self.infer_vcap(state), vdict)

    # ---- cover-forest run loop (round 5) ---- #
    def run(self, stream) -> Iterator[Candidates]:
        mesh = self._resolve_mesh(stream)
        vdict = stream.vertex_dict
        k = int(getattr(self, "superbatch", 1) or 1)
        if (k > 1 or self.superbatch_auto) and not self.transient_state:
            # the fused K-window drive loop (the GroupFoldable
            # declaration); transient_state keeps the per-window loop —
            # its per-yield carry reset is window-granular by definition
            self._gf_mesh = mesh
            self._gf_vdict = vdict
            yield from drive_group_folded(
                self, stream, k, controller=self._attach_control(k)
            )
            return
        for block in stream.blocks():
            cache = getattr(block, "_host_cache", None)
            if (
                mesh is not None
                or cache is None
                or self.carry == "dense"
                or self._bp_mode == "dense"
            ):
                if self._bp_mode in ("forest", "host"):
                    self._to_dense()
                self._bp_mode = "dense"
                self._device_block(block, mesh)
                self._sync_ref = self._summary
                yield self.transform(self._summary, vdict)
            else:
                if self._bp_mode is None:
                    self._bp_mode = (
                        self.carry if self.carry != "auto"
                        else _auto_carry()
                    )
                self._ensure_forest(block.n_vertices)
                if self._bp_mode == "host":
                    yield self._host_window(cache[0], cache[1], vdict)
                else:
                    self._canon, self._failed, tids = cover_forest_window(
                        self._canon, self._failed, cache[0], cache[1],
                        self._vcap, self._prep,
                    )
                    # the log tracks BASE ids only; the negative cover
                    # half derives as base + vcap at emission/checkpoint
                    # time, so growth never needs a log rebuild and held
                    # emissions cannot leak grown ids into the negative
                    # half
                    self._log.add(tids)
                    self._summary = {"labels": self._canon}
                    self._sync_ref = (self._canon, self._failed)
                    yield Candidates.from_forest(
                        self._canon, self._failed, self._log,
                        self._log.count, self._vcap, vdict,
                    )
            if self.transient_state:
                self._reset_transient()

    def _host_window(self, src_h, dst_h, vdict) -> Candidates:
        """One window through the host cover union-find: fold both cover
        edges per base edge, mirror the delta to the device forest, and
        advance the odd-cycle latch from the window's touched delta."""
        vcap = self._vcap
        s2, d2 = _cover_cols(src_h, dst_h, vcap)
        t, r, c, cr = self._uf.fold(s2, d2, 2 * vcap)
        self._canon = mirror_update(
            self._canon,
            np.concatenate([t, c]),
            np.concatenate([r, cr]),
            2 * vcap,
        )
        if not self._failed:
            self._failed = _delta_conflict(t, r, vcap)
        self._log.add(t[t < vcap])
        self._summary = {"labels": self._canon}
        self._sync_ref = self._canon
        return Candidates.from_forest(
            self._canon, self._failed, self._log, self._log.count,
            vcap, vdict,
        )

    # ---- GroupFoldable declaration (summaries/groupfold.py) ---------- #
    def fold_group(self, group) -> Iterator[Candidates]:
        """The cover carry's declared group fold: the host carry folds
        the group's cover edges in ONE native union-find call with one
        batched mirror commit (:meth:`_host_group` — the CPU fast path,
        the CC ``_host_group`` shape); the forest carry runs ONE fused
        group-local cover dispatch
        (:func:`~gelly_streaming_tpu.summaries.candidates.cover_forest_superbatch`
        — one 2*vcap chase/commit per GROUP, a scan over group-local
        cover label tables with the per-window conflict latch riding the
        carry). Mid-group canons reconstruct lazily on first read.
        Groups without host column views — and sharded meshes, whose
        cover fold runs the dense engine — downgrade to dense, exactly
        like the per-window loop."""
        mesh, vdict = self._gf_mesh, self._gf_vdict
        windowed = (
            mesh is None
            and group.cols is not None
            and self.carry != "dense"
            and self._bp_mode != "dense"
        )
        if not windowed:
            if self._bp_mode in ("forest", "host"):
                self._to_dense()
            self._bp_mode = "dense"
            for state in self._fold_group_states(group, mesh):
                yield self.transform(state, vdict)
            return
        if self._bp_mode is None:
            self._bp_mode = (
                self.carry if self.carry != "auto" else _auto_carry()
            )
        if self._bp_mode == "host":
            yield from self._host_group(group, vdict)
            return
        # span covers the fold dispatch + log advance, NOT the lazy
        # per-window emissions reconstructed later on first read
        with _trace.span(
            "bp.cover_group",
            {"k": len(group), "n_vertices": int(group.n_vertices)}
            if _trace.on() else None,
        ):
            self._ensure_forest(group.n_vertices)
            windows = [(c[0], c[1]) for c in group.cols]
            (self._canon, self._failed, tids_list, replay,
             fail_s) = cover_forest_superbatch(
                self._canon, self._failed, windows, self._vcap,
                self._prep,
            )
            counts = []
            for tids in tids_list:
                self._log.add(tids)
                counts.append(self._log.count)
            self._summary = {"labels": self._canon}
            self._sync_ref = (self._canon, self._failed)
        for i, count in enumerate(counts):
            yield Candidates.from_forest_replay(
                replay, i, fail_s, self._log, count, self._vcap, vdict
            )

    def _host_group(self, group, vdict) -> Iterator[Candidates]:
        """Host-carry superbatch: K windows' cover edges in ONE native
        ``cuf_fold_group`` call, one numpy group commit on the device
        mirror (the CC host-group contract: the published canon is a
        fresh immutable buffer per group), per-window odd-cycle latches
        resolved lazily — the end-of-group state answers the whole group
        when the verdict does not flip inside it (the monotone-latch
        fast path; a flip resolves per window from the deltas the
        union-find computed anyway, at most once per run)."""
        with _trace.span(
            "bp.cover_host_group",
            {"k": len(group), "n_vertices": int(group.n_vertices)}
            if _trace.on() else None,
        ):
            self._ensure_forest(group.n_vertices)
            vcap = self._vcap
            cover_cols = [
                _cover_cols(c[0], c[1], vcap) for c in group.cols
            ]
            wins, gids, groots, gtcnt = self._uf.fold_group(
                cover_cols, 2 * vcap
            )
            ngt = int(np.sum(gtcnt))
            # base-only grouped log advance: filter the group-unique
            # touched prefix to the base half, preserving window order
            gt = gids[:ngt]
            base_mask = gt < vcap
            ends = np.cumsum(np.asarray(gtcnt, np.int64))
            starts = np.concatenate([[0], ends[:-1]])
            counts_base = [
                int(base_mask[a:b].sum()) for a, b in zip(starts, ends)
            ]
            counts = self._log.add_grouped(
                gt[base_mask], np.asarray(counts_base, np.int64)
            )
            base_np = np.asarray(self._canon)  # zero-copy view on CPU
            new_np = base_np.copy()
            new_np[gids] = groots
            self._canon = jnp.asarray(new_np)
            replay = MirrorReplay(base_np, wins)
            fails = self._host_group_fails(wins, new_np, gt, vcap)
            self._summary = {"labels": self._canon}
            self._sync_ref = self._canon
        for i, count in enumerate(counts):
            yield Candidates.from_forest_replay(
                replay, i, fails, self._log, count, vcap, vdict
            )

    def _host_group_fails(self, wins, end_np, gt, vcap: int) -> list:
        """Per-window odd-cycle latch values for one host group. The
        latch is monotone, so only a group containing the flip needs
        per-window resolution (from the per-window deltas); every other
        group answers from the carried latch or the end-of-group roots
        (``end_np[id]`` IS the post-group root for every re-rooted id —
        ``cuf_fold_group``'s group delta contract)."""
        k = len(wins)
        if self._failed:
            return [True] * k
        base_g = gt[gt < vcap]
        end_conflict = bool(
            len(base_g)
            and np.any(end_np[base_g] == end_np[base_g + vcap])
        )
        if not end_conflict:
            return [False] * k
        fails = []
        failed = False
        for t, r, _c, _cr in wins:
            if not failed:
                failed = _delta_conflict(t, r, vcap)
            fails.append(failed)
        self._failed = failed
        return fails

    def checkpoint_granularity(self) -> int:
        """Like the CC mixin: superbatching (and thus group-aligned
        barriers) is skipped under ``transient_state``."""
        return 1 if self.transient_state else super().checkpoint_granularity()

    def _ensure_forest(self, vcap: int) -> None:
        host = self._bp_mode == "host"
        if self._canon is None:
            if self._summary is not None and "touched" in self._summary:
                # restored (or converted) dense state: flat cover labels
                # ARE a valid forest; the latch recomputes from the truth
                lab = np.asarray(self._summary["labels"])
                tch = np.asarray(self._summary["touched"])
                self._vcap = len(lab) // 2
                self._canon = jnp.asarray(lab.astype(np.int32))
                self._log = TouchLog(self._vcap)
                base = np.nonzero(tch[: self._vcap])[0].astype(np.int32)
                self._log.add(base)
                flat = resolve_flat_host(lab.astype(np.int32))
                failed = (
                    bool(np.any(flat[base] == flat[base + self._vcap]))
                    if len(base) else False
                )
            else:
                self._vcap = vcap
                self._canon = jnp.arange(2 * vcap, dtype=jnp.int32)
                self._log = TouchLog(vcap)
                failed = False
            self._failed = failed if host else jnp.bool_(failed)
            if host:
                from .. import native

                self._uf = native.CompactUnionFind()
                self._uf.load(np.asarray(self._canon))
            else:
                self._prep = WindowPrep()
        if vcap > self._vcap:
            if host:
                # the cover re-index rule applies to the union-find's
                # table too: flatten, shift the negative half, reload
                shifted = _shift_cover_labels(
                    self._uf.flatten(2 * self._vcap), self._vcap, vcap
                )
                self._uf.load(shifted)
                self._canon = jnp.asarray(shifted)
            else:
                self._canon = cover_grow_forest(
                    self._canon, self._vcap, vcap
                )
            # base-only log: base ids never shift on growth
            self._vcap = vcap
        self._log.grow(self._vcap)

    def _to_dense(self) -> None:
        if self._bp_mode == "host":
            flat = jnp.asarray(self._uf.flatten(2 * self._vcap))
        else:
            flat = resolve_flat(self._canon)
        touched2 = np.zeros(2 * self._vcap, bool)
        touched2[: self._vcap] = self._log.touched_bool(self._vcap)
        self._summary = {"labels": flat, "touched": jnp.asarray(touched2)}
        self._canon = None
        self._failed = None
        self._log = None
        self._prep = None
        self._uf = None

    def _reset_transient(self) -> None:
        if self._bp_mode in ("forest", "host"):
            self._canon = jnp.arange(2 * self._vcap, dtype=jnp.int32)
            self._log = TouchLog(self._vcap)
            self._summary = {"labels": self._canon}
            if self._bp_mode == "host":
                self._failed = False
                self._uf.load(np.arange(2 * self._vcap, dtype=np.int32))
            else:
                self._failed = jnp.bool_(False)
        else:
            self._summary = self.initial_state(self._vcap)

    # ---- checkpoint surface: one format for all carries ---- #
    def snapshot_state(self) -> Any:
        if self._bp_mode in ("forest", "host"):
            if self._bp_mode == "host":
                lab = self._uf.flatten(2 * self._vcap)
            else:
                lab = resolve_flat_host(np.asarray(self._canon))
            touched2 = np.zeros(2 * self._vcap, bool)
            touched2[: self._vcap] = self._log.touched_bool(self._vcap)
            return {"labels": lab, "touched": touched2}
        return super().snapshot_state()

    def restore_state(self, state: Any, vcap: Optional[int] = None) -> None:
        super().restore_state(state, vcap)
        self._bp_mode = None
        self._canon = None
        self._failed = None
        self._log = None
        self._prep = None
        self._uf = None

    # ---- serving surface (serving/server.py Servable contract) ------- #
    def servable(self, vdict=None) -> "BipartitenessServable":
        """Adapter publishing the live cover table per window for
        :class:`~gelly_streaming_tpu.serving.query.BipartiteQuery`
        (typed yes/no + odd-cycle conflict witness). ``vdict`` seeds the
        boot payload when restoring from a checkpoint before any stream
        is attached."""
        return BipartitenessServable(self, vdict)


class BipartitenessServable:
    """:class:`~gelly_streaming_tpu.serving.server.Servable` adapter for
    :class:`BipartitenessCheck`. Every carry publishes the 2*vcap cover
    table per window — the live cover pointer forest (forest carry: each
    window's functional scatter leaves the published buffer immutable)
    or the dense flat cover labels — plus touch evidence for the seen
    set: the forest carry ships its append-only log by reference and
    COUNT (the first ``tcount`` entries never change, so the published
    view is a valid snapshot with zero per-publish O(vcap) work), the
    dense carry its ``touched`` table. The
    :class:`~gelly_streaming_tpu.serving.query.QueryEngine` recomputes
    the verdict + witness from the cover structure, so a query never
    trusts a carried latch.

    SUPERBATCH GRANULARITY: with ``superbatch=K`` the published cover
    is the END-of-group state for all K publishes — safe (the cover
    merge is monotone: a query sees a FRESHER verdict, never a wrong
    one; bipartite->non-bipartite only ever flips forward), with the
    same group-granular snapshot caveat as ``CCServable``."""

    def __init__(self, agg, vdict=None):
        from ..serving import BipartiteQuery

        self.query_classes = (BipartiteQuery,)
        self._agg = agg
        self._vdict = vdict

    def _payload(self, vdict) -> Optional[dict]:
        agg = self._agg
        if agg._bp_mode in ("forest", "host") and agg._canon is not None:
            return {
                "cover": agg._canon,
                "tids": agg._log.ids,
                "tcount": agg._log.count,
                "vdict": vdict,
            }
        if (
            agg._summary is not None
            and "labels" in agg._summary
            and "touched" in agg._summary
        ):
            labels = agg._summary["labels"]
            if agg._donated_carry:
                # dense superbatch carries are DONATED to the next
                # group's dispatch — published snapshots must own
                # their buffer (the CCServable rule)
                labels = jnp.array(labels)
            # count-snapshotted novelty shadow, same interface as the
            # forest carry (and CCServable): the engine's delta-pull
            # diff keys on tids[:tcount] whichever carry published
            log = TouchLog.from_touched_bool(
                np.asarray(agg._summary["touched"])
            )
            return {
                "cover": labels,
                "touched": agg._summary["touched"],
                "tids": log.ids,
                "tcount": log.count,
                "vdict": vdict,
            }
        return None

    def payloads(self, stream):
        vdict = stream.vertex_dict
        self._vdict = vdict
        window = 0
        for _ in self._agg.run(stream):
            window += 1
            payload = self._payload(vdict)
            if payload is None:  # carry not inspectable this window
                continue
            yield payload, window

    def boot_payload(self):
        """The restored summary as a servable payload (None when nothing
        was restored yet, or no vdict is known)."""
        if self._vdict is None:
            return None
        payload = self._payload(self._vdict)
        if payload is None:
            return None
        return payload, 0
