"""Streaming bipartiteness check via the signed double cover.

Replaces ``library/BipartitenessCheck.java:39-133`` + its ``Candidates``
merge machinery with CC over the signed double cover (see
``summaries/candidates.py``): bipartite iff no vertex's (+) and (-) cover
nodes share a component. The update/combine are the same dense label kernels
as CC, over a 2*vcap table; emission reproduces the reference's
``(true,{...})`` / ``(false,{})`` output format.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..aggregate.summary import SummaryBulkAggregation
from ..summaries.candidates import Candidates, cover_fold, cover_grow, init_cover
from ..summaries.labels import label_combine


class BipartitenessCheck(SummaryBulkAggregation):
    """Single-pass bipartiteness (``library/BipartitenessCheck.java``)."""

    def initial_state(self, vcap: int):
        return init_cover(max(1, vcap))

    def grow_state(self, state, old_vcap: int, new_vcap: int):
        return cover_grow(state, old_vcap, new_vcap)

    def update(self, state, src, dst, val, mask):
        vcap = state["labels"].shape[0] // 2
        return cover_fold(state, src, dst, mask, vcap)

    def combine(self, a, b):
        return label_combine(a, b)

    def infer_vcap(self, state) -> int:
        # the cover table has 2*vcap rows
        return state["labels"].shape[0] // 2

    def transform(self, state, vdict) -> Candidates:
        return Candidates.from_cover(state, self.infer_vcap(state), vdict)
