"""Streaming bipartiteness check via the signed double cover.

Replaces ``library/BipartitenessCheck.java:39-133`` + its ``Candidates``
merge machinery with CC over the signed double cover (see
``summaries/candidates.py``): bipartite iff no vertex's (+) and (-) cover
nodes share a component.

Two carries (``carry=`` option, default ``auto``):

- **Cover forest** (auto default on the single-device ingest path): the
  round-5 window-local treatment — a pointer forest over the 2*vcap
  cover ids updated by window-sized kernels, with the odd-cycle latch
  computed in-step from the touched lanes' sibling roots and carried on
  device (zero mid-stream D2H; the cover component containing a
  conflict is sign-symmetric, so touched lanes alone witness every new
  conflict). Per-window cost scales with the window, not the vertex
  space — the same redesign that took CC from 0.45x to 2.4x the
  compiled baseline on the CPU bracket.
- **Dense cover labels**: the full-table fixpoint + pointer-graph
  combine, used under a sharded mesh and for device-transformed streams
  (the forest's touched set is host-computed). Downgrade is one
  canonicalization; checkpoints share one format (flat cover labels +
  touched), so the carries are cross-restorable.

Emission reproduces the reference's ``(true,{...})`` / ``(false,{})``
output format in both carries.
"""

from __future__ import annotations

from typing import Any, Iterator, Optional

import jax.numpy as jnp
import numpy as np

from ..aggregate.summary import SummaryBulkAggregation
from ..summaries.candidates import (
    Candidates,
    cover_fold,
    cover_forest_window,
    cover_grow,
    cover_grow_forest,
    init_cover,
)
from ..summaries.forest import TouchLog, WindowPrep, resolve_flat, resolve_flat_host
from ..summaries.labels import label_combine


class BipartitenessCheck(SummaryBulkAggregation):
    """Single-pass bipartiteness (``library/BipartitenessCheck.java``)."""

    def __init__(self, *args, carry: str = "auto", **kwargs):
        super().__init__(*args, **kwargs)
        if carry not in ("auto", "forest", "dense"):
            raise ValueError(f"carry must be auto/forest/dense, got {carry!r}")
        self.carry = carry
        self._bp_mode = None  # None | "forest" | "dense"
        self._canon = None    # cover forest int32[2*vcap]
        self._failed = None   # device bool latch
        self._log = None      # host TouchLog over COVER ids
        self._prep = None

    # ---- dense-engine hooks (mesh / device-transformed fallback) ---- #
    def initial_state(self, vcap: int):
        return init_cover(max(1, vcap))

    def grow_state(self, state, old_vcap: int, new_vcap: int):
        return cover_grow(state, old_vcap, new_vcap)

    def update(self, state, src, dst, val, mask):
        vcap = state["labels"].shape[0] // 2
        return cover_fold(state, src, dst, mask, vcap)

    def combine(self, a, b):
        return label_combine(a, b)

    def infer_vcap(self, state) -> int:
        # the cover table has 2*vcap rows
        return state["labels"].shape[0] // 2

    def transform(self, state, vdict) -> Candidates:
        return Candidates.from_cover(state, self.infer_vcap(state), vdict)

    # ---- cover-forest run loop (round 5) ---- #
    def run(self, stream) -> Iterator[Candidates]:
        mesh = self._resolve_mesh(stream)
        vdict = stream.vertex_dict
        for block in stream.blocks():
            cache = getattr(block, "_host_cache", None)
            if (
                mesh is not None
                or cache is None
                or self.carry == "dense"
                or self._bp_mode == "dense"
            ):
                if self._bp_mode == "forest":
                    self._to_dense()
                self._bp_mode = "dense"
                self._device_block(block, mesh)
                self._sync_ref = self._summary
                yield self.transform(self._summary, vdict)
            else:
                self._bp_mode = "forest"
                self._ensure_forest(block.n_vertices)
                self._canon, self._failed, tids = cover_forest_window(
                    self._canon, self._failed, cache[0], cache[1],
                    self._vcap, self._prep,
                )
                # the log tracks BASE ids only; the negative cover half
                # derives as base + vcap at emission/checkpoint time, so
                # growth never needs a log rebuild and held emissions
                # cannot leak grown ids into the negative half
                self._log.add(tids)
                self._summary = {"labels": self._canon}
                self._sync_ref = (self._canon, self._failed)
                yield Candidates.from_forest(
                    self._canon, self._failed, self._log, self._log.count,
                    self._vcap, vdict,
                )
            if self.transient_state:
                self._reset_transient()

    def _ensure_forest(self, vcap: int) -> None:
        if self._canon is None:
            if self._summary is not None and "touched" in self._summary:
                # restored (or converted) dense state: flat cover labels
                # ARE a valid forest; the latch recomputes from the truth
                lab = np.asarray(self._summary["labels"])
                tch = np.asarray(self._summary["touched"])
                self._vcap = len(lab) // 2
                self._canon = jnp.asarray(lab.astype(np.int32))
                self._log = TouchLog(self._vcap)
                base = np.nonzero(tch[: self._vcap])[0].astype(np.int32)
                self._log.add(base)
                flat = resolve_flat_host(lab.astype(np.int32))
                self._failed = jnp.bool_(
                    bool(np.any(flat[base] == flat[base + self._vcap]))
                    if len(base) else False
                )
            else:
                self._vcap = vcap
                self._canon = jnp.arange(2 * vcap, dtype=jnp.int32)
                self._failed = jnp.bool_(False)
                self._log = TouchLog(vcap)
            self._prep = WindowPrep()
        if vcap > self._vcap:
            self._canon = cover_grow_forest(self._canon, self._vcap, vcap)
            # base-only log: base ids never shift on growth
            self._vcap = vcap
        self._log.grow(self._vcap)

    def _to_dense(self) -> None:
        flat = resolve_flat(self._canon)
        touched2 = np.zeros(2 * self._vcap, bool)
        touched2[: self._vcap] = self._log.touched_bool(self._vcap)
        self._summary = {"labels": flat, "touched": jnp.asarray(touched2)}
        self._canon = None
        self._failed = None
        self._log = None
        self._prep = None

    def _reset_transient(self) -> None:
        if self._bp_mode == "forest":
            self._canon = jnp.arange(2 * self._vcap, dtype=jnp.int32)
            self._failed = jnp.bool_(False)
            self._log = TouchLog(self._vcap)
            self._summary = {"labels": self._canon}
        else:
            self._summary = self.initial_state(self._vcap)

    # ---- checkpoint surface: one format for both carries ---- #
    def snapshot_state(self) -> Any:
        if self._bp_mode == "forest":
            lab = resolve_flat_host(np.asarray(self._canon))
            touched2 = np.zeros(2 * self._vcap, bool)
            touched2[: self._vcap] = self._log.touched_bool(self._vcap)
            return {"labels": lab, "touched": touched2}
        return super().snapshot_state()

    def restore_state(self, state: Any, vcap: Optional[int] = None) -> None:
        super().restore_state(state, vcap)
        self._bp_mode = None
        self._canon = None
        self._failed = None
        self._log = None
        self._prep = None
