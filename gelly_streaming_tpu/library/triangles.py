"""Triangle counting workloads: per-window exact and streaming exact.

TPU-native re-designs of two reference examples:

- :class:`WindowTriangles` — exact triangle count per time slice
  (``example/WindowTriangles.java:60-139``). The reference generates
  O(Σdeg²) wedge *candidates* per vertex and joins them against real edges
  across two more shuffles; here each slice is one compiled
  sorted-adjacency-intersection step (``ops/triangles.py``), emitting
  ``(count, window_max_timestamp)`` pairs exactly like the reference's
  final ``timeWindowAll().sum(0)`` stream.

- :class:`ExactTriangleCount` — single-pass exact local + global triangle
  count over the whole stream (``example/ExactTriangleCount.java:41-207``).
  The reference pairs per-edge neighborhood snapshots in keyed state so a
  triangle is counted exactly once — when its last edge arrives. Here each
  accumulated edge carries an *arrival rank*; per window, one device step
  counts for every new edge the common neighbors whose closing edges both
  have smaller rank (same once-per-triangle semantics, batched). Duplicate
  edges are dropped (the reference's TreeSet adjacency is likewise
  duplicate-insensitive). Emission is per-window change-only: ``(vertex,
  running_count)`` for every vertex whose count changed, and ``(-1,
  running_total)`` — the reference's ``SumAndEmitCounters`` stream
  (``ExactTriangleCount.java:121-134``) at window granularity
  (SURVEY.md §7 "semantic deltas").
"""

from __future__ import annotations

import functools
from typing import Iterable, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.edgeblock import bucket_capacity
from ..core.window import CountWindow, WindowPolicy, Windower
from ..ops.triangles import (
    ranked_triangle_update,
    sorted_ranked_rows,
    window_triangle_count,
)

GLOBAL_KEY = -1  # the reference's "total" counter vertex id


def _pad(a: np.ndarray, cap: int) -> np.ndarray:
    out = np.zeros(cap, a.dtype)
    out[: len(a)] = a
    return out


@functools.partial(jax.jit, static_argnums=(3, 4))
def _window_step(src, dst, mask, num_vertices: int, max_degree: int):
    return window_triangle_count(src, dst, mask, num_vertices, max_degree)


@functools.partial(jax.jit, static_argnums=(4, 5))
def _rebuild_rows(acc_u, acc_v, acc_rank, acc_mask, num_vertices: int,
                  max_degree: int):
    """Full sorted-row rebuild — used only on checkpoint restore; the
    steady path merges incrementally (:func:`_incremental_step`)."""
    return sorted_ranked_rows(
        acc_u, acc_v, acc_rank, acc_mask, num_vertices, max_degree
    )


_BIG = jnp.iinfo(jnp.int32).max


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def _incremental_step(
    ids, ranks, counts,
    touched, add_ids, add_ranks,
    new_u, new_v, new_rank, new_mask,
):
    """One window of streaming exact triangles, one dispatch.

    ``ids``/``ranks`` are the carried ``[Vcap+1, D]`` sorted-by-id
    neighbor/rank rows of the ACCUMULATED graph (row Vcap is scratch —
    padded ``touched`` slots point there so their writes never land on a
    real vertex). The step (a) merges each touched vertex's new neighbors
    into its row — per-window merge cost scales with the touched set, not
    the accumulated edge count (the round-1 version re-sorted every
    accumulated edge per window) — then (b) counts the triangles closed
    by the new edges via the rank-ordered membership kernel.
    """
    rows = jnp.concatenate([ids[touched], add_ids], axis=1)
    rrk = jnp.concatenate([ranks[touched], add_ranks], axis=1)
    order = jnp.argsort(rows, axis=1)
    D = ids.shape[1]
    rows = jnp.take_along_axis(rows, order, axis=1)[:, :D]
    rrk = jnp.take_along_axis(rrk, order, axis=1)[:, :D]
    ids = ids.at[touched].set(rows)
    ranks = ranks.at[touched].set(rrk)
    counts, delta = ranked_triangle_update(
        ids, ranks, new_u, new_v, new_rank, new_mask, counts
    )
    return ids, ranks, counts, delta


class WindowTriangles:
    """Exact triangles per tumbling window.

    ``run(edges)`` yields ``(count, max_timestamp)`` per window —
    ``max_timestamp`` is the inclusive window end for event-time windows
    (Flink's ``TimeWindow.maxTimestamp()``), the window index for count
    windows.
    """

    def __init__(self, window: WindowPolicy):
        self.window = window

    def run(self, edges: Iterable[Tuple]) -> Iterator[Tuple[int, Optional[float]]]:
        windower = Windower(self.window)
        for info, block in windower.blocks_with_info(edges):
            s, d, _ = block.to_host()
            max_deg = _oriented_degree_bucket(s, d, block.n_vertices)
            total, _ = _window_step(
                block.src, block.dst, block.mask, block.n_vertices, max_deg
            )
            ts = info.max_timestamp if info.max_timestamp is not None else info.index
            yield int(total), ts


def _oriented_degree_bucket(s: np.ndarray, d: np.ndarray, num_vertices: int) -> int:
    """Bucket (power of two) covering the max ORIENTED out-degree of the
    window — the dense-row width of the degree-oriented kernel; at most
    ~sqrt(2E) for any degree distribution."""
    u = np.minimum(s, d).astype(np.int64)
    v = np.maximum(s, d).astype(np.int64)
    ok = u != v
    u, v = u[ok], v[ok]
    if u.size == 0:
        return bucket_capacity(0)
    key = np.unique(u * num_vertices + v)
    u = key // num_vertices
    v = key % num_vertices
    deg = np.bincount(u, minlength=num_vertices) + np.bincount(
        v, minlength=num_vertices
    )
    du, dv = deg[u], deg[v]
    swap = (dv < du) | ((dv == du) & (v < u))
    a = np.where(swap, v, u)
    return bucket_capacity(int(np.bincount(a, minlength=num_vertices).max()))


class ExactTriangleCount:
    """Single-pass exact local + global triangle counting.

    ``run(stream)`` consumes a ``SimpleEdgeStream`` and yields, per window, a
    list of ``(raw_vertex_id, running_count)`` for changed vertices plus
    ``(GLOBAL_KEY, running_total)`` when it changed.
    """

    def __init__(self):
        # host carry: canonical accumulated edges in arrival order + dedup key
        self._u = np.zeros(0, np.int32)
        self._v = np.zeros(0, np.int32)
        self._seen_keys = np.zeros(0, np.int64)  # sorted
        self._deg = np.zeros(0, np.int64)
        # device carry: counts [Vcap] + sorted neighbor/rank rows
        # [Vcap+1, Dcap] (last row = scratch for padded scatter indices)
        self._counts = None
        self._ids = None
        self._ranks = None
        self._total = 0

    def run(self, stream) -> Iterator[List[Tuple[int, int]]]:
        vdict = stream.vertex_dict
        for block in stream.blocks():
            s, d, _ = block.to_host()
            vcap = block.n_vertices
            new_u, new_v = self._dedup_new(s, d)
            yield self._process(new_u, new_v, vcap, vdict)

    def state_dict(self) -> dict:
        """Checkpoint surface (``aggregate/checkpoint.py:save_workload``).
        The sorted rows are NOT serialized — they are rebuilt from the
        edge list on restore (one full-build step)."""
        return {
            "u": self._u, "v": self._v, "seen_keys": self._seen_keys,
            "deg": self._deg,
            "counts": None if self._counts is None else np.asarray(self._counts),
            "total": self._total,
        }

    def load_state_dict(self, d: dict) -> None:
        self._u, self._v = d["u"], d["v"]
        self._seen_keys, self._deg = d["seen_keys"], d["deg"]
        self._counts = None if d["counts"] is None else jnp.asarray(d["counts"])
        self._total = int(d["total"])
        self._ids = self._ranks = None
        if self._counts is not None and len(self._u):
            vcap = int(self._counts.shape[0])
            dcap = bucket_capacity(int(self._deg[:vcap].max()))
            n = len(self._u)
            cap = bucket_capacity(n)
            ids, ranks = _rebuild_rows(
                jnp.asarray(_pad(self._u, cap)),
                jnp.asarray(_pad(self._v, cap)),
                jnp.asarray(_pad(np.arange(n, dtype=np.int32), cap)),
                jnp.asarray(np.arange(cap) < n),
                vcap, dcap,
            )
            # append the scratch row
            self._ids = jnp.concatenate(
                [ids, jnp.full((1, dcap), _BIG, jnp.int32)]
            )
            self._ranks = jnp.concatenate(
                [ranks, jnp.zeros((1, dcap), jnp.int32)]
            )

    # ------------------------------------------------------------------ #
    def _dedup_new(self, s: np.ndarray, d: np.ndarray):
        """Canonicalize, drop self-loops and edges seen before (order kept)."""
        u = np.minimum(s, d).astype(np.int64)
        v = np.maximum(s, d).astype(np.int64)
        ok = u != v
        u, v = u[ok], v[ok]
        key = (u << 32) | v
        # in-window first-occurrence dedup, arrival order preserved
        _, first_idx = np.unique(key, return_index=True)
        first_idx.sort()
        u, v, key = u[first_idx], v[first_idx], key[first_idx]
        # drop edges already accumulated
        pos = np.searchsorted(self._seen_keys, key)
        pos_c = np.minimum(pos, max(len(self._seen_keys) - 1, 0))
        dup = (
            (self._seen_keys[pos_c] == key) if len(self._seen_keys) else
            np.zeros(len(key), bool)
        )
        u, v, key = u[~dup], v[~dup], key[~dup]
        self._seen_keys = np.sort(np.concatenate([self._seen_keys, key]))
        return u.astype(np.int32), v.astype(np.int32)

    def _grow(self, vcap: int, dcap: int) -> None:
        """Grow the carried device matrices to [vcap+1, dcap] (scratch row
        last; log-many re-pads over the stream). Appending +INT_MAX columns
        keeps rows sorted; the old scratch row is cleared when it becomes a
        real vertex row."""
        if self._ids is None:
            self._ids = jnp.full((vcap + 1, dcap), _BIG, jnp.int32)
            self._ranks = jnp.zeros((vcap + 1, dcap), jnp.int32)
            return
        old_v = self._ids.shape[0] - 1
        old_d = self._ids.shape[1]
        if old_v == vcap and old_d == dcap:
            return
        ids = self._ids
        ranks = self._ranks
        if dcap > old_d:
            ids = jnp.concatenate(
                [ids, jnp.full((old_v + 1, dcap - old_d), _BIG, jnp.int32)], 1
            )
            ranks = jnp.concatenate(
                [ranks, jnp.zeros((old_v + 1, dcap - old_d), jnp.int32)], 1
            )
        if vcap > old_v:
            ids = jnp.concatenate(
                [ids, jnp.full((vcap - old_v, dcap), _BIG, jnp.int32)]
            )
            ranks = jnp.concatenate(
                [ranks, jnp.zeros((vcap - old_v, dcap), jnp.int32)]
            )
            # the old scratch row (index old_v) is now a real vertex row;
            # it holds junk from padded-slot writes — reset it
            ids = ids.at[old_v].set(jnp.full(dcap, _BIG, jnp.int32))
            ranks = ranks.at[old_v].set(jnp.zeros(dcap, jnp.int32))
        self._ids = ids
        self._ranks = ranks

    @staticmethod
    def _new_rows(new_u, new_v, new_ranks):
        """Host-built per-vertex additions: (touched[T], add_ids[T, Dn],
        add_ranks[T, Dn]) covering both directions of the new edges."""
        key = np.concatenate([new_u, new_v]).astype(np.int64)
        nbr = np.concatenate([new_v, new_u]).astype(np.int32)
        rk = np.concatenate([new_ranks, new_ranks]).astype(np.int32)
        order = np.argsort(key, kind="stable")
        k, nb, rr = key[order], nbr[order], rk[order]
        touched, start = np.unique(k, return_index=True)
        cnt = np.diff(np.append(start, len(k)))
        # floor 16: windows flapping between tiny Dn buckets would
        # recompile the step for negligible memory savings
        dn = bucket_capacity(int(cnt.max()), minimum=16)
        t = len(touched)
        tcap = bucket_capacity(t)
        add_ids = np.full((tcap, dn), np.iinfo(np.int32).max, np.int32)
        add_ranks = np.zeros((tcap, dn), np.int32)
        row = np.repeat(np.arange(t), cnt)
        col = np.arange(len(k)) - np.repeat(start, cnt)
        add_ids[row, col] = nb
        add_ranks[row, col] = rr
        return touched.astype(np.int32), tcap, add_ids, add_ranks

    def _process(self, new_u, new_v, vcap: int, vdict) -> List[Tuple[int, int]]:
        n_old = len(self._u)
        self._u = np.concatenate([self._u, new_u])
        self._v = np.concatenate([self._v, new_v])
        if vcap > len(self._deg):
            self._deg = np.concatenate(
                [self._deg, np.zeros(vcap - len(self._deg), np.int64)]
            )
        np.add.at(self._deg, new_u, 1)
        np.add.at(self._deg, new_v, 1)
        if self._counts is None:
            self._counts = jnp.zeros(vcap, jnp.int32)
        elif vcap > self._counts.shape[0]:
            self._counts = jnp.concatenate(
                [self._counts, jnp.zeros(vcap - self._counts.shape[0], jnp.int32)]
            )
        if len(new_u) == 0:
            return []

        n_acc = len(self._u)
        new_cap = bucket_capacity(len(new_u))
        max_deg = bucket_capacity(int(self._deg[:vcap].max()))
        self._grow(vcap, max_deg)

        new_ranks = np.arange(n_old, n_acc, dtype=np.int32)
        touched, tcap, add_ids, add_ranks = self._new_rows(
            new_u, new_v, new_ranks
        )
        # padded touched slots point at the scratch row (index vcap)
        touched_p = np.full(tcap, vcap, np.int32)
        touched_p[: len(touched)] = touched
        new_mask = np.zeros(new_cap, bool)
        new_mask[: len(new_u)] = True

        # snapshot counts host-side BEFORE dispatch: the device buffer is
        # donated to the step and must not be read afterwards
        old_host = np.asarray(self._counts)
        self._ids, self._ranks, self._counts, delta = _incremental_step(
            self._ids, self._ranks, self._counts,
            jnp.asarray(touched_p), jnp.asarray(add_ids), jnp.asarray(add_ranks),
            jnp.asarray(_pad(new_u, new_cap)), jnp.asarray(_pad(new_v, new_cap)),
            jnp.asarray(_pad(new_ranks, new_cap)), jnp.asarray(new_mask),
        )
        new_counts = np.asarray(self._counts)
        changed = np.nonzero(new_counts != old_host)[0]
        raw = vdict.decode(changed) if len(changed) else []
        out = [(int(r), int(new_counts[c])) for r, c in zip(raw, changed)]
        delta = int(delta)
        if delta:
            self._total += delta
            out.append((GLOBAL_KEY, self._total))
        return out
