"""Triangle counting workloads: per-window exact and streaming exact.

TPU-native re-designs of two reference examples:

- :class:`WindowTriangles` — exact triangle count per time slice
  (``example/WindowTriangles.java:60-139``). The reference generates
  O(Σdeg²) wedge *candidates* per vertex and joins them against real edges
  across two more shuffles; here each slice is one compiled
  sorted-adjacency-intersection step (``ops/triangles.py``), emitting
  ``(count, window_max_timestamp)`` pairs exactly like the reference's
  final ``timeWindowAll().sum(0)`` stream.

- :class:`ExactTriangleCount` — single-pass exact local + global triangle
  count over the whole stream (``example/ExactTriangleCount.java:41-207``).
  The reference pairs per-edge neighborhood snapshots in keyed state so a
  triangle is counted exactly once — when its last edge arrives. Here each
  accumulated edge carries an *arrival rank*; per window, one device step
  counts for every new edge the common neighbors whose closing edges both
  have smaller rank (same once-per-triangle semantics, batched). Duplicate
  edges are dropped (the reference's TreeSet adjacency is likewise
  duplicate-insensitive). Emission is per-window change-only: ``(vertex,
  running_count)`` for every vertex whose count changed, and ``(-1,
  running_total)`` — the reference's ``SumAndEmitCounters`` stream
  (``ExactTriangleCount.java:121-134``) at window granularity
  (SURVEY.md §7 "semantic deltas").
"""

from __future__ import annotations

import functools
from typing import Iterable, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.edgeblock import bucket_capacity
from ..core.emission import LazyListBatch
from ..core.window import WindowPolicy, Windower
from ..utils.keyruns import SortedRunSet
from ..ops.triangles import (
    build_sorted_directed,
    degree_class_plan,
    grow_packed_columns,
    packed_triangle_update,
    prepare_packed_window,
    sticky_search_steps,
    window_triangle_count,
)

GLOBAL_KEY = -1  # the reference's "total" counter vertex id


def _pad(a: np.ndarray, cap: int) -> np.ndarray:
    out = np.zeros(cap, a.dtype)
    out[: len(a)] = a
    return out


def _pad_fill(a: np.ndarray, cap: int, fill) -> np.ndarray:
    out = np.full(cap, fill, a.dtype)
    out[: len(a)] = a
    return out


@functools.partial(jax.jit, static_argnums=(3, 4))
def _window_step(src, dst, mask, num_vertices: int, max_degree: int):
    return window_triangle_count(src, dst, mask, num_vertices, max_degree)


_BIG = jnp.iinfo(jnp.int32).max


@functools.partial(jax.jit, static_argnums=(7, 8), donate_argnums=(0, 1, 2))
def _prep_step(pv, pn, pr, src, dst, mask, rank0, num_vertices: int,
               search_steps: int):
    return prepare_packed_window(
        pv, pn, pr, src, dst, mask, rank0, num_vertices,
        search_steps=search_steps,
    )


@functools.partial(jax.jit, static_argnums=(9, 10, 11))
def _packed_count_step(
    pn, pr, row_ptr, qu, qv, qrank, qmask, sel, counts_and_delta,
    enum_width: int, search_steps: int, chunk: int,
):
    # no donation: emission is lazy (consumers may download a window's
    # counts after later windows have dispatched), so every window's
    # counts array must stay valid. `sel` (padded with -1) selects this
    # degree class's queries — the gather runs on device, so the host
    # never materializes per-class columns.
    from ..ops.triangles import chunked_class_scan

    def body(carry, s_i):
        counts, delta = carry
        selc = jnp.clip(s_i, 0, qu.shape[0] - 1)
        mask_s = (s_i >= 0) & qmask[selc]
        counts, d = packed_triangle_update(
            pn, pr, row_ptr, qu[selc], qv[selc], qrank[selc], mask_s,
            counts, enum_width, search_steps=search_steps,
        )
        return counts, delta + d

    return chunked_class_scan(body, counts_and_delta, sel, chunk)


@jax.jit
def _accum_total(total, delta):
    return total + delta


class WindowTriangles:
    """Exact triangles per tumbling window.

    ``run(edges)`` yields ``(count, max_timestamp)`` per window —
    ``max_timestamp`` is the inclusive window end for event-time windows
    (Flink's ``TimeWindow.maxTimestamp()``), the window index for count
    windows.
    """

    def __init__(self, window: WindowPolicy):
        self.window = window

    def run(self, edges: Iterable[Tuple]) -> Iterator[Tuple[int, Optional[float]]]:
        windower = Windower(self.window)
        for info, block in windower.blocks_with_info(edges):
            s, d, _ = block.to_host()
            max_deg = _oriented_degree_bucket(s, d, block.n_vertices)
            total, _ = _window_step(
                block.src, block.dst, block.mask, block.n_vertices, max_deg
            )
            ts = info.max_timestamp if info.max_timestamp is not None else info.index
            yield int(total), ts

    def run_stream(self, stream) -> Iterator[Tuple[jax.Array, int]]:
        """System path: consume a ``SimpleEdgeStream`` through
        ``stream.slice(self.window)`` (re-windowing + vertex mapping) and
        count per slice. Yields ``(count, window_index)`` with ``count``
        still a DEVICE scalar — ``int(count)`` syncs; draining without
        reading keeps the pipeline free of per-window round trips."""
        snaps = stream.slice(self.window)
        for i, block in enumerate(snaps._block_iter_fn()):
            s, d, _ = block.to_host()
            max_deg = _oriented_degree_bucket(s, d, block.n_vertices)
            total, _ = _window_step(
                block.src, block.dst, block.mask, block.n_vertices, max_deg
            )
            yield total, i


def _oriented_degree_bucket(
    s: np.ndarray, d: np.ndarray, num_vertices: int,
    dense_budget_bytes: int = 2 << 30,
) -> int:
    """Bucket (power of two) covering the max ORIENTED out-degree of the
    window — the dense-row width of the degree-oriented kernel.

    Fast path (one bincount, no sort): with degree-ordered orientation
    every out-neighbor of ``a`` has degree >= deg(a) >= outdeg(a), so
    outdeg(a)^2 <= sum of out-neighbor degrees <= 2E' — i.e. the width is
    bounded by ``min(max degree, sqrt(2E))``, both computable WITHOUT the
    dedup sort (duplicate edges only inflate the bound, never shrink it).
    The previous exact computation np.unique-sorted every window's keys
    (~100 ms per 1M-edge window — the whole system rate). If the sound
    bound would blow the kernel's dense [V, width] rows past
    ``dense_budget_bytes``, fall back to the exact sort-based width.
    """
    E = len(s)
    if E == 0:
        return bucket_capacity(0)
    deg = np.bincount(s, minlength=num_vertices)
    deg = deg + np.bincount(d, minlength=num_vertices)
    w = int(min(int(deg.max()), int(np.ceil(np.sqrt(2.0 * E))) + 1))
    cap = bucket_capacity(max(w, 8))
    if num_vertices * cap * 4 <= dense_budget_bytes:
        return cap
    # exact width: dedup + orient on host (sort-heavy, rare path)
    u = np.minimum(s, d).astype(np.int64)
    v = np.maximum(s, d).astype(np.int64)
    ok = u != v
    u, v = u[ok], v[ok]
    if u.size == 0:
        return bucket_capacity(0)
    key = np.unique(u * num_vertices + v)
    u = key // num_vertices
    v = key % num_vertices
    deg = np.bincount(u, minlength=num_vertices) + np.bincount(
        v, minlength=num_vertices
    )
    du, dv = deg[u], deg[v]
    swap = (dv < du) | ((dv == du) & (v < u))
    a = np.where(swap, v, u)
    return bucket_capacity(int(np.bincount(a, minlength=num_vertices).max()))


class TriangleBatch(LazyListBatch):
    """One window's change-only emission, LAZY: device arrays are held and
    the download happens on first read (iteration / indexing). Unconsumed
    windows cost zero device->host traffic, so the device pipeline never
    stalls on the tunnel (the round-2 verdict's seconds/window was mostly
    two full [vcap] count downloads per window).

    Changes are reported against the counts at the PREVIOUS materialized
    batch — materializing batches in stream order (the normal consumption
    pattern) reproduces per-window change-only emission exactly; skipping
    windows folds their changes into the next one read, and reading an
    old batch after a newer one diffs against the newer state without
    regressing the workload's diff base.
    """

    __slots__ = ("_workload", "_counts", "_total", "_vdict", "_seq", "_items")

    def __init__(self, workload, counts, total, vdict, seq):
        self._workload = workload
        self._counts = counts
        self._total = total
        self._vdict = vdict
        self._seq = seq
        self._items = None

    def _compute(self) -> list:
        w = self._workload
        counts, total = jax.device_get((self._counts, self._total))
        total = int(total)
        prev = w._emit_prev
        if prev is None or len(prev) < len(counts):
            grown = np.zeros(len(counts), counts.dtype)
            if prev is not None:
                grown[: len(prev)] = prev
            prev = grown
        changed = np.nonzero(counts != prev[: len(counts)])[0]
        raw = self._vdict.decode(changed) if len(changed) else []
        out = [(int(r), int(counts[c])) for r, c in zip(raw, changed)]
        if total != w._emit_prev_total:
            out.append((GLOBAL_KEY, total))
        if self._seq >= w._emit_seq_base:
            # newest materialization wins; older batches read later must
            # not clobber the diff base
            w._emit_prev = counts
            w._emit_prev_total = total
            w._emit_seq_base = self._seq
        return out


class ExactTriangleCount:
    """Single-pass exact local + global triangle counting.

    ``run(stream)`` consumes a ``SimpleEdgeStream`` and yields, per window, a
    list-like :class:`TriangleBatch` of ``(raw_vertex_id, running_count)``
    for changed vertices plus ``(GLOBAL_KEY, running_total)`` when it
    changed (downloaded lazily on first read).
    """

    def __init__(self):
        # host carry: the RAW edge columns in arrival order (checkpoint
        # source of truth — canonicalization/dedup happen on device) and a
        # duplicate-inflated degree bound (bincount only, no sorts) that
        # soundly over-covers every true adjacency-row length for class
        # assignment
        # raw columns as per-window chunks (concatenated only at the
        # checkpoint sync point: a per-window concatenate of the whole
        # history is O(stream) memcpy per window — quadratic)
        self._u_chunks: List[np.ndarray] = []
        self._v_chunks: List[np.ndarray] = []
        self._deg = np.zeros(0, np.int64)
        self._have = SortedRunSet()  # distinct canonical keys (LSM runs)
        self._n_raw = 0  # cumulative rank offset (padded block widths)
        self._emit_prev = None  # host counts at the last materialized batch
        self._emit_prev_total = 0
        self._emit_seq = 0  # batches yielded (order watermark source)
        self._emit_seq_base = 0  # seq of the last materialized batch
        # device carry: counts [Vcap] + PACKED sorted adjacency — columns
        # (vertex, nbr, rank) sorted by (vertex, nbr), both directions of
        # every canonical edge, +INT32_MAX vertex sentinel padding. O(E)
        # memory: the round-2 interim [V, max_degree] dense rows let one
        # hub size every vertex's row (O(V*D) — 17 GB at a 16k-degree hub
        # over 262k vertices).
        self._counts = None
        self._pv = None
        self._pn = None
        self._pr = None
        self._n_packed = 0
        self._total = jnp.int32(0)  # device scalar (no per-window sync)

    def run(self, stream) -> Iterator[List[Tuple[int, int]]]:
        vdict = stream.vertex_dict
        for block in stream.blocks():
            yield self._process(block, vdict)

    def _raw_columns(self) -> Tuple[np.ndarray, np.ndarray]:
        """Flatten (and collapse) the per-window raw-column chunks — the
        checkpoint-time sync point; per-window code never concatenates."""
        if len(self._u_chunks) > 1:
            self._u_chunks = [np.concatenate(self._u_chunks)]
            self._v_chunks = [np.concatenate(self._v_chunks)]
        if not self._u_chunks:
            return np.zeros(0, np.int32), np.zeros(0, np.int32)
        return self._u_chunks[0], self._v_chunks[0]

    def state_dict(self) -> dict:
        """Checkpoint surface (``aggregate/checkpoint.py:save_workload``).
        The packed adjacency is NOT serialized — ``load_state_dict``
        rebuilds it from the raw edge columns (rank ORDER, the only thing
        the counting rule reads, survives the renumbering)."""
        u, v = self._raw_columns()
        return {
            "u": u, "v": v,
            "deg": self._deg,
            "n_raw": self._n_raw,
            "counts": None if self._counts is None else np.asarray(self._counts),
            "total": int(self._total),
        }

    def load_state_dict(self, d: dict) -> None:
        u, v = np.asarray(d["u"]), np.asarray(d["v"])
        self._u_chunks = [u] if len(u) else []
        self._v_chunks = [v] if len(v) else []
        self._deg = d["deg"]
        self._n_raw = int(d.get("n_raw", len(u)))
        self._counts = None if d["counts"] is None else jnp.asarray(d["counts"])
        self._total = jnp.int32(int(d["total"]))
        self._emit_prev = None if d["counts"] is None else np.asarray(d["counts"]).copy()
        self._emit_prev_total = int(d["total"])
        self._emit_seq = 0
        self._emit_seq_base = 0
        self._pv = self._pn = self._pr = None
        self._n_packed = 0
        self._have = SortedRunSet()
        if len(u):
            # rebuild the packed adjacency from the raw columns: canonical
            # first occurrences, ranked by raw arrival position
            cu = np.minimum(u, v).astype(np.int64)
            cv = np.maximum(u, v).astype(np.int64)
            ok = cu != cv
            pos_all = np.nonzero(ok)[0]
            cu, cv = cu[ok], cv[ok]
            key = (cu << 32) | cv
            _, first = np.unique(key, return_index=True)
            self._have = SortedRunSet(key)  # host shadow of the packed count
            ranks = pos_all[first].astype(np.int32)
            cu = cu[first].astype(np.int32)
            cv = cv[first].astype(np.int32)
            pvp, pnp, prp, n_new = build_sorted_directed(cu, cv, ranks)
            self._n_packed = n_new
            self._pv = jnp.asarray(pvp)
            self._pn = jnp.asarray(pnp)
            self._pr = jnp.asarray(prp)
            # future ranks must exceed every rebuilt rank
            self._n_raw = max(self._n_raw, len(u))

    # ------------------------------------------------------------------ #
    def _grow_packed(self, need: int) -> None:
        self._pv, self._pn, self._pr = grow_packed_columns(
            self._pv, self._pn, self._pr, need
        )

    def _process(self, block, vdict) -> List[Tuple[int, int]]:
        vcap = block.n_vertices
        # host columns drive CLASS assignment only (free via the block's
        # host cache on the ingest path); dedup/merge/count run on device
        cache = getattr(block, "_host_cache", None)
        if cache is not None:
            s, d = cache[0], cache[1]
            # None = prefix alignment (host row i == device slot i);
            # non-prefix producers (distinct()) record real slot positions
            pos = getattr(block, "_host_cache_pos", None)
        else:
            mask_h = np.asarray(block.mask)
            s = np.asarray(block.src)[mask_h]
            d = np.asarray(block.dst)[mask_h]
            pos = np.nonzero(mask_h)[0].astype(np.int32)
        n_raw = len(s)
        if self._counts is None:
            self._counts = jnp.zeros(vcap, jnp.int32)
        elif vcap > self._counts.shape[0]:
            self._counts = jnp.concatenate(
                [self._counts, jnp.zeros(vcap - self._counts.shape[0], jnp.int32)]
            )
        if n_raw == 0:
            return []
        self._u_chunks.append(np.asarray(s, np.int32))
        self._v_chunks.append(np.asarray(d, np.int32))
        if vcap > len(self._deg):
            self._deg = np.concatenate(
                [self._deg, np.zeros(vcap - len(self._deg), np.int64)]
            )
        np.add.at(self._deg, s, 1)
        np.add.at(self._deg, d, 1)

        # 1. one device dispatch: canonicalize/dedup/reject-known, merge
        # into the packed adjacency, rebuild row_ptr
        cap = block.capacity
        rank0 = self._n_raw
        self._n_raw += cap  # ranks are slot-indexed; only ORDER matters
        # EXACT host shadow of the packed count ([[novelty-tracked]] device
        # growth): distinct first-seen canonical keys, computed beside the
        # stream — the same dedup rule the device applies, so the packed
        # capacity grows by exactly the entries the merge will add. The
        # round-3 version read the true count back through the tunnel at
        # growth boundaries ((pv != BIG).sum() — ~0.5-3 s per D2H on the
        # remote runtime), which WAS the 107k-eps system rate.
        cu = np.minimum(s, d).astype(np.int64)
        cvv = np.maximum(s, d).astype(np.int64)
        okc = cu != cvv
        new_key = self._have.filter_new(np.unique((cu[okc] << 32) | cvv[okc]))
        n_new_distinct = len(new_key)
        self._have.add(new_key)
        self._grow_packed(self._n_packed + 2 * n_new_distinct)
        search_steps = max(4, int(self._pv.shape[0]).bit_length())
        (self._pv, self._pn, self._pr, row_ptr, qu, qv, qrank,
         qmask) = _prep_step(
            self._pv, self._pn, self._pr, block.src, block.dst, block.mask,
            jnp.int32(rank0), vcap, search_steps,
        )
        self._n_packed += 2 * n_new_distinct  # exact (host novelty shadow)

        # 2. count closures per min-degree class (shared coarse-class /
        # enum-budget / sticky-steps policy: ops/triangles.py). The
        # duplicate-inflated degree bound only ever WIDENS a class — sound.
        mindeg = np.minimum(self._deg[s], self._deg[d])
        acc = (self._counts, jnp.int32(0))
        self._search_steps = sticky_search_steps(
            getattr(self, "_search_steps", 8), int(self._deg.max())
        )
        for width, sel, tcap, chunk in degree_class_plan(mindeg):
            if pos is not None:
                sel = pos[sel]
            acc = _packed_count_step(
                self._pn, self._pr, row_ptr, qu, qv, qrank, qmask,
                jnp.asarray(_pad_fill(sel, tcap, np.int32(-1))),
                acc,
                width,
                self._search_steps,
                chunk,
            )
        self._counts, delta = acc
        self._total = _accum_total(self._total, delta)
        self._emit_seq += 1
        return TriangleBatch(self, self._counts, self._total, vdict,
                             self._emit_seq)
