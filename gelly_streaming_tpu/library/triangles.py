"""Triangle counting workloads: per-window exact and streaming exact.

TPU-native re-designs of two reference examples:

- :class:`WindowTriangles` — exact triangle count per time slice
  (``example/WindowTriangles.java:60-139``). The reference generates
  O(Σdeg²) wedge *candidates* per vertex and joins them against real edges
  across two more shuffles; here each slice is one compiled
  sorted-adjacency-intersection step (``ops/triangles.py``), emitting
  ``(count, window_max_timestamp)`` pairs exactly like the reference's
  final ``timeWindowAll().sum(0)`` stream.

- :class:`ExactTriangleCount` — single-pass exact local + global triangle
  count over the whole stream (``example/ExactTriangleCount.java:41-207``).
  The reference pairs per-edge neighborhood snapshots in keyed state so a
  triangle is counted exactly once — when its last edge arrives. Here each
  accumulated edge carries an *arrival rank*; per window, one device step
  counts for every new edge the common neighbors whose closing edges both
  have smaller rank (same once-per-triangle semantics, batched). Duplicate
  edges are dropped (the reference's TreeSet adjacency is likewise
  duplicate-insensitive). Emission is per-window change-only: ``(vertex,
  running_count)`` for every vertex whose count changed, and ``(-1,
  running_total)`` — the reference's ``SumAndEmitCounters`` stream
  (``ExactTriangleCount.java:121-134``) at window granularity
  (SURVEY.md §7 "semantic deltas").
"""

from __future__ import annotations

import functools
from typing import Iterable, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.edgeblock import bucket_capacity
from ..core.window import CountWindow, WindowPolicy, Windower
from ..ops.triangles import (
    merge_packed_adjacency,
    packed_triangle_update,
    window_triangle_count,
)

GLOBAL_KEY = -1  # the reference's "total" counter vertex id


def _pad(a: np.ndarray, cap: int) -> np.ndarray:
    out = np.zeros(cap, a.dtype)
    out[: len(a)] = a
    return out


def _pad_fill(a: np.ndarray, cap: int, fill) -> np.ndarray:
    out = np.full(cap, fill, a.dtype)
    out[: len(a)] = a
    return out


@functools.partial(jax.jit, static_argnums=(3, 4))
def _window_step(src, dst, mask, num_vertices: int, max_degree: int):
    return window_triangle_count(src, dst, mask, num_vertices, max_degree)


_BIG = jnp.iinfo(jnp.int32).max


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def _merge_step(pv, pn, pr, new_v, new_n, new_r, n_new):
    return merge_packed_adjacency(pv, pn, pr, new_v, new_n, new_r, n_new)


@functools.partial(jax.jit, static_argnums=(1,))
def _row_ptr_of(pv, num_vertices: int):
    return jnp.searchsorted(
        pv, jnp.arange(num_vertices + 1, dtype=jnp.int32)
    ).astype(jnp.int32)


@functools.partial(jax.jit, static_argnums=(7, 8), donate_argnums=(6,))
def _packed_count_step(
    pn, pr, row_ptr, qu, qv, qrank, counts_and_delta, enum_width: int,
    search_steps: int, *, qmask,
):
    counts, delta = counts_and_delta
    counts, d = packed_triangle_update(
        pn, pr, row_ptr, qu, qv, qrank, qmask, counts, enum_width,
        search_steps=search_steps,
    )
    return counts, delta + d


class WindowTriangles:
    """Exact triangles per tumbling window.

    ``run(edges)`` yields ``(count, max_timestamp)`` per window —
    ``max_timestamp`` is the inclusive window end for event-time windows
    (Flink's ``TimeWindow.maxTimestamp()``), the window index for count
    windows.
    """

    def __init__(self, window: WindowPolicy):
        self.window = window

    def run(self, edges: Iterable[Tuple]) -> Iterator[Tuple[int, Optional[float]]]:
        windower = Windower(self.window)
        for info, block in windower.blocks_with_info(edges):
            s, d, _ = block.to_host()
            max_deg = _oriented_degree_bucket(s, d, block.n_vertices)
            total, _ = _window_step(
                block.src, block.dst, block.mask, block.n_vertices, max_deg
            )
            ts = info.max_timestamp if info.max_timestamp is not None else info.index
            yield int(total), ts


def _oriented_degree_bucket(s: np.ndarray, d: np.ndarray, num_vertices: int) -> int:
    """Bucket (power of two) covering the max ORIENTED out-degree of the
    window — the dense-row width of the degree-oriented kernel; at most
    ~sqrt(2E) for any degree distribution."""
    u = np.minimum(s, d).astype(np.int64)
    v = np.maximum(s, d).astype(np.int64)
    ok = u != v
    u, v = u[ok], v[ok]
    if u.size == 0:
        return bucket_capacity(0)
    key = np.unique(u * num_vertices + v)
    u = key // num_vertices
    v = key % num_vertices
    deg = np.bincount(u, minlength=num_vertices) + np.bincount(
        v, minlength=num_vertices
    )
    du, dv = deg[u], deg[v]
    swap = (dv < du) | ((dv == du) & (v < u))
    a = np.where(swap, v, u)
    return bucket_capacity(int(np.bincount(a, minlength=num_vertices).max()))


class ExactTriangleCount:
    """Single-pass exact local + global triangle counting.

    ``run(stream)`` consumes a ``SimpleEdgeStream`` and yields, per window, a
    list of ``(raw_vertex_id, running_count)`` for changed vertices plus
    ``(GLOBAL_KEY, running_total)`` when it changed.
    """

    def __init__(self):
        # host carry: canonical accumulated edges in arrival order + dedup key
        self._u = np.zeros(0, np.int32)
        self._v = np.zeros(0, np.int32)
        self._seen_keys = np.zeros(0, np.int64)  # sorted
        self._deg = np.zeros(0, np.int64)
        # device carry: counts [Vcap] + PACKED sorted adjacency — columns
        # (vertex, nbr, rank) sorted by (vertex, nbr), both directions of
        # every canonical edge, +INT32_MAX vertex sentinel padding. O(E)
        # memory: the round-2 interim [V, max_degree] dense rows let one
        # hub size every vertex's row (O(V*D) — 17 GB at a 16k-degree hub
        # over 262k vertices).
        self._counts = None
        self._pv = None
        self._pn = None
        self._pr = None
        self._n_packed = 0
        self._total = 0

    def run(self, stream) -> Iterator[List[Tuple[int, int]]]:
        vdict = stream.vertex_dict
        for block in stream.blocks():
            s, d, _ = block.to_host()
            vcap = block.n_vertices
            new_u, new_v = self._dedup_new(s, d)
            yield self._process(new_u, new_v, vcap, vdict)

    def state_dict(self) -> dict:
        """Checkpoint surface (``aggregate/checkpoint.py:save_workload``).
        The packed adjacency is NOT serialized — ``load_state_dict``
        rebuilds it from the edge list (one host lexsort + device put)."""
        return {
            "u": self._u, "v": self._v, "seen_keys": self._seen_keys,
            "deg": self._deg,
            "counts": None if self._counts is None else np.asarray(self._counts),
            "total": self._total,
        }

    def load_state_dict(self, d: dict) -> None:
        self._u, self._v = d["u"], d["v"]
        self._seen_keys, self._deg = d["seen_keys"], d["deg"]
        self._counts = None if d["counts"] is None else jnp.asarray(d["counts"])
        self._total = int(d["total"])
        self._pv = self._pn = self._pr = None
        self._n_packed = 0
        if len(self._u):
            # rebuild the packed adjacency from the edge list (host
            # lexsort once — checkpoints stay in the edge-list format)
            ranks = np.arange(len(self._u), dtype=np.int32)
            pv = np.concatenate([self._u, self._v])
            pn = np.concatenate([self._v, self._u])
            pr = np.concatenate([ranks, ranks])
            order = np.lexsort((pn, pv))
            self._n_packed = len(pv)
            cap = bucket_capacity(self._n_packed)
            self._pv = jnp.asarray(
                _pad_fill(pv[order], cap, np.iinfo(np.int32).max)
            )
            self._pn = jnp.asarray(_pad(pn[order].astype(np.int32), cap))
            self._pr = jnp.asarray(_pad(pr[order], cap))

    # ------------------------------------------------------------------ #
    def _dedup_new(self, s: np.ndarray, d: np.ndarray):
        """Canonicalize, drop self-loops and edges seen before (order kept)."""
        u = np.minimum(s, d).astype(np.int64)
        v = np.maximum(s, d).astype(np.int64)
        ok = u != v
        u, v = u[ok], v[ok]
        key = (u << 32) | v
        # in-window first-occurrence dedup, arrival order preserved
        _, first_idx = np.unique(key, return_index=True)
        first_idx.sort()
        u, v, key = u[first_idx], v[first_idx], key[first_idx]
        # drop edges already accumulated
        pos = np.searchsorted(self._seen_keys, key)
        pos_c = np.minimum(pos, max(len(self._seen_keys) - 1, 0))
        dup = (
            (self._seen_keys[pos_c] == key) if len(self._seen_keys) else
            np.zeros(len(key), bool)
        )
        u, v, key = u[~dup], v[~dup], key[~dup]
        self._seen_keys = np.sort(np.concatenate([self._seen_keys, key]))
        return u.astype(np.int32), v.astype(np.int32)

    def _grow_packed(self, need: int) -> None:
        """Grow the packed columns to a bucket covering ``need`` entries
        (appending +INT32_MAX vertex sentinels keeps them sorted)."""
        cap = bucket_capacity(max(need, 8))
        if self._pv is None:
            self._pv = jnp.full(cap, _BIG, jnp.int32)
            self._pn = jnp.zeros(cap, jnp.int32)
            self._pr = jnp.zeros(cap, jnp.int32)
            return
        old = self._pv.shape[0]
        if cap <= old:
            return
        self._pv = jnp.concatenate(
            [self._pv, jnp.full(cap - old, _BIG, jnp.int32)]
        )
        self._pn = jnp.concatenate([self._pn, jnp.zeros(cap - old, jnp.int32)])
        self._pr = jnp.concatenate([self._pr, jnp.zeros(cap - old, jnp.int32)])

    def _process(self, new_u, new_v, vcap: int, vdict) -> List[Tuple[int, int]]:
        n_old = len(self._u)
        self._u = np.concatenate([self._u, new_u])
        self._v = np.concatenate([self._v, new_v])
        if vcap > len(self._deg):
            self._deg = np.concatenate(
                [self._deg, np.zeros(vcap - len(self._deg), np.int64)]
            )
        np.add.at(self._deg, new_u, 1)
        np.add.at(self._deg, new_v, 1)
        if self._counts is None:
            self._counts = jnp.zeros(vcap, jnp.int32)
        elif vcap > self._counts.shape[0]:
            self._counts = jnp.concatenate(
                [self._counts, jnp.zeros(vcap - self._counts.shape[0], jnp.int32)]
            )
        if len(new_u) == 0:
            return []

        n_acc = len(self._u)
        new_ranks = np.arange(n_old, n_acc, dtype=np.int32)

        # 1. merge both directions of the new edges into the packed
        # adjacency (host lexsort of the NEW entries only, device merge)
        pv_new = np.concatenate([new_u, new_v])
        pn_new = np.concatenate([new_v, new_u])
        pr_new = np.concatenate([new_ranks, new_ranks])
        order = np.lexsort((pn_new, pv_new))
        n_new = len(pv_new)
        ncap = bucket_capacity(n_new, minimum=16)
        self._grow_packed(self._n_packed + n_new)
        self._pv, self._pn, self._pr = _merge_step(
            self._pv, self._pn, self._pr,
            jnp.asarray(_pad_fill(pv_new[order].astype(np.int32), ncap,
                                  np.iinfo(np.int32).max)),
            jnp.asarray(_pad(pn_new[order].astype(np.int32), ncap)),
            jnp.asarray(_pad(pr_new[order], ncap)),
            jnp.int32(n_new),
        )
        self._n_packed += n_new
        row_ptr = _row_ptr_of(self._pv, vcap)

        # 2. count closures per min-degree class: enumeration rows are
        # only as wide as each class's bucket (no hub-sized dense rows)
        mindeg = np.minimum(self._deg[new_u], self._deg[new_v])
        classes = np.int64(1) << np.ceil(
            np.log2(np.maximum(mindeg, 1))
        ).astype(np.int64)
        classes = np.maximum(classes, 16)
        old_host = np.asarray(self._counts)
        acc = (self._counts, jnp.int32(0))
        # the binary search only ever spans the largest row; a tight step
        # count (vs a blanket 32) cuts the dominant inner loop ~2-3x
        steps = max(4, int(bucket_capacity(int(self._deg.max()))).bit_length())
        for c in np.unique(classes):
            sel = np.nonzero(classes == c)[0]
            t = len(sel)
            tcap = bucket_capacity(t, minimum=16)
            qmask = np.zeros(tcap, bool)
            qmask[:t] = True
            acc = _packed_count_step(
                self._pn, self._pr, row_ptr,
                jnp.asarray(_pad(new_u[sel], tcap)),
                jnp.asarray(_pad(new_v[sel], tcap)),
                jnp.asarray(_pad(new_ranks[sel], tcap)),
                acc,
                int(c),
                steps,
                qmask=jnp.asarray(qmask),
            )
        self._counts, delta = acc
        new_counts = np.asarray(self._counts)
        changed = np.nonzero(new_counts != old_host)[0]
        raw = vdict.decode(changed) if len(changed) else []
        out = [(int(r), int(new_counts[c])) for r, c in zip(raw, changed)]
        delta = int(delta)
        if delta:
            self._total += delta
            out.append((GLOBAL_KEY, self._total))
        return out
