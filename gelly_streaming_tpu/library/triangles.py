"""Triangle counting workloads: per-window exact and streaming exact.

TPU-native re-designs of two reference examples:

- :class:`WindowTriangles` — exact triangle count per time slice
  (``example/WindowTriangles.java:60-139``). The reference generates
  O(Σdeg²) wedge *candidates* per vertex and joins them against real edges
  across two more shuffles; here each slice is one compiled
  sorted-adjacency-intersection step (``ops/triangles.py``), emitting
  ``(count, window_max_timestamp)`` pairs exactly like the reference's
  final ``timeWindowAll().sum(0)`` stream.

- :class:`ExactTriangleCount` — single-pass exact local + global triangle
  count over the whole stream (``example/ExactTriangleCount.java:41-207``).
  The reference pairs per-edge neighborhood snapshots in keyed state so a
  triangle is counted exactly once — when its last edge arrives. Here each
  accumulated edge carries an *arrival rank*; per window, one device step
  counts for every new edge the common neighbors whose closing edges both
  have smaller rank (same once-per-triangle semantics, batched). Duplicate
  edges are dropped (the reference's TreeSet adjacency is likewise
  duplicate-insensitive). Emission is per-window change-only: ``(vertex,
  running_count)`` for every vertex whose count changed, and ``(-1,
  running_total)`` — the reference's ``SumAndEmitCounters`` stream
  (``ExactTriangleCount.java:121-134``) at window granularity
  (SURVEY.md §7 "semantic deltas").
"""

from __future__ import annotations

import functools
from typing import Iterable, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.edgeblock import bucket_capacity
from ..core.window import CountWindow, WindowPolicy, Windower
from ..ops.triangles import (
    ranked_triangle_update,
    sorted_ranked_rows,
    window_triangle_count,
)

GLOBAL_KEY = -1  # the reference's "total" counter vertex id


def _pad(a: np.ndarray, cap: int) -> np.ndarray:
    out = np.zeros(cap, a.dtype)
    out[: len(a)] = a
    return out


@functools.partial(jax.jit, static_argnums=(3, 4))
def _window_step(src, dst, mask, num_vertices: int, max_degree: int):
    return window_triangle_count(src, dst, mask, num_vertices, max_degree)


@functools.partial(jax.jit, static_argnums=(8, 9))
def _streaming_step(
    acc_u, acc_v, acc_rank, acc_mask,
    new_u, new_v, new_rank, new_mask,
    num_vertices: int, max_degree: int,
    counts,
):
    ids, ranks = sorted_ranked_rows(
        acc_u, acc_v, acc_rank, acc_mask, num_vertices, max_degree
    )
    return ranked_triangle_update(
        ids, ranks, new_u, new_v, new_rank, new_mask, counts
    )


class WindowTriangles:
    """Exact triangles per tumbling window.

    ``run(edges)`` yields ``(count, max_timestamp)`` per window —
    ``max_timestamp`` is the inclusive window end for event-time windows
    (Flink's ``TimeWindow.maxTimestamp()``), the window index for count
    windows.
    """

    def __init__(self, window: WindowPolicy):
        self.window = window

    def run(self, edges: Iterable[Tuple]) -> Iterator[Tuple[int, Optional[float]]]:
        windower = Windower(self.window)
        for info, block in windower.blocks_with_info(edges):
            s, d, _ = block.to_host()
            max_deg = _oriented_degree_bucket(s, d, block.n_vertices)
            total, _ = _window_step(
                block.src, block.dst, block.mask, block.n_vertices, max_deg
            )
            ts = info.max_timestamp if info.max_timestamp is not None else info.index
            yield int(total), ts


def _oriented_degree_bucket(s: np.ndarray, d: np.ndarray, num_vertices: int) -> int:
    """Bucket (power of two) covering the max ORIENTED out-degree of the
    window — the dense-row width of the degree-oriented kernel; at most
    ~sqrt(2E) for any degree distribution."""
    u = np.minimum(s, d).astype(np.int64)
    v = np.maximum(s, d).astype(np.int64)
    ok = u != v
    u, v = u[ok], v[ok]
    if u.size == 0:
        return bucket_capacity(0)
    key = np.unique(u * num_vertices + v)
    u = key // num_vertices
    v = key % num_vertices
    deg = np.bincount(u, minlength=num_vertices) + np.bincount(
        v, minlength=num_vertices
    )
    du, dv = deg[u], deg[v]
    swap = (dv < du) | ((dv == du) & (v < u))
    a = np.where(swap, v, u)
    return bucket_capacity(int(np.bincount(a, minlength=num_vertices).max()))


class ExactTriangleCount:
    """Single-pass exact local + global triangle counting.

    ``run(stream)`` consumes a ``SimpleEdgeStream`` and yields, per window, a
    list of ``(raw_vertex_id, running_count)`` for changed vertices plus
    ``(GLOBAL_KEY, running_total)`` when it changed.
    """

    def __init__(self):
        # host carry: canonical accumulated edges in arrival order + dedup key
        self._u = np.zeros(0, np.int32)
        self._v = np.zeros(0, np.int32)
        self._seen_keys = np.zeros(0, np.int64)  # sorted
        self._deg = np.zeros(0, np.int64)
        # device carry
        self._counts = None
        self._total = 0

    def run(self, stream) -> Iterator[List[Tuple[int, int]]]:
        vdict = stream.vertex_dict
        for block in stream.blocks():
            s, d, _ = block.to_host()
            vcap = block.n_vertices
            new_u, new_v = self._dedup_new(s, d)
            yield self._process(new_u, new_v, vcap, vdict)

    def state_dict(self) -> dict:
        """Checkpoint surface (``aggregate/checkpoint.py:save_workload``)."""
        return {
            "u": self._u, "v": self._v, "seen_keys": self._seen_keys,
            "deg": self._deg,
            "counts": None if self._counts is None else np.asarray(self._counts),
            "total": self._total,
        }

    def load_state_dict(self, d: dict) -> None:
        self._u, self._v = d["u"], d["v"]
        self._seen_keys, self._deg = d["seen_keys"], d["deg"]
        self._counts = None if d["counts"] is None else jnp.asarray(d["counts"])
        self._total = int(d["total"])

    # ------------------------------------------------------------------ #
    def _dedup_new(self, s: np.ndarray, d: np.ndarray):
        """Canonicalize, drop self-loops and edges seen before (order kept)."""
        u = np.minimum(s, d).astype(np.int64)
        v = np.maximum(s, d).astype(np.int64)
        ok = u != v
        u, v = u[ok], v[ok]
        key = (u << 32) | v
        # in-window first-occurrence dedup, arrival order preserved
        _, first_idx = np.unique(key, return_index=True)
        first_idx.sort()
        u, v, key = u[first_idx], v[first_idx], key[first_idx]
        # drop edges already accumulated
        pos = np.searchsorted(self._seen_keys, key)
        pos_c = np.minimum(pos, max(len(self._seen_keys) - 1, 0))
        dup = (
            (self._seen_keys[pos_c] == key) if len(self._seen_keys) else
            np.zeros(len(key), bool)
        )
        u, v, key = u[~dup], v[~dup], key[~dup]
        self._seen_keys = np.sort(np.concatenate([self._seen_keys, key]))
        return u.astype(np.int32), v.astype(np.int32)

    def _process(self, new_u, new_v, vcap: int, vdict) -> List[Tuple[int, int]]:
        n_old = len(self._u)
        self._u = np.concatenate([self._u, new_u])
        self._v = np.concatenate([self._v, new_v])
        if vcap > len(self._deg):
            self._deg = np.concatenate(
                [self._deg, np.zeros(vcap - len(self._deg), np.int64)]
            )
        np.add.at(self._deg, new_u, 1)
        np.add.at(self._deg, new_v, 1)
        if self._counts is None:
            self._counts = jnp.zeros(vcap, jnp.int32)
        elif vcap > self._counts.shape[0]:
            self._counts = jnp.concatenate(
                [self._counts, jnp.zeros(vcap - self._counts.shape[0], jnp.int32)]
            )
        if len(new_u) == 0:
            return []

        n_acc = len(self._u)
        acc_cap = bucket_capacity(n_acc)
        new_cap = bucket_capacity(len(new_u))
        max_deg = bucket_capacity(int(self._deg[:vcap].max()))
        acc_u = _pad(self._u, acc_cap)
        acc_v = _pad(self._v, acc_cap)
        acc_rank = _pad(np.arange(n_acc, dtype=np.int32), acc_cap)
        acc_mask = np.zeros(acc_cap, bool)
        acc_mask[:n_acc] = True
        new_rank = _pad(np.arange(n_old, n_acc, dtype=np.int32), new_cap)
        new_mask = np.zeros(new_cap, bool)
        new_mask[: len(new_u)] = True

        old_counts = self._counts
        self._counts, delta = _streaming_step(
            jnp.asarray(acc_u), jnp.asarray(acc_v),
            jnp.asarray(acc_rank), jnp.asarray(acc_mask),
            jnp.asarray(_pad(new_u, new_cap)), jnp.asarray(_pad(new_v, new_cap)),
            jnp.asarray(new_rank), jnp.asarray(new_mask),
            vcap, max_deg,
            old_counts,
        )
        changed = np.nonzero(
            np.asarray(self._counts) != np.asarray(old_counts)
        )[0]
        out = [(int(vdict.decode_one(c)), int(np.asarray(self._counts)[c]))
               for c in changed]
        delta = int(delta)
        if delta:
            self._total += delta
            out.append((GLOBAL_KEY, self._total))
        return out
