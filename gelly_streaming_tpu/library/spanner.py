"""Streaming k-spanner: host-exact fold and device-batched variant.

:class:`Spanner` — behavioral parity with ``library/Spanner.java:40-118``:
per edge, if the spanner already connects the endpoints within k hops the
edge is dropped, else added (``UpdateLocal``); partial spanners merge
smaller-into-larger under the same bounded-BFS test (``CombineSpanners``).
The per-edge decision is sequential in arrival order and irregular (bounded
BFS), so this flavor stays host-side (SURVEY.md §7 build step 5), plugged
into the engine as a host-state summary (``device=False``).

:class:`DeviceSpanner` — the §7 "revisit as hop-limited relaxation on
device" variant: per window, ALL new edges test k-bounded reachability in
the spanner-as-of-window-start simultaneously — k rounds of frontier
expansion over the spanner's edge list as batched gather + scatter-or
(each round: ``frontier[:, q] |= frontier[:, p]``). Semantics delta
(documented): edges of one window cannot reject each other, so the device
spanner may keep MORE edges than the sequential fold — but the k-spanner
guarantee (every dropped edge has a ≤k-hop spanner path) holds for any
windowing, and it converges to the host result as window size shrinks.
"""

from __future__ import annotations

import functools
from typing import Iterator, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..aggregate.summary import SummaryBulkAggregation
from ..core.edgeblock import bucket_capacity
from ..ops.triangles import degree_class_plan, sticky_search_steps
from ..summaries.adjacency import AdjacencyListGraph

_BIG = jnp.iinfo(jnp.int32).max


@functools.partial(jax.jit, static_argnums=(1,))
def _span_row_ptr(pv, num_vertices: int):
    return jnp.searchsorted(
        pv, jnp.arange(num_vertices + 1, dtype=jnp.int32)
    ).astype(jnp.int32)


@functools.partial(jax.jit, static_argnums=(6, 7, 8))
def _k2_exists_step(pn, row_ptr, qu, qv, sel, acc, enum_width: int,
                    search_steps: int, chunk: int):
    """One min-degree class of common-neighbor existence queries; results
    scatter into the shared per-window accumulator. ``chunked_class_scan``
    bounds the [chunk, enum_width] enumeration block — a whole 1M-query
    class at width 4096 would otherwise materialize 16 GB."""
    from ..ops.triangles import (
        chunked_class_scan,
        packed_common_neighbor_exists,
    )

    def body(acc, s_i):
        selc = jnp.clip(s_i, 0, qu.shape[0] - 1)
        mask = s_i >= 0
        ex = packed_common_neighbor_exists(
            pn, row_ptr, qu[selc], qv[selc], mask, enum_width,
            search_steps=search_steps,
        )
        return acc.at[jnp.where(mask, selc, acc.shape[0])].set(ex, mode="drop")

    return chunked_class_scan(body, acc, sel, chunk)


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def _span_merge(pv, pn, pr, new_v, new_n, new_r, n_new):
    from ..ops.triangles import merge_packed_adjacency

    return merge_packed_adjacency(pv, pn, pr, new_v, new_n, new_r, n_new)


class Spanner(SummaryBulkAggregation):
    """k-spanner over the edge stream (``library/Spanner.java``)."""

    device = False
    config_fields = ("k",)

    def __init__(self, k: int, transient_state: bool = False):
        super().__init__(transient_state=transient_state)
        self.k = k

    def initial_state(self, vcap: int) -> AdjacencyListGraph:
        return AdjacencyListGraph()

    def grow_state(self, state, old_vcap, new_vcap):
        return state

    def update(self, g: AdjacencyListGraph, src, dst, val, mask) -> AdjacencyListGraph:
        """Arrival-order fold (``Spanner.UpdateLocal.foldEdges``)."""
        for u, v in zip(src.tolist(), dst.tolist()):
            if not g.bounded_bfs(u, v, self.k):
                g.add_edge(u, v)
        return g

    def combine(self, g1: AdjacencyListGraph, g2: AdjacencyListGraph) -> AdjacencyListGraph:
        """Merge smaller into larger (``Spanner.CombineSpanners.reduce``)."""
        if len(g1.adj) < len(g2.adj):
            g1, g2 = g2, g1
        for u, v in g2.edges():
            if not g1.bounded_bfs(u, v, self.k):
                g1.add_edge(u, v)
        return g1

    def transform(self, g: AdjacencyListGraph, vdict) -> AdjacencyListGraph:
        # Emit a snapshot copy: the running summary keeps mutating across
        # windows, and emissions must stay stable once yielded.
        return g.copy()


@functools.partial(jax.jit, static_argnums=(6, 7))
def _k_reach(sp, sq, smask, u, v, m, num_vertices: int, k: int):
    """For each query edge i: is v[i] within k hops of u[i] over the
    spanner edge list (sp, sq)? Batched BFS with the query batch PACKED
    into uint32 bitplanes: frontier[B//32, V] words instead of a [B, V]
    bool — 32x the queries per byte of frontier (round-2 verdict #10; at
    V=2^23 the bool frontier admitted ~32 queries per dispatch).

    There is no scatter-OR primitive, so the hop expansion sorts the
    spanner edges by target once and ORs each target's incoming words
    with a segmented ``associative_scan`` (OR is associative), then ORs
    the per-vertex result into the frontier densely. ``B`` must be a
    multiple of 32.
    """
    B = u.shape[0]
    W = B // 32
    word = jnp.arange(B) // 32
    bit = (jnp.uint32(1) << (jnp.arange(B, dtype=jnp.uint32) % 32))
    frontier = jnp.zeros((W, num_vertices), jnp.uint32)
    # distinct queries carry distinct bits, so add == bitwise-or here
    frontier = frontier.at[word, u].add(jnp.where(m, bit, 0))

    # spanner edges sorted by target; padding targets -> sentinel V
    q_s, p_s = jax.lax.sort(
        (jnp.where(smask, sq, num_vertices), jnp.where(smask, sp, 0)),
        num_keys=1,
    )
    S = q_s.shape[0]
    flags = jnp.concatenate([jnp.ones(1, bool), q_s[1:] != q_s[:-1]])
    seg = jnp.arange(num_vertices, dtype=q_s.dtype)
    right = jnp.searchsorted(q_s, seg, side="right")
    left = jnp.searchsorted(q_s, seg, side="left")
    nonempty = right > left
    last = jnp.clip(right - 1, 0, S - 1)

    def seg_or(vals_t):
        def op(a, b):
            fa, va = a
            fb, vb = b
            return fa | fb, jnp.where(fb[:, None], vb, va | vb)

        _, scanned = jax.lax.associative_scan(op, (flags, vals_t))
        return scanned

    for _ in range(k):
        vals_t = frontier[:, p_s].T  # [S, W] incoming words per edge
        scanned = seg_or(vals_t)
        per_vertex = jnp.where(
            nonempty[:, None], scanned[last], jnp.uint32(0)
        )  # [V, W]
        frontier = frontier | per_vertex.T
    return (((frontier[word, v] >> (jnp.arange(B) % 32)) & 1) != 0) & m


class DeviceSpanner:
    """Batched device k-spanner. ``run(stream)`` yields the spanner edge
    set snapshot per window; ``edges()`` returns the current set (raw
    ids).

    ``k == 2`` takes a structurally different fast path: 2-hop
    reachability is "already an edge OR the endpoint rows share a
    neighbor", so the spanner carries a packed sorted adjacency (the
    triangle pipeline's structure) and each window is a handful of
    class-bounded common-neighbor dispatches — O(Q x min-degree-class)
    work, no frontier at all. General ``k`` uses the bitplane-packed
    frontier BFS (O(k x spanner-edges x Q/32) per window)."""

    def __init__(
        self,
        k: int,
        query_chunk: int = 1024,
        mem_budget_entries: int = 1 << 28,
        expected_edges: int = 0,
    ):
        """``expected_edges``: pre-size the k=2 packed adjacency for this
        many spanner edges. Purely a compile-stability hint: every packed
        capacity bucket is a distinct jit signature, and the remote
        compiler charges ~20-40 s per signature — growth still works
        without it."""
        self.k = k
        self.query_chunk = query_chunk
        self.expected_edges = int(expected_edges)
        #: bound on the packed-frontier footprint (uint32 words): the
        #: per-window query batch shrinks as the vertex capacity grows, so
        #: corpus-scale vertex counts cost more dispatches instead of
        #: exploding HBM.
        self.mem_budget_entries = mem_budget_entries
        self._su = np.zeros(0, np.int32)  # spanner edges, compact canonical
        self._sv = np.zeros(0, np.int32)
        self._have = np.zeros(0, np.int64)  # sorted canonical keys
        self._have_vcap = 0
        self._vdict = None
        # k=2 packed-adjacency carry (device) + host degree table
        self._pv = None
        self._pn = None
        self._pr = None
        self._n_packed = 0
        self._deg = np.zeros(0, np.int64)

    def _batch_cap(self, vcap: int) -> int:
        # budget is BYTES of frontier: [B/32, V] uint32 words hold 32
        # queries per 4 bytes, so bitplane packing buys 8x the queries of
        # the old [B, V] bool frontier at the same footprint; the kernel
        # needs B to be a multiple of 32
        words = max(1, self.mem_budget_entries // (4 * max(vcap, 1)))
        b = max(32, min(self.query_chunk, words * 32))
        b = (b // 32) * 32
        return bucket_capacity(b) // 2 if bucket_capacity(b) > b else b

    def run(self, stream) -> Iterator[Set[Tuple[int, int]]]:
        self._vdict = stream.vertex_dict
        for block in stream.blocks():
            s, d, _ = block.to_host()
            vcap = block.n_vertices
            if vcap != self._have_vcap:
                # key space changed with the capacity bucket: re-key
                self._have = np.sort(
                    self._su.astype(np.int64) * vcap
                    + self._sv.astype(np.int64)
                )
                self._have_vcap = vcap
            u = np.minimum(s, d).astype(np.int64)
            v = np.maximum(s, d).astype(np.int64)
            ok = u != v
            u, v = u[ok], v[ok]
            if u.size:
                # in-window dedup (order does not matter for the batch
                # decision) + drop edges already in the spanner (carried
                # sorted key set, merged incrementally — no per-window
                # rebuild of the whole spanner's keys)
                key = np.unique(u * vcap + v)
                pos = np.searchsorted(self._have, key)
                pos_c = np.minimum(pos, max(len(self._have) - 1, 0))
                dup = (
                    (self._have[pos_c] == key) if len(self._have)
                    else np.zeros(len(key), bool)
                )
                key = key[~dup]
                u = (key // vcap).astype(np.int32)
                v = (key % vcap).astype(np.int32)
            if u.size == 0:
                yield self.edges()
                continue
            if self.k == 2:
                keep_u2, keep_v2 = self._window_k2(
                    u.astype(np.int32), v.astype(np.int32), vcap
                )
                self._accept(keep_u2, keep_v2, vcap)
                yield self.edges()
                continue
            # both directions of the current spanner, padded
            scap = bucket_capacity(2 * max(len(self._su), 1))
            sp = np.zeros(scap, np.int32)
            sq = np.zeros(scap, np.int32)
            smask = np.zeros(scap, bool)
            ns = len(self._su)
            sp[:ns], sp[ns : 2 * ns] = self._su, self._sv
            sq[:ns], sq[ns : 2 * ns] = self._sv, self._su
            smask[: 2 * ns] = True
            spj, sqj, smj = jnp.asarray(sp), jnp.asarray(sq), jnp.asarray(smask)
            keep_u, keep_v = [], []
            batch = self._batch_cap(vcap)
            for a in range(0, len(u), batch):
                b = min(a + batch, len(u))
                qcap = bucket_capacity(b - a, minimum=32)
                uq = np.zeros(qcap, np.int32)
                vq = np.zeros(qcap, np.int32)
                mq = np.zeros(qcap, bool)
                uq[: b - a], vq[: b - a] = u[a:b], v[a:b]
                mq[: b - a] = True
                reached = np.asarray(
                    _k_reach(
                        spj, sqj, smj,
                        jnp.asarray(uq), jnp.asarray(vq), jnp.asarray(mq),
                        vcap, self.k,
                    )
                )[: b - a]
                keep_u.append(u[a:b][~reached])
                keep_v.append(v[a:b][~reached])
            self._accept(
                np.concatenate(keep_u).astype(np.int32),
                np.concatenate(keep_v).astype(np.int32),
                vcap,
            )
            yield self.edges()

    # ------------------------------------------------------------------ #
    def _accept(self, ku: np.ndarray, kv: np.ndarray, vcap: int) -> None:
        """Admit the window's accepted edges into every carried structure."""
        self._su = np.concatenate([self._su, ku])
        self._sv = np.concatenate([self._sv, kv])
        new_keys = ku.astype(np.int64) * vcap + kv.astype(np.int64)
        if new_keys.size:
            sk = np.sort(new_keys)
            ins = np.searchsorted(self._have, sk)
            self._have = np.insert(self._have, ins, sk)
        if self.k == 2 and ku.size:
            from ..ops.triangles import build_sorted_directed

            np.add.at(self._deg, ku, 1)
            np.add.at(self._deg, kv, 1)
            pvp, pnp, prp, n_new = build_sorted_directed(ku, kv)
            self._grow_packed(self._n_packed + n_new)
            self._pv, self._pn, self._pr = _span_merge(
                self._pv, self._pn, self._pr,
                jnp.asarray(pvp), jnp.asarray(pnp), jnp.asarray(prp),
                jnp.int32(n_new),
            )
            self._n_packed += n_new

    def _grow_packed(self, need: int) -> None:
        from ..ops.triangles import grow_packed_columns

        self._pv, self._pn, self._pr = grow_packed_columns(
            self._pv, self._pn, self._pr, need, minimum=16
        )

    def _window_k2(self, u: np.ndarray, v: np.ndarray, vcap: int):
        """2-hop reachability for all window queries via class-bounded
        common-neighbor tests on the packed spanner adjacency (direct
        edges were already rejected by the host dedup). One device bool
        download per window."""
        if vcap > len(self._deg):
            self._deg = np.concatenate(
                [self._deg, np.zeros(vcap - len(self._deg), np.int64)]
            )
        if self._pv is None and len(self._su):
            # checkpoint restore: rebuild the packed adjacency once
            from ..ops.triangles import build_sorted_directed

            pvp, pnp, prp, n_new = build_sorted_directed(self._su, self._sv)
            self._n_packed = n_new
            self._pv = jnp.asarray(pvp)
            self._pn = jnp.asarray(pnp)
            self._pr = jnp.asarray(prp)
            np.add.at(self._deg, self._su, 1)
            np.add.at(self._deg, self._sv, 1)
        self._grow_packed(max(self._n_packed, 2 * self.expected_edges, 1))
        row_ptr = _span_row_ptr(self._pv, vcap)

        n_q = len(u)
        qcap = bucket_capacity(n_q, minimum=32)
        qu = np.zeros(qcap, np.int32)
        qv = np.zeros(qcap, np.int32)
        qu[:n_q] = u
        qv[:n_q] = v
        quj, qvj = jnp.asarray(qu), jnp.asarray(qv)
        acc = jnp.zeros(qcap, bool)
        mindeg = np.minimum(self._deg[u], self._deg[v])
        # shared coarse-class / enum-budget / sticky-steps policy
        # (ops/triangles.py — one implementation with the triangle pipeline)
        self._steps = sticky_search_steps(
            getattr(self, "_steps", 8), int(max(self._deg.max(), 1))
        )
        for width, sel, tcap, chunk in degree_class_plan(mindeg):
            selp = np.full(tcap, -1, np.int32)
            selp[: len(sel)] = sel
            acc = _k2_exists_step(
                self._pn, row_ptr, quj, qvj, jnp.asarray(selp), acc,
                width, self._steps, chunk,
            )
        reached = np.asarray(acc)[:n_q]
        return u[~reached], v[~reached]

    def state_dict(self) -> dict:
        """Checkpoint surface (``aggregate/checkpoint.py:save_workload``)."""
        return {"su": self._su, "sv": self._sv}

    def load_state_dict(self, d: dict) -> None:
        self._su, self._sv = d["su"], d["sv"]
        self._have = np.zeros(0, np.int64)
        self._have_vcap = 0
        self._pv = self._pn = self._pr = None
        self._n_packed = 0
        self._deg = np.zeros(0, np.int64)

    def edges(self) -> Set[Tuple[int, int]]:
        """Current spanner edges as raw-id pairs."""
        if self._vdict is None or len(self._su) == 0:
            return set()
        ru = self._vdict.decode(self._su)
        rv = self._vdict.decode(self._sv)
        return {
            (min(int(a), int(b)), max(int(a), int(b))) for a, b in zip(ru, rv)
        }
