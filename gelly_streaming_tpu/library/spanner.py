"""Streaming k-spanner: host-exact fold and device-batched variant.

:class:`Spanner` — behavioral parity with ``library/Spanner.java:40-118``:
per edge, if the spanner already connects the endpoints within k hops the
edge is dropped, else added (``UpdateLocal``); partial spanners merge
smaller-into-larger under the same bounded-BFS test (``CombineSpanners``).
The per-edge decision is sequential in arrival order and irregular (bounded
BFS), so this flavor stays host-side (SURVEY.md §7 build step 5), plugged
into the engine as a host-state summary (``device=False``).

:class:`DeviceSpanner` — the §7 "revisit as hop-limited relaxation on
device" variant: per window, ALL new edges test k-bounded reachability in
the spanner-as-of-window-start simultaneously — k rounds of frontier
expansion over the spanner's edge list as batched gather + scatter-or
(each round: ``frontier[:, q] |= frontier[:, p]``). Semantics delta
(documented): edges of one window cannot reject each other, so the device
spanner may keep MORE edges than the sequential fold — but the k-spanner
guarantee (every dropped edge has a ≤k-hop spanner path) holds for any
windowing, and it converges to the host result as window size shrinks.
"""

from __future__ import annotations

import functools
from typing import Iterator, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..aggregate.summary import SummaryBulkAggregation
from ..core.edgeblock import bucket_capacity
from ..summaries.adjacency import AdjacencyListGraph


class Spanner(SummaryBulkAggregation):
    """k-spanner over the edge stream (``library/Spanner.java``)."""

    device = False
    config_fields = ("k",)

    def __init__(self, k: int, transient_state: bool = False):
        super().__init__(transient_state=transient_state)
        self.k = k

    def initial_state(self, vcap: int) -> AdjacencyListGraph:
        return AdjacencyListGraph()

    def grow_state(self, state, old_vcap, new_vcap):
        return state

    def update(self, g: AdjacencyListGraph, src, dst, val, mask) -> AdjacencyListGraph:
        """Arrival-order fold (``Spanner.UpdateLocal.foldEdges``)."""
        for u, v in zip(src.tolist(), dst.tolist()):
            if not g.bounded_bfs(u, v, self.k):
                g.add_edge(u, v)
        return g

    def combine(self, g1: AdjacencyListGraph, g2: AdjacencyListGraph) -> AdjacencyListGraph:
        """Merge smaller into larger (``Spanner.CombineSpanners.reduce``)."""
        if len(g1.adj) < len(g2.adj):
            g1, g2 = g2, g1
        for u, v in g2.edges():
            if not g1.bounded_bfs(u, v, self.k):
                g1.add_edge(u, v)
        return g1

    def transform(self, g: AdjacencyListGraph, vdict) -> AdjacencyListGraph:
        # Emit a snapshot copy: the running summary keeps mutating across
        # windows, and emissions must stay stable once yielded.
        return g.copy()


@functools.partial(jax.jit, static_argnums=(6, 7))
def _k_reach(sp, sq, smask, u, v, m, num_vertices: int, k: int):
    """For each query edge i: is v[i] within k hops of u[i] over the
    spanner edge list (sp, sq)? Batched BFS: frontier[B, V] expands one
    hop per round via gather + scatter-or along the spanner edges."""
    B = u.shape[0]
    frontier = jnp.zeros((B, num_vertices), bool)
    frontier = frontier.at[jnp.arange(B), u].set(m)
    sp_c = jnp.where(smask, sp, 0)
    sq_c = jnp.where(smask, sq, 0)
    for _ in range(k):
        vals = frontier[:, sp_c] & smask[None, :]
        frontier = frontier.at[:, sq_c].max(vals)
    return frontier[jnp.arange(B), v] & m


class DeviceSpanner:
    """Batched device k-spanner. ``run(stream)`` yields the spanner edge
    set snapshot per window; ``edges()`` returns the current set (raw
    ids)."""

    def __init__(
        self,
        k: int,
        query_chunk: int = 1024,
        mem_budget_entries: int = 1 << 28,
    ):
        self.k = k
        self.query_chunk = query_chunk
        #: bound on the [B, V] frontier footprint: the per-window query
        #: batch shrinks as the vertex capacity grows, so corpus-scale
        #: vertex counts cost more dispatches instead of exploding HBM
        #: (round-1 weak item: B fixed at 1024 made the frontier O(B*V)).
        self.mem_budget_entries = mem_budget_entries
        self._su = np.zeros(0, np.int32)  # spanner edges, compact canonical
        self._sv = np.zeros(0, np.int32)
        self._have = np.zeros(0, np.int64)  # sorted canonical keys
        self._have_vcap = 0
        self._vdict = None

    def _batch_cap(self, vcap: int) -> int:
        b = max(8, min(self.query_chunk, self.mem_budget_entries // max(vcap, 1)))
        return bucket_capacity(b) // 2 if bucket_capacity(b) > b else b

    def run(self, stream) -> Iterator[Set[Tuple[int, int]]]:
        self._vdict = stream.vertex_dict
        for block in stream.blocks():
            s, d, _ = block.to_host()
            vcap = block.n_vertices
            if vcap != self._have_vcap:
                # key space changed with the capacity bucket: re-key
                self._have = np.sort(
                    self._su.astype(np.int64) * vcap
                    + self._sv.astype(np.int64)
                )
                self._have_vcap = vcap
            u = np.minimum(s, d).astype(np.int64)
            v = np.maximum(s, d).astype(np.int64)
            ok = u != v
            u, v = u[ok], v[ok]
            if u.size:
                # in-window dedup (order does not matter for the batch
                # decision) + drop edges already in the spanner (carried
                # sorted key set, merged incrementally — no per-window
                # rebuild of the whole spanner's keys)
                key = np.unique(u * vcap + v)
                pos = np.searchsorted(self._have, key)
                pos_c = np.minimum(pos, max(len(self._have) - 1, 0))
                dup = (
                    (self._have[pos_c] == key) if len(self._have)
                    else np.zeros(len(key), bool)
                )
                key = key[~dup]
                u = (key // vcap).astype(np.int32)
                v = (key % vcap).astype(np.int32)
            if u.size == 0:
                yield self.edges()
                continue
            # both directions of the current spanner, padded
            scap = bucket_capacity(2 * max(len(self._su), 1))
            sp = np.zeros(scap, np.int32)
            sq = np.zeros(scap, np.int32)
            smask = np.zeros(scap, bool)
            ns = len(self._su)
            sp[:ns], sp[ns : 2 * ns] = self._su, self._sv
            sq[:ns], sq[ns : 2 * ns] = self._sv, self._su
            smask[: 2 * ns] = True
            spj, sqj, smj = jnp.asarray(sp), jnp.asarray(sq), jnp.asarray(smask)
            keep_u, keep_v = [], []
            batch = self._batch_cap(vcap)
            for a in range(0, len(u), batch):
                b = min(a + batch, len(u))
                qcap = bucket_capacity(b - a, minimum=min(batch, 8))
                uq = np.zeros(qcap, np.int32)
                vq = np.zeros(qcap, np.int32)
                mq = np.zeros(qcap, bool)
                uq[: b - a], vq[: b - a] = u[a:b], v[a:b]
                mq[: b - a] = True
                reached = np.asarray(
                    _k_reach(
                        spj, sqj, smj,
                        jnp.asarray(uq), jnp.asarray(vq), jnp.asarray(mq),
                        vcap, self.k,
                    )
                )[: b - a]
                keep_u.append(u[a:b][~reached])
                keep_v.append(v[a:b][~reached])
            self._su = np.concatenate([self._su, *keep_u])
            self._sv = np.concatenate([self._sv, *keep_v])
            new_keys = (
                np.concatenate(keep_u).astype(np.int64) * vcap
                + np.concatenate(keep_v).astype(np.int64)
            )
            if new_keys.size:
                ins = np.searchsorted(self._have, np.sort(new_keys))
                self._have = np.insert(self._have, ins, np.sort(new_keys))
            yield self.edges()

    def state_dict(self) -> dict:
        """Checkpoint surface (``aggregate/checkpoint.py:save_workload``)."""
        return {"su": self._su, "sv": self._sv}

    def load_state_dict(self, d: dict) -> None:
        self._su, self._sv = d["su"], d["sv"]
        self._have = np.zeros(0, np.int64)
        self._have_vcap = 0

    def edges(self) -> Set[Tuple[int, int]]:
        """Current spanner edges as raw-id pairs."""
        if self._vdict is None or len(self._su) == 0:
            return set()
        ru = self._vdict.decode(self._su)
        rv = self._vdict.decode(self._sv)
        return {
            (min(int(a), int(b)), max(int(a), int(b))) for a, b in zip(ru, rv)
        }
