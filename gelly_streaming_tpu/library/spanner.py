"""Streaming k-spanner (host-state aggregation).

Behavioral parity with ``library/Spanner.java:40-118``: per edge, if the
spanner already connects the endpoints within k hops the edge is dropped,
else added (``UpdateLocal``); partial spanners merge smaller-into-larger
under the same bounded-BFS test (``CombineSpanners``).

The per-edge decision is sequential in arrival order and irregular (bounded
BFS) — the reference runs it inside a window fold, and SURVEY.md §7 (build
step 5) keeps it host-side here, plugged into the engine as a host-state
summary (``device=False``). A device-side hop-limited relaxation variant is
a future optimization, not a capability gap: the API and semantics match.
"""

from __future__ import annotations

from ..aggregate.summary import SummaryBulkAggregation
from ..summaries.adjacency import AdjacencyListGraph


class Spanner(SummaryBulkAggregation):
    """k-spanner over the edge stream (``library/Spanner.java``)."""

    device = False

    def __init__(self, k: int, transient_state: bool = False):
        super().__init__(transient_state=transient_state)
        self.k = k

    def initial_state(self, vcap: int) -> AdjacencyListGraph:
        return AdjacencyListGraph()

    def grow_state(self, state, old_vcap, new_vcap):
        return state

    def update(self, g: AdjacencyListGraph, src, dst, val, mask) -> AdjacencyListGraph:
        """Arrival-order fold (``Spanner.UpdateLocal.foldEdges``)."""
        for u, v in zip(src.tolist(), dst.tolist()):
            if not g.bounded_bfs(u, v, self.k):
                g.add_edge(u, v)
        return g

    def combine(self, g1: AdjacencyListGraph, g2: AdjacencyListGraph) -> AdjacencyListGraph:
        """Merge smaller into larger (``Spanner.CombineSpanners.reduce``)."""
        if len(g1.adj) < len(g2.adj):
            g1, g2 = g2, g1
        for u, v in g2.edges():
            if not g1.bounded_bfs(u, v, self.k):
                g1.add_edge(u, v)
        return g1

    def transform(self, g: AdjacencyListGraph, vdict) -> AdjacencyListGraph:
        # Emit a snapshot copy: the running summary keeps mutating across
        # windows, and emissions must stay stable once yielded.
        return g.copy()
