"""Streaming k-spanner: host-exact fold and device-batched variant.

:class:`Spanner` — behavioral parity with ``library/Spanner.java:40-118``:
per edge, if the spanner already connects the endpoints within k hops the
edge is dropped, else added (``UpdateLocal``); partial spanners merge
smaller-into-larger under the same bounded-BFS test (``CombineSpanners``).
The per-edge decision is sequential in arrival order and irregular (bounded
BFS), so this flavor stays host-side (SURVEY.md §7 build step 5), plugged
into the engine as a host-state summary (``device=False``).

:class:`DeviceSpanner` — the §7 "revisit as hop-limited relaxation on
device" variant: per window, ALL new edges test k-bounded reachability in
the spanner-as-of-window-start simultaneously. Semantics delta
(documented): edges of one window cannot reject each other, so the device
spanner may keep MORE edges than the sequential fold — but the k-spanner
guarantee (every dropped edge has a ≤k-hop spanner path) holds for any
windowing, and it converges to the host result as window size shrinks.

Round-4 redesign — ZERO mid-stream device→host reads: the round-3 flavor
downloaded every window's accept decisions to update host edge lists
(~0.5-3 s per D2H on the remote runtime — the recorded 98k-eps system
rate). Now accept AND merge run on device (masked packed-adjacency merge
for k=2, masked append for general k); the host keeps only the
[[novelty-tracked]] shadow it can compute beside the stream — first-seen
candidate keys (growth bound + query dedup: an edge can only ever be
accepted at its FIRST appearance, since the spanner only grows and a
once-reachable pair stays reachable) and candidate degrees (a sound upper
bound on true spanner degrees for enumeration-class planning). Emission is
a lazy set-like :class:`SpannerEdges` snapshot per window; nothing syncs
until a consumer actually reads one.
"""

from __future__ import annotations

import functools
from typing import Iterator, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..aggregate.summary import SummaryBulkAggregation
from ..core.edgeblock import bucket_capacity
from ..ops.triangles import (
    degree_class_plan,
    grow_packed_columns,
    merge_packed_adjacency,
    sticky_search_steps,
)
from ..summaries.adjacency import AdjacencyListGraph
from ..utils.keyruns import SortedRunSet

_BIG = jnp.iinfo(jnp.int32).max


@functools.partial(jax.jit, static_argnums=(1,))
def _span_row_ptr(pv, num_vertices: int):
    return jnp.searchsorted(
        pv, jnp.arange(num_vertices + 1, dtype=jnp.int32)
    ).astype(jnp.int32)


@functools.partial(jax.jit, static_argnums=(6, 7, 8))
def _k2_exists_step(pn, row_ptr, qu, qv, sel, acc, enum_width: int,
                    search_steps: int, chunk: int):
    """One min-degree class of common-neighbor existence queries; results
    scatter into the shared per-window accumulator. ``chunked_class_scan``
    bounds the [chunk, enum_width] enumeration block — a whole 1M-query
    class at width 4096 would otherwise materialize 16 GB."""
    from ..ops.triangles import (
        chunked_class_scan,
        packed_common_neighbor_exists,
    )

    def body(acc, s_i):
        selc = jnp.clip(s_i, 0, qu.shape[0] - 1)
        mask = s_i >= 0
        ex = packed_common_neighbor_exists(
            pn, row_ptr, qu[selc], qv[selc], mask, enum_width,
            search_steps=search_steps,
        )
        return acc.at[jnp.where(mask, selc, acc.shape[0])].set(ex, mode="drop")

    return chunked_class_scan(body, acc, sel, chunk)


@jax.jit
def _k2_accept_merge(pv, pn, pr, qu, qv, qmask, reached):
    """Merge the window's ACCEPTED queries (qmask & ~reached) into the
    packed sorted adjacency, entirely on device. NOT donated: emission
    snapshots hold references to each window's columns (lazy download),
    so earlier windows' arrays must stay valid."""
    keep = qmask & ~reached
    pv_new = jnp.concatenate([jnp.where(keep, qu, _BIG), jnp.where(keep, qv, _BIG)])
    pn_new = jnp.concatenate([jnp.where(keep, qv, 0), jnp.where(keep, qu, 0)])
    pr_new = jnp.zeros(pv_new.shape[0], jnp.int32)
    spv, spn, spr = jax.lax.sort((pv_new, pn_new, pr_new), num_keys=2)
    n_new = 2 * keep.sum().astype(jnp.int32)
    return merge_packed_adjacency(pv, pn, pr, spv, spn, spr, n_new)


@functools.partial(jax.jit, static_argnums=(6, 7))
def _k_reach_cnt(sp, sq, cnt, u, v, m, num_vertices: int, k: int):
    """For each query edge i: is v[i] within k hops of u[i] over the first
    ``cnt`` spanner edges (sp, sq)? Batched BFS with the query batch PACKED
    into uint32 bitplanes: frontier[B//32, V] words instead of a [B, V]
    bool — 32x the queries per byte of frontier (round-2 verdict #10; at
    V=2^23 the bool frontier admitted ~32 queries per dispatch).

    There is no scatter-OR primitive, so the hop expansion sorts the
    spanner edges by target once and ORs each target's incoming words
    with a segmented ``associative_scan`` (OR is associative), then ORs
    the per-vertex result into the frontier densely. ``B`` must be a
    multiple of 32.
    """
    smask = jnp.arange(sp.shape[0], dtype=jnp.int32) < cnt
    B = u.shape[0]
    W = B // 32
    word = jnp.arange(B) // 32
    bit = (jnp.uint32(1) << (jnp.arange(B, dtype=jnp.uint32) % 32))
    frontier = jnp.zeros((W, num_vertices), jnp.uint32)
    # distinct queries carry distinct bits, so add == bitwise-or here
    frontier = frontier.at[word, u].add(jnp.where(m, bit, 0))

    # both directions of the spanner edges, sorted by target; padding
    # targets -> sentinel V
    sp2 = jnp.concatenate([sp, sq])
    sq2 = jnp.concatenate([sq, sp])
    smask2 = jnp.concatenate([smask, smask])
    q_s, p_s = jax.lax.sort(
        (jnp.where(smask2, sq2, num_vertices), jnp.where(smask2, sp2, 0)),
        num_keys=1,
    )
    S = q_s.shape[0]
    flags = jnp.concatenate([jnp.ones(1, bool), q_s[1:] != q_s[:-1]])
    seg = jnp.arange(num_vertices, dtype=q_s.dtype)
    right = jnp.searchsorted(q_s, seg, side="right")
    left = jnp.searchsorted(q_s, seg, side="left")
    nonempty = right > left
    last = jnp.clip(right - 1, 0, S - 1)

    def seg_or(vals_t):
        def op(a, b):
            fa, va = a
            fb, vb = b
            return fa | fb, jnp.where(fb[:, None], vb, va | vb)

        _, scanned = jax.lax.associative_scan(op, (flags, vals_t))
        return scanned

    for _ in range(k):
        vals_t = frontier[:, p_s].T  # [S, W] incoming words per edge
        scanned = seg_or(vals_t)
        per_vertex = jnp.where(
            nonempty[:, None], scanned[last], jnp.uint32(0)
        )  # [V, W]
        frontier = frontier | per_vertex.T
    return (((frontier[word, v] >> (jnp.arange(B) % 32)) & 1) != 0) & m


@jax.jit
def _gen_append(sp, sq, cnt, qu, qv, keep):
    """Append the ACCEPTED queries to the spanner edge columns at device-
    computed positions (prefix sum over the keep mask). NOT donated —
    emission snapshots hold per-window references."""
    off = jnp.cumsum(keep.astype(jnp.int32)) - 1
    pos = jnp.where(keep, cnt + off, sp.shape[0])  # rejected -> dropped
    sp2 = sp.at[pos].set(qu, mode="drop")
    sq2 = sq.at[pos].set(qv, mode="drop")
    return sp2, sq2, cnt + keep.sum().astype(jnp.int32)


def _grow_cols(sp, sq, need: int):
    """Grow (or create) the general-k padded edge columns to a pow2
    bucket covering ``need`` entries."""
    cap = bucket_capacity(max(need, 16))
    if sp is None:
        return jnp.zeros(cap, jnp.int32), jnp.zeros(cap, jnp.int32)
    if cap <= sp.shape[0]:
        return sp, sq
    pad = cap - sp.shape[0]
    return (
        jnp.concatenate([sp, jnp.zeros(pad, jnp.int32)]),
        jnp.concatenate([sq, jnp.zeros(pad, jnp.int32)]),
    )


class Spanner(SummaryBulkAggregation):
    """k-spanner over the edge stream (``library/Spanner.java``)."""

    device = False
    config_fields = ("k",)

    def __init__(self, k: int, transient_state: bool = False):
        super().__init__(transient_state=transient_state)
        self.k = k

    def initial_state(self, vcap: int) -> AdjacencyListGraph:
        return AdjacencyListGraph()

    def grow_state(self, state, old_vcap, new_vcap):
        return state

    def update(self, g: AdjacencyListGraph, src, dst, val, mask) -> AdjacencyListGraph:
        """Arrival-order fold (``Spanner.UpdateLocal.foldEdges``)."""
        for u, v in zip(src.tolist(), dst.tolist()):
            if not g.bounded_bfs(u, v, self.k):
                g.add_edge(u, v)
        return g

    def combine(self, g1: AdjacencyListGraph, g2: AdjacencyListGraph) -> AdjacencyListGraph:
        """Merge smaller into larger (``Spanner.CombineSpanners.reduce``)."""
        if len(g1.adj) < len(g2.adj):
            g1, g2 = g2, g1
        for u, v in g2.edges():
            if not g1.bounded_bfs(u, v, self.k):
                g1.add_edge(u, v)
        return g1

    def transform(self, g: AdjacencyListGraph, vdict) -> AdjacencyListGraph:
        # Emit a snapshot copy: the running summary keeps mutating across
        # windows, and emissions must stay stable once yielded.
        return g.copy()


class SpannerEdges:
    """One window's spanner edge set, LAZY: device references are held and
    the download happens on first read (iteration / membership / len /
    equality). Unconsumed snapshots cost zero device→host traffic, so the
    device pipeline never stalls on the tunnel.

    Materializing also feeds the revealed TRUE accepted count back into
    the workload's capacity bound (round-4 advisor finding): under the
    normal run-loop + lazy-read consumption pattern (no checkpoint, so
    ``_host_columns``'s reconcile never fires) the carried device columns
    would otherwise grow with the stream's DISTINCT edges rather than the
    spanner size. The feedback bound is true-count-at-snapshot plus the
    entries offered SINCE, measured on the workload's monotone offer
    counter — sound under any read order (measuring "since" on the
    tightenable ``_cnt_ub`` itself is not: it understates the delta once
    a newer read reconciled and the bound regrew)."""

    __slots__ = (
        "_kind", "_arrays", "_vdict", "_set", "_workload", "_add", "_lin"
    )

    def __init__(self, kind, arrays, vdict, workload=None):
        self._kind = kind
        self._arrays = arrays
        self._vdict = vdict
        self._set = None
        self._workload = workload
        self._add = 0 if workload is None else workload._add_total
        self._lin = 0 if workload is None else workload._lineage

    def _materialize(self) -> Set[Tuple[int, int]]:
        if self._set is not None:
            return self._set
        if self._arrays is None or self._vdict is None:
            self._set = set()
            self._workload = None  # nothing to feed back; don't pin it
            return self._set
        if self._kind == "k2":
            pv, pn = jax.device_get(self._arrays)
            sel = (pv != np.iinfo(np.int32).max) & (pv < pn)
            cu, cv = pv[sel], pn[sel]
        else:
            sp, sq, cnt = jax.device_get(self._arrays)
            cu, cv = sp[: int(cnt)], sq[: int(cnt)]
        w = self._workload
        if w is not None and self._lin == w._lineage:
            true_entries = 2 * len(cu) if self._kind == "k2" else len(cu)
            w._cnt_ub = min(
                w._cnt_ub, true_entries + (w._add_total - self._add)
            )
        self._workload = None  # feedback fired; don't pin the workload
        ru = self._vdict.decode(cu)
        rv = self._vdict.decode(cv)
        self._set = {
            (min(int(a), int(b)), max(int(a), int(b)))
            for a, b in zip(ru, rv)
        }
        self._arrays = None  # release the device references once read
        return self._set

    def __iter__(self):
        return iter(self._materialize())

    def __len__(self) -> int:
        return len(self._materialize())

    def __contains__(self, e) -> bool:
        return e in self._materialize()

    def __eq__(self, other) -> bool:
        if isinstance(other, SpannerEdges):
            return self._materialize() == other._materialize()
        return self._materialize() == other

    def __repr__(self) -> str:
        return repr(self._materialize())


class DeviceSpanner:
    """Batched device k-spanner. ``run(stream)`` yields a lazy
    :class:`SpannerEdges` snapshot per window; ``edges()`` returns the
    current set (raw ids; explicit sync point).

    ``k == 2`` takes a structurally different fast path: 2-hop
    reachability between FIRST-SEEN candidate endpoints is exactly "the
    endpoint rows share a neighbor" (a direct (u,v) spanner edge would
    mean the candidate was accepted before — impossible for a first-seen
    key), so the spanner carries a packed sorted adjacency (the triangle
    pipeline's structure) and each window is a handful of class-bounded
    common-neighbor dispatches — O(Q x min-degree-class) work, no
    frontier at all. General ``k`` uses the bitplane-packed frontier BFS
    (O(k x spanner-edges x Q/32) per window). Both paths accept AND merge
    on device; no mid-stream D2H anywhere."""

    def __init__(
        self,
        k: int,
        query_chunk: int = 1024,
        mem_budget_entries: int = 1 << 28,
        expected_edges: int = 0,
    ):
        """``expected_edges``: pre-size the carried device columns for
        this many spanner edges. Purely a compile-stability hint: every
        capacity bucket is a distinct jit signature, and the remote
        compiler charges ~20-40 s per signature — growth still works
        without it."""
        self.k = k
        self.query_chunk = query_chunk
        self.expected_edges = int(expected_edges)
        #: bound on the packed-frontier footprint (uint32 words): the
        #: per-window query batch shrinks as the vertex capacity grows, so
        #: corpus-scale vertex counts cost more dispatches instead of
        #: exploding HBM.
        self.mem_budget_entries = mem_budget_entries
        self._vdict = None
        # host shadow ([[novelty-tracked]] growth): first-seen candidate
        # keys (LSM sorted runs — amortized O(N log N), no per-window
        # O(total) np.insert) + candidate degrees (sound upper bounds on
        # the accepted structures the device carries)
        self._seen = SortedRunSet()
        self._deg = np.zeros(0, np.int64)
        self._cnt_ub = 0  # upper bound on carried device entries
        # monotone sum of candidate entries ever offered to the device
        # (NEVER tightened): snapshots record it so a stale lazy read can
        # reconstruct "entries offered since this snapshot" exactly —
        # (cnt_ub_now - snapshot_ub) understates that once a newer read
        # reconciled and the bound regrew (round-5 review)
        self._add_total = 0
        self._lineage = 0  # bumped on restore; stale-lineage reads skip
        # k=2 packed-adjacency carry (device)
        self._pv = None
        self._pn = None
        self._pr = None
        # general-k edge-column carry (device)
        self._sp = None
        self._sq = None
        self._cnt = jnp.int32(0)
        # deferred checkpoint restore (device state rebuilt lazily)
        self._restore = None

    def _batch_cap(self, vcap: int) -> int:
        # budget is BYTES of frontier: [B/32, V] uint32 words hold 32
        # queries per 4 bytes, so bitplane packing buys 8x the queries of
        # the old [B, V] bool frontier at the same footprint; the kernel
        # needs B to be a multiple of 32
        words = max(1, self.mem_budget_entries // (4 * max(vcap, 1)))
        b = max(32, min(self.query_chunk, words * 32))
        b = (b // 32) * 32
        return bucket_capacity(b) // 2 if bucket_capacity(b) > b else b

    def run(self, stream) -> Iterator[SpannerEdges]:
        self._vdict = stream.vertex_dict
        for block in stream.blocks():
            s, d, _ = block.to_host()
            vcap = block.n_vertices
            self._ensure_restored(vcap)
            # host prep beside the stream: canonicalize, drop self-loops,
            # in-window dedup, FIRST-SEEN novelty filter (exact shadow of
            # what the device would accept at most once)
            u = np.minimum(s, d).astype(np.int64)
            v = np.maximum(s, d).astype(np.int64)
            ok = u != v
            u, v = u[ok], v[ok]
            if u.size:
                key = self._seen.filter_new(np.unique((u << 32) | v))
                self._seen.add(key)
                u = (key >> 32).astype(np.int32)
                v = (key & 0xFFFFFFFF).astype(np.int32)
            if u.size == 0:
                yield self._snapshot()
                continue
            if vcap > len(self._deg):
                self._deg = np.concatenate(
                    [self._deg, np.zeros(vcap - len(self._deg), np.int64)]
                )
            np.add.at(self._deg, u, 1)
            np.add.at(self._deg, v, 1)
            if self.k == 2:
                self._window_k2(u, v, vcap)
            else:
                self._window_gen(u, v, vcap)
            yield self._snapshot()

    # ------------------------------------------------------------------ #
    def _window_k2(self, u: np.ndarray, v: np.ndarray, vcap: int) -> None:
        """2-hop reachability for all first-seen window queries via
        class-bounded common-neighbor tests on the packed spanner
        adjacency, then a masked on-device accept-merge."""
        self._cnt_ub += 2 * len(u)
        self._add_total += 2 * len(u)
        self._grow_packed(max(self._cnt_ub, 2 * self.expected_edges, 1))
        row_ptr = _span_row_ptr(self._pv, vcap)
        n_q = len(u)
        qcap = bucket_capacity(n_q, minimum=32)
        qu = np.zeros(qcap, np.int32)
        qv = np.zeros(qcap, np.int32)
        qm = np.zeros(qcap, bool)
        qu[:n_q], qv[:n_q], qm[:n_q] = u, v, True
        quj, qvj, qmj = jnp.asarray(qu), jnp.asarray(qv), jnp.asarray(qm)
        acc = jnp.zeros(qcap, bool)
        # class plan from the candidate-degree shadow: >= true spanner
        # degrees, so every class's enum width covers its true rows
        mindeg = np.minimum(self._deg[u], self._deg[v])
        self._steps = sticky_search_steps(
            getattr(self, "_steps", 8), int(max(self._deg.max(), 1))
        )
        for width, sel, tcap, chunk in degree_class_plan(mindeg):
            selp = np.full(tcap, -1, np.int32)
            selp[: len(sel)] = sel
            acc = _k2_exists_step(
                self._pn, row_ptr, quj, qvj, jnp.asarray(selp), acc,
                width, self._steps, chunk,
            )
        self._pv, self._pn, self._pr = _k2_accept_merge(
            self._pv, self._pn, self._pr, quj, qvj, qmj, acc
        )

    def _window_gen(self, u: np.ndarray, v: np.ndarray, vcap: int) -> None:
        """General-k: bitplane frontier BFS per query batch against the
        window-start spanner (batches cannot reject each other — the same
        windowing relaxation as k=2), then on-device appends."""
        self._cnt_ub += len(u)
        self._add_total += len(u)
        self._sp, self._sq = _grow_cols(
            self._sp, self._sq, max(self._cnt_ub, self.expected_edges)
        )
        batch = self._batch_cap(vcap)
        cnt0 = self._cnt
        sp0, sq0 = self._sp, self._sq
        decisions = []
        for a in range(0, len(u), batch):
            b = min(a + batch, len(u))
            qcap = bucket_capacity(b - a, minimum=32)
            uq = np.zeros(qcap, np.int32)
            vq = np.zeros(qcap, np.int32)
            mq = np.zeros(qcap, bool)
            uq[: b - a], vq[: b - a] = u[a:b], v[a:b]
            mq[: b - a] = True
            uj, vj, mj = jnp.asarray(uq), jnp.asarray(vq), jnp.asarray(mq)
            reached = _k_reach_cnt(sp0, sq0, cnt0, uj, vj, mj, vcap, self.k)
            decisions.append((uj, vj, mj, reached))
        for uj, vj, mj, reached in decisions:
            self._sp, self._sq, self._cnt = _gen_append(
                self._sp, self._sq, self._cnt, uj, vj, mj & ~reached
            )

    # ------------------------------------------------------------------ #
    def _snapshot(self) -> SpannerEdges:
        if self.k == 2:
            arrays = None if self._pv is None else (self._pv, self._pn)
            return SpannerEdges("k2", arrays, self._vdict, self)
        arrays = None if self._sp is None else (self._sp, self._sq, self._cnt)
        return SpannerEdges("gen", arrays, self._vdict, self)

    def _grow_packed(self, need: int) -> None:
        self._pv, self._pn, self._pr = grow_packed_columns(
            self._pv, self._pn, self._pr, need, minimum=16
        )

    def _host_columns(self) -> Tuple[np.ndarray, np.ndarray]:
        """Current spanner edges as COMPACT canonical id columns (one
        download; the checkpoint/emission sync point). The download also
        reveals the TRUE accepted count, so reconcile the candidate-based
        capacity bound here — on a dense stream most candidates are
        rejected, and without reconcile the carried columns (and every
        per-window kernel over them) would scale with the STREAM, not the
        spanner."""
        if self._restore is not None:
            return self._restore
        if self.k == 2:
            if self._pv is None:
                return np.zeros(0, np.int32), np.zeros(0, np.int32)
            pv, pn = jax.device_get((self._pv, self._pn))
            sel = (pv != np.iinfo(np.int32).max) & (pv < pn)
            su, sv = pv[sel], pn[sel]
            self._reconcile(su, sv)
            return su, sv
        if self._sp is None:
            return np.zeros(0, np.int32), np.zeros(0, np.int32)
        sp, sq, cnt = jax.device_get((self._sp, self._sq, self._cnt))
        su, sv = sp[: int(cnt)], sq[: int(cnt)]
        self._reconcile(su, sv)
        return su, sv

    def _reconcile(self, su: np.ndarray, sv: np.ndarray) -> None:
        """Snap the capacity upper bound to the true accepted count and
        re-compact the device columns when they are >=4x oversized (the
        hysteresis avoids recompile churn: shrinking one pow2 bucket is
        not worth a fresh jit signature)."""
        true_entries = 2 * len(su) if self.k == 2 else len(su)
        self._cnt_ub = true_entries
        floor = max(true_entries, 2 * self.expected_edges
                    if self.k == 2 else self.expected_edges, 1)
        if self.k == 2:
            if self._pv is not None and (
                self._pv.shape[0] >= 4 * bucket_capacity(max(floor, 16))
            ):
                from ..ops.triangles import build_sorted_directed

                pvp, pnp, prp, _ = build_sorted_directed(su, sv)
                self._pv = jnp.asarray(pvp)
                self._pn = jnp.asarray(pnp)
                self._pr = jnp.asarray(prp)
        elif self._sp is not None and (
            self._sp.shape[0] >= 4 * bucket_capacity(max(floor, 16))
        ):
            cap = bucket_capacity(max(floor, 16))
            spn = np.zeros(cap, np.int32)
            sqn = np.zeros(cap, np.int32)
            spn[: len(su)], sqn[: len(sv)] = su, sv
            self._sp = jnp.asarray(spn)
            self._sq = jnp.asarray(sqn)
            self._cnt = jnp.int32(len(su))

    def _ensure_restored(self, vcap: int) -> None:
        """Rebuild device state from a checkpoint's host columns, once the
        first window reveals the capacity bucket."""
        if self._restore is None:
            return
        su, sv = self._restore
        self._restore = None
        self._seen = SortedRunSet(
            (su.astype(np.int64) << 32) | sv.astype(np.int64)
            if len(su) else None
        )
        self._deg = np.zeros(vcap, np.int64)
        if len(su):
            np.add.at(self._deg, su, 1)
            np.add.at(self._deg, sv, 1)
        if self.k == 2:
            self._cnt_ub = 2 * len(su)
            self._add_total = 2 * len(su)
            if len(su):
                from ..ops.triangles import build_sorted_directed

                pvp, pnp, prp, _ = build_sorted_directed(su, sv)
                self._pv = jnp.asarray(pvp)
                self._pn = jnp.asarray(pnp)
                self._pr = jnp.asarray(prp)
        else:
            self._cnt_ub = len(su)
            self._add_total = len(su)
            if len(su):
                self._sp, self._sq = _grow_cols(None, None, len(su))
                sp = np.zeros(self._sp.shape[0], np.int32)
                sq = np.zeros(self._sq.shape[0], np.int32)
                sp[: len(su)], sq[: len(sv)] = su, sv
                self._sp = jnp.asarray(sp)
                self._sq = jnp.asarray(sq)
                self._cnt = jnp.int32(len(su))

    def sync(self) -> None:
        """Block until the carried device spanner state is complete (the
        end-of-stream barrier for throughput timing), whichever carry —
        k=2 packed adjacency or general-k edge columns — is live."""
        jax.block_until_ready(
            (self._pv, self._pn, self._pr) if self.k == 2
            else (self._sp, self._sq, self._cnt)
        )

    def state_dict(self) -> dict:
        """Checkpoint surface (``aggregate/checkpoint.py:save_workload``).
        One device download at checkpoint time (a natural sync point)."""
        su, sv = self._host_columns()
        return {"su": np.ascontiguousarray(su), "sv": np.ascontiguousarray(sv)}

    def load_state_dict(self, d: dict) -> None:
        self._restore = (
            np.asarray(d["su"], np.int32), np.asarray(d["sv"], np.int32)
        )
        self._seen = SortedRunSet()
        self._deg = np.zeros(0, np.int64)
        self._cnt_ub = 0
        self._add_total = 0
        self._lineage += 1  # snapshots minted pre-restore must not feed back
        self._pv = self._pn = self._pr = None
        self._sp = self._sq = None
        self._cnt = jnp.int32(0)

    def edges(self) -> Set[Tuple[int, int]]:
        """Current spanner edges as raw-id pairs (explicit sync point)."""
        if self._vdict is None:
            return set()
        su, sv = self._host_columns()
        if len(su) == 0:
            return set()
        ru = self._vdict.decode(su)
        rv = self._vdict.decode(sv)
        return {
            (min(int(a), int(b)), max(int(a), int(b))) for a, b in zip(ru, rv)
        }
