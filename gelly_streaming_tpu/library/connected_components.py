"""Streaming Connected Components — the flagship workload.

TPU-native re-design of ``library/ConnectedComponents.java:41-126``: the
reference folds each edge into a per-partition ``DisjointSet`` (``UpdateCC``)
and merges partials smaller-into-larger (``CombineCC``). Here the summary is
a dense label table (``summaries/labels.py``): the per-shard update is a
min-label fixpoint over the shard's edge block, the cross-shard combine is a
label merge riding the engine's collectives, and the carried Merger state is
the running global label table. Emission converts labels to a
:class:`~gelly_streaming_tpu.summaries.labels.Components` view (the
``DisjointSet`` stand-in).

Usage parity with the reference::

    for comps in stream.aggregate(ConnectedComponents()):
        print(comps)   # {1=[1, 2, 3, 5], 6=[6, 7], 8=[8, 9]}
"""

from __future__ import annotations

from ..aggregate.summary import SummaryBulkAggregation, SummaryTreeReduce
from ..summaries.labels import (
    Components,
    cc_fold,
    grow_labels,
    init_labels,
    label_combine,
)


class _CCMixin:
    def initial_state(self, vcap: int):
        return init_labels(max(1, vcap))

    def grow_state(self, state, old_vcap: int, new_vcap: int):
        return grow_labels(state, new_vcap)

    def update(self, state, src, dst, val, mask):
        return cc_fold(state, src, dst, mask)

    def combine(self, a, b):
        return label_combine(a, b)

    def transform(self, state, vdict) -> Components:
        return Components.from_labels(state, vdict)


class ConnectedComponents(_CCMixin, SummaryBulkAggregation):
    """Flat-combine streaming CC (``library/ConnectedComponents.java``)."""


class ConnectedComponentsTree(_CCMixin, SummaryTreeReduce):
    """Tree-combine variant (``library/ConnectedComponentsTree.java:26-36``):
    same update/combine on the butterfly engine."""
