"""Streaming Connected Components — the flagship workload.

TPU-native re-design of ``library/ConnectedComponents.java:41-126``: the
reference folds each edge into a per-partition ``DisjointSet`` (``UpdateCC``)
and merges partials smaller-into-larger (``CombineCC``).

Three carries implement that contract here (``carry=`` constructor
option, default ``"auto"``):

- **Forest carry** (auto default with an accelerator attached): a pointer
  forest ``canon[vcap]`` updated by window-local kernels — host-computed
  touched set, root chase, T-sized local fixpoint, one masked scatter
  (``summaries/forest.py``). Per-window cost scales with the WINDOW, the
  reference's cost shape (``SummaryBulkAggregation.java:76-80``), not
  with the vertex capacity; chains canonicalize lazily at emission or
  checkpoint. This is the round-5 answer to the measured V-bound of the
  dense path (BENCH_CPU r4: 0.45x the compiled baseline at 1M windows).
  Under a sharded mesh the T-sized local fixpoint runs as the engine's
  fold+combine shape — per-shard folds over the edge columns, label
  tables merged by the bulk stack or the degree-d butterfly — so the
  vcap-sized carry never crosses the mesh.
- **Host carry** (auto default on a CPU backend): the native incremental
  union-find (``native/ingest.cpp: cuf_*``) folds each window beside the
  parser and the device keeps a pointer-forest MIRROR updated by one
  O(touched) scatter. Union-find is control flow, not math — the P6
  "centralized sequential" placement (SURVEY.md §2.5), same rationale as
  the matching/spanner host paths. Emission/checkpoint are identical to
  the forest carry (the mirror IS a forest).
- **Dense labels** (``summaries/labels.py``): full-table min-label
  fixpoint + pointer-graph combine. Used for device-transformed streams
  whose compact columns never exist on host (the windowed carries'
  touched set is host-computed) and on explicit ``carry="dense"``. A
  stream can downgrade to dense mid-run (either carry canonicalizes to
  flat labels); it never needs to upgrade back.

Emission converts either carry to a
:class:`~gelly_streaming_tpu.summaries.labels.Components` view (the
``DisjointSet`` stand-in); checkpoints always store canonical flat labels
+ touched, so the two carries share one checkpoint format.

``superbatch=K`` fuses K consecutive windows into one dispatch on every
carry (the small-window latency-cliff fix, ISSUE 2): the forest carry
runs a group-local fused fold (one vcap-sized chase/commit per GROUP,
scan over window-sized label tables), the host carry folds the group in
ONE native call (``cuf_fold_group``) with one batched mirror commit,
and dense mode scans the group's stacked block through the generic
engine. Emission VALUES are per-window identical (equivalence-tested);
a group's K records surface together after its dispatch, mid-group
snapshots reconstruct lazily on first read, and checkpoint barriers
land on group boundaries (see ``aggregate/autockpt.py``).
``transient_state`` keeps the per-window loop (its carry reset is
window-granular by definition).

Usage parity with the reference::

    for comps in stream.aggregate(ConnectedComponents()):
        print(comps)   # {1=[1, 2, 3, 5], 6=[6, 7], 8=[8, 9]}
"""

from __future__ import annotations

from typing import Any, Iterator, Optional

import jax.numpy as jnp
import numpy as np

from ..aggregate.summary import SummaryBulkAggregation, SummaryTreeReduce
from ..obs import trace as _trace
from ..summaries.forest import (
    MirrorReplay,
    TouchLog,
    WindowPrep,
    forest_superbatch,
    forest_window,
    grow_forest,
    init_forest,
    mirror_update,
    resolve_flat,
    resolve_flat_host,
)
from ..summaries.labels import (
    Components,
    cc_fold,
    grow_labels,
    init_labels,
    label_combine,
)


def _validate_min_rooted(lab: np.ndarray) -> None:
    """Reject labels violating the min-rooted invariant (mirroring
    ``cuf_load``): a corrupt table with ``label[v] > v`` would spin
    ``resolve_flat_host``/``resolve_flat`` (and the serving root chase)
    forever instead of failing fast."""
    iota = np.arange(len(lab), dtype=lab.dtype)
    if np.any(lab > iota) or np.any(lab < 0):
        raise ValueError(
            "restored labels are not a min-rooted forest "
            "(label[v] must be in [0, v])"
        )


def _auto_carry() -> str:
    """Pick the windowed-ingest carry for this process.

    ``host`` — the native incremental union-find beside the parser with a
    device pointer-forest mirror (one O(touched) scatter per window).
    Union-find is the one graph kernel that is control flow, not math: on
    a CPU backend the XLA path would re-do scalar pointer chasing as
    vector passes, so the P6 "centralized sequential" placement
    (SURVEY.md §2.5, same rationale as matching/spanner host paths) wins
    outright — measured 2.1x the compiled hash-map baseline where the
    dense device path was 0.45x.

    ``forest`` — the window-local device kernels; the default whenever an
    accelerator is attached (its HBM absorbs the table passes, and host
    cycles belong to the parser).
    """
    import jax

    if jax.default_backend() != "cpu":
        return "forest"
    try:
        from .. import native

        native.CompactUnionFind()
        return "host"
    except Exception:
        return "forest"


class _CCMixin:
    def __init__(self, *args, carry: str = "auto", **kwargs):
        super().__init__(*args, **kwargs)
        if carry not in ("auto", "forest", "host", "dense"):
            raise ValueError(f"carry must be auto/forest/host/dense, got {carry!r}")
        self.carry = carry
        self._cc_mode = None  # None | "forest" | "host" | "dense"
        self._canon = None    # device pointer forest (forest/host carries)
        self._log = None      # host TouchLog
        self._uf = None       # native CompactUnionFind (host carry)
        self._prep = None     # WindowPrep scratch (forest carry)
        self._gf_degree = 2   # resolved tree degree for the group fold

    # ---- dense-engine hooks (mesh / device-transformed fallback) ---- #
    def initial_state(self, vcap: int):
        return init_labels(max(1, vcap))

    def grow_state(self, state, old_vcap: int, new_vcap: int):
        return grow_labels(state, new_vcap)

    def update(self, state, src, dst, val, mask):
        return cc_fold(state, src, dst, mask)

    def combine(self, a, b):
        return label_combine(a, b)

    def transform(self, state, vdict) -> Components:
        return Components.from_labels(state, vdict)

    # ---- windowed-carry run loop ---- #
    def run(self, stream) -> Iterator[Components]:
        mesh = self._resolve_mesh(stream)
        eff_degree = getattr(self, "degree", 2)
        if mesh is not None and self._is_tree():
            # resolve the tree degree against the mesh EAGERLY: the host
            # carry never runs the butterfly, so without this a
            # misconfigured degree would pass silently (or warn midway
            # through the stream after a downgrade to dense). A degree
            # the mesh cannot honor degrades to 2 with ONE warning here.
            from ..parallel import comm
            from ..parallel.mesh import EDGE_AXIS

            eff_degree = comm.resolve_tree_degree(
                mesh.shape[EDGE_AXIS], eff_degree
            )
        vdict = stream.vertex_dict
        k = int(getattr(self, "superbatch", 1) or 1)
        if (k > 1 or self.superbatch_auto) and not self.transient_state:
            # the superbatched drive loop (fused K-window groups); the
            # transient_state edge case keeps the per-window loop — its
            # per-yield carry reset is inherently window-granular here
            yield from self._run_superbatched_cc(
                stream, mesh, eff_degree, vdict, k
            )
            return
        for block in stream.blocks():
            cache = getattr(block, "_host_cache", None)
            yield from self._one_window(block, cache, mesh, eff_degree, vdict)

    def _run_superbatched_cc(self, stream, mesh, eff_degree, vdict, k):
        """Drive the stream in fused K-window groups through the shared
        :func:`~gelly_streaming_tpu.summaries.groupfold.drive_group_folded`
        loop — the CC carries' ``GroupFoldable`` declaration. Each group
        folds as ONE batched dispatch (``_host_group`` /
        ``_forest_group``) with mid-group canons reconstructed lazily by
        the group's emissions; dense mode superbatches through the
        generic engine scan (``_dense_group``)."""
        from ..summaries.groupfold import drive_group_folded

        self._gf_mesh = mesh
        self._gf_vdict = vdict
        self._gf_degree = eff_degree
        yield from drive_group_folded(
            self, stream, k, controller=self._attach_control(k)
        )

    def fold_group(self, group) -> Iterator[Components]:
        """The CC carries' declared group fold: host union-find group
        call / forest group-local fused scan / dense engine scan, picked
        by the live carry mode. Supports every group — members without
        host column views downgrade to the dense carry, exactly like the
        per-window path."""
        mesh, vdict = self._gf_mesh, self._gf_vdict
        windowed = (
            group.cols is not None
            and self.carry != "dense"
            and self._cc_mode != "dense"
        )
        if windowed and self._cc_mode is None:
            self._cc_mode = (
                self.carry if self.carry != "auto" else _auto_carry()
            )
        if windowed and self._cc_mode in ("forest", "host"):
            if self._cc_mode == "host":
                yield from self._host_group(group, vdict)
            else:
                yield from self._forest_group(
                    group, mesh, self._gf_degree, vdict
                )
        else:
            if self._cc_mode in ("forest", "host"):
                self._to_dense()
            self._cc_mode = "dense"
            yield from self._dense_group(group, mesh, vdict)

    def _one_window(self, block, cache, mesh, eff_degree, vdict):
        """The per-window path (every carry; superbatch groups bypass it)."""
        if (
            cache is None
            or self.carry == "dense"
            or self._cc_mode == "dense"
        ):
            if self._cc_mode in ("forest", "host"):
                self._to_dense()
            self._cc_mode = "dense"
            self._device_block(block, mesh)
            self._sync_ref = self._summary
            yield self.transform(self._summary, vdict)
        else:
            if self._cc_mode is None:
                self._cc_mode = (
                    self.carry if self.carry != "auto" else _auto_carry()
                )
            self._ensure_windowed(block.n_vertices)
            src_h, dst_h = cache[0], cache[1]
            if self._cc_mode == "host":
                # the host union-find computes the merge exactly; a
                # mesh adds nothing (the mirror is one scatter)
                tids, roots, changed, chroots = self._uf.fold(
                    src_h, dst_h, self._vcap
                )
                self._canon = mirror_update(
                    self._canon,
                    np.concatenate([tids, changed]),
                    np.concatenate([roots, chroots]),
                    self._vcap,
                )
            else:
                self._canon, tids = forest_window(
                    self._canon, src_h, dst_h, self._vcap, self._prep,
                    mesh=mesh, tree=self._is_tree(),
                    degree=eff_degree,
                )
            self._log.add(tids)
            # sync()/bench barriers block on _summary; keep it aimed
            # at the live carry
            self._summary = {"labels": self._canon}
            self._sync_ref = self._canon
            yield Components.from_forest(self._canon, self._log, vdict)
        if self.transient_state:
            self._reset_transient()

    def _forest_group(self, group, mesh, eff_degree, vdict):
        """Fold a K-window group as ONE fused group-local dispatch
        (:func:`~gelly_streaming_tpu.summaries.forest.forest_superbatch`)
        and yield the K per-window emissions, resolution-identical to K
        :func:`forest_window` steps. Mid-group canons exist only as the
        group's delta stack; emissions reconstruct them lazily on first
        read (``Components.from_forest_replay``), so unread windows cost
        nothing and the group pays ONE vcap-sized buffer copy where the
        per-window path paid K."""
        # span covers the fold dispatch + log advance, NOT the lazy
        # per-window emissions reconstructed later on first read
        with _trace.span(
            "cc.forest_group",
            {"k": len(group), "n_vertices": int(group.n_vertices)}
            if _trace.on() else None,
        ):
            self._ensure_windowed(group.n_vertices)
            windows = [(c[0], c[1]) for c in group.cols]
            self._canon, tids_list, replay = forest_superbatch(
                self._canon, windows, self._vcap, self._prep,
                mesh=mesh, tree=self._is_tree(), degree=eff_degree,
            )
            # first-seen log advances in window order BEFORE the
            # emissions surface; each snapshot is a count into the
            # append-only log
            counts = []
            for tids in tids_list:
                self._log.add(tids)
                counts.append(self._log.count)
            self._summary = {"labels": self._canon}
            self._sync_ref = self._canon
        for i, count in enumerate(counts):
            yield Components.from_forest_replay(
                replay, i, self._log, count, vdict
            )

    def _host_group(self, group, vdict):
        """Host-carry superbatch: K union-find window folds in ONE
        native call (``CompactUnionFind.fold_group`` — the per-window
        python/ctypes fold overhead dominates sub-8k windows), ONE
        batched device mirror scatter per group from the C-deduped
        group delta. The per-window deltas the UF computes anyway become
        the group's lazy replay
        (:class:`~gelly_streaming_tpu.summaries.forest.MirrorReplay`),
        so mid-group emissions reconstruct on first read and the group
        pays one vcap buffer copy where the per-window mirror paid K."""
        # span covers the native group fold + mirror commit, NOT the
        # lazy per-window emissions reconstructed later on first read
        with _trace.span(
            "cc.host_group",
            {"k": len(group), "n_vertices": int(group.n_vertices)}
            if _trace.on() else None,
        ):
            self._ensure_windowed(group.n_vertices)
            wins, gids, groots, gtcnt = self._uf.fold_group(
                group.cols, self._vcap
            )
            ngt = int(np.sum(gtcnt))
            counts = self._log.add_grouped(gids[:ngt], gtcnt)
            # group commit on HOST: the union-find's truth is host-side
            # anyway, and one numpy fancy-assign (+ two vcap memcpys)
            # beats the XLA scatter by ~10x on the CPU backend where
            # this carry runs; the published device canon is a fresh
            # immutable buffer per group, same contract as
            # mirror_update's functional scatter
            base = np.asarray(self._canon)  # zero-copy view on CPU
            new_np = base.copy()
            new_np[gids] = groots
            self._canon = jnp.asarray(new_np)
            replay = MirrorReplay(base, wins)
            self._summary = {"labels": self._canon}
            self._sync_ref = self._canon
        for i, count in enumerate(counts):
            yield Components.from_forest_replay(
                replay, i, self._log, count, vdict
            )

    def _dense_group(self, group, mesh, vdict):
        """Dense-mode superbatch: the generic engine scan over the
        group's stacked block (``SummaryAggregation._fold_group_states``),
        one lazy ``Components`` per stacked summary row."""
        for state in self._fold_group_states(group, mesh):
            yield self.transform(state, vdict)

    def checkpoint_granularity(self) -> int:
        """Superbatching (and thus group-aligned barriers) is skipped
        under ``transient_state`` — the per-yield carry reset is
        window-granular, so every window is a valid barrier point."""
        return 1 if self.transient_state else super().checkpoint_granularity()

    def _ensure_windowed(self, vcap: int) -> None:
        if self._canon is None:
            if self._summary is not None and "touched" in self._summary:
                # restored (or converted) dense state: flat labels ARE a
                # valid forest; rebuild the host touched log from the mask
                _validate_min_rooted(np.asarray(self._summary["labels"]))
                self._canon = self._summary["labels"]
                self._log = TouchLog.from_touched_bool(
                    np.asarray(self._summary["touched"])
                )
                self._vcap = self._canon.shape[0]
            else:
                self._vcap = vcap
                self._canon = init_forest(vcap)
                self._log = TouchLog(vcap)
            if self._cc_mode == "host":
                from .. import native

                self._uf = native.CompactUnionFind()
                self._uf.load(np.asarray(self._canon))
            else:
                self._prep = WindowPrep()
        if vcap > self._vcap:
            self._canon = grow_forest(self._canon, vcap)
            self._vcap = vcap
        self._log.grow(self._vcap)

    def _to_dense(self) -> None:
        """Downgrade to the dense engine; the dense path owns growth from
        here. The host carry flattens exactly on host; the forest carry
        canonicalizes in one device fixpoint."""
        if self._cc_mode == "host":
            flat = jnp.asarray(self._uf.flatten(self._vcap))
        else:
            flat = resolve_flat(self._canon)
        touched = jnp.asarray(self._log.touched_bool(self._vcap))
        self._summary = {"labels": flat, "touched": touched}
        self._canon = None
        self._log = None
        self._uf = None
        self._prep = None

    def _reset_transient(self) -> None:
        if self._cc_mode in ("forest", "host"):
            self._canon = init_forest(self._vcap)
            self._log = TouchLog(self._vcap)
            self._summary = {"labels": self._canon}
            if self._cc_mode == "host":
                self._uf.load(np.arange(self._vcap, dtype=np.int32))
        else:
            self._summary = self.initial_state(self._vcap)

    # ---- checkpoint surface: one canonical format for all carries ---- #
    def snapshot_state(self) -> Any:
        if self._cc_mode == "host":
            return {
                "labels": self._uf.flatten(self._vcap),
                "touched": self._log.touched_bool(self._vcap),
            }
        if self._cc_mode == "forest":
            lab = resolve_flat_host(np.asarray(self._canon))
            return {
                "labels": lab,
                "touched": self._log.touched_bool(self._vcap),
            }
        return super().snapshot_state()

    def restore_state(self, state: Any, vcap: Optional[int] = None) -> None:
        super().restore_state(state, vcap)
        # undecided until the first block reveals the stream's shape; the
        # restored flat labels work as any carry
        self._cc_mode = None
        self._canon = None
        self._log = None
        self._uf = None
        self._prep = None

    # ---- serving surface (serving/server.py Servable contract) ------- #
    def servable(self, vdict=None) -> "CCServable":
        """Adapter mapping this aggregation's carry to per-window
        serving snapshots: ``labels`` is the live pointer forest (forest/
        host carries — each window's functional scatter leaves the
        published buffer immutable) or the dense flat-label table; the
        :class:`~gelly_streaming_tpu.serving.query.QueryEngine` chases
        either. Serves ``ConnectedQuery`` and ``ComponentSizeQuery``.
        ``vdict`` seeds the boot payload when restoring from a
        checkpoint before any stream is attached."""
        return CCServable(self, vdict)


def _counted_blocks(blocks, total):
    """Pass blocks through, accumulating the edge watermark into
    ``total[0]``: exact from host caches, the padded capacity (an upper
    bound) for device-transformed blocks — never a mid-stream D2H."""
    for b in blocks:
        cache = getattr(b, "_host_cache", None)
        total[0] += len(cache[0]) if cache is not None else int(b.capacity)
        yield b


class CCServable:
    """:class:`~gelly_streaming_tpu.serving.server.Servable` adapter for
    the CC aggregation. Every carry publishes one ``labels`` array per
    window — the live pointer forest for the forest/host carries (each
    window's functional update allocates a fresh buffer, so the
    published one is immutable) or the dense flat table — plus the
    stream's vertex dict for raw-id resolution.

    SUPERBATCH GRANULARITY: with ``superbatch=K`` the aggregation
    yields a group's K emissions after its fused fold, so the live
    carry read here is the END-of-group state for all K publishes (the
    per-window replay views exist only for emission consumers). That
    is safe — the CC carry is monotone, so a query sees a FRESHER
    snapshot, never a wrong one — but snapshots and their seq
    watermark advance at group granularity: serving deployments that
    need per-window snapshot pinning should run ``superbatch=1``."""

    def __init__(self, agg, vdict=None):
        from ..serving import (
            ComponentSizeQuery,
            ConnectedQuery,
            SummaryPullQuery,
        )

        # SummaryPullQuery makes the servable ROUTABLE: a shard router
        # pulls the forest as a raw-id mergeable summary (the
        # cross-shard union input) through the same query wire
        self.query_classes = (
            ConnectedQuery, ComponentSizeQuery, SummaryPullQuery,
        )
        self._agg = agg
        self._vdict = vdict

    def _payload(self, vdict) -> dict:
        agg = self._agg
        if agg._cc_mode in ("forest", "host") and agg._canon is not None:
            labels = agg._canon
        elif agg._summary is not None and "labels" in agg._summary:
            labels = agg._summary["labels"]
            if agg._donated_carry:
                # the dense superbatch carry is DONATED to the next
                # group's dispatch (in-place HBM update) — publishing
                # the live buffer would hand queries an alias that the
                # dispatch invalidates. Snapshots must own their
                # buffer; one vcap copy per publish is the price of
                # donation on serving streams.
                labels = jnp.array(labels)
        else:
            return None
        payload = {"labels": labels, "vdict": vdict}
        log = getattr(agg, "_log", None)
        if log is not None:
            # the TouchLog novelty shadow rides every snapshot (count-
            # snapshotted: the first tcount entries of an append-only
            # log never change) — the delta-pull diff's candidate
            # bound, same publish shape as the bipartiteness cover
            payload["tids"] = log.ids
            payload["tcount"] = log.count
        return payload

    def payloads(self, stream):
        vdict = stream.vertex_dict
        self._vdict = vdict
        total = [0]
        derive = getattr(stream, "_derive", None)
        counted = (
            stream if derive is None
            else derive(lambda blocks: _counted_blocks(blocks, total))
        )
        window = 0
        for _ in self._agg.run(counted):
            window += 1
            payload = self._payload(vdict)
            if payload is None:  # carry not inspectable this window
                continue
            yield payload, (total[0] or window)

    def boot_payload(self):
        """The restored summary as a servable payload (None when nothing
        was restored yet, or no vdict is known). Validates the
        min-rooted invariant like ``_ensure_windowed``: a corrupt
        checkpoint served as a boot snapshot would otherwise spin the
        query worker's root chase forever on the first query, long
        before the first live window could raise."""
        if self._vdict is None:
            return None
        payload = self._payload(self._vdict)
        if payload is None:
            return None
        _validate_min_rooted(np.asarray(payload["labels"]))
        return payload, 0


class ConnectedComponents(_CCMixin, SummaryBulkAggregation):
    """Flat-combine streaming CC (``library/ConnectedComponents.java``)."""

    @classmethod
    def sliding(cls, size: int, slide=None, **kwargs):
        """The EVENT-TIME shape of this workload: CC over a sliding
        window that retracts expired panes via bounded forest repair
        (ISSUE 18) — a configured
        :class:`~gelly_streaming_tpu.eventtime.SlidingGraphAggregator`
        restricted to the CC summary. ``size``/``slide`` are event time
        units; extra kwargs pass through (``allowed_lateness``,
        ``nshards``, ``commit_dir``, ...)."""
        from ..eventtime import SlidingGraphAggregator

        return SlidingGraphAggregator(
            size, slide, summaries=("cc",), **kwargs
        )


class ConnectedComponentsTree(_CCMixin, SummaryTreeReduce):
    """Tree-combine variant (``library/ConnectedComponentsTree.java:26-36``):
    same UDFs on the butterfly engine. The tree/bulk split only matters
    under a sharded mesh, which is exactly where the dense engine runs;
    the single-device forest carry is shared."""
