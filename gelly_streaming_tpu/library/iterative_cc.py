"""Iterative (label-emitting) connected components.

TPU-native re-design of ``example/IterativeConnectedComponents.java:56-168``,
the reference's feedback-loop CC variant: a streaming iteration whose keyed
state maps component-id -> member set, emitting corrected ``(vertex,
componentId)`` pairs as labels shrink (componentId = min raw vertex id in
the component, ``:116-121``).

The TPU form needs no feedback edge: the engine's per-window
``lax.while_loop`` min-label propagation IS the iteration (SURVEY.md §2.5
P7), so this is the shared CC device path
(``library/connected_components.py``) with a per-vertex change-only label
emission layered on top — per window, every vertex whose component id
changed is re-emitted, which is exactly the reference's "corrected labels"
stream at window granularity (SURVEY.md §7 semantic deltas).
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Tuple

from .connected_components import ConnectedComponents


class IterativeConnectedComponents:
    """``run(stream)`` yields, per window, the changed ``(vertex,
    component_id)`` pairs; ``labels()`` returns the full current mapping."""

    def __init__(self, mesh=None):
        self._agg = ConnectedComponents(mesh=mesh)
        self._labels: Dict[int, int] = {}

    def run(self, stream) -> Iterator[List[Tuple[int, int]]]:
        for comps in self._agg.run(stream):
            new_labels: Dict[int, int] = {}
            for root, members in comps.components.items():
                for v in members:
                    new_labels[v] = root
            changed = [
                (v, c) for v, c in sorted(new_labels.items())
                if self._labels.get(v) != c
            ]
            self._labels = new_labels
            yield changed

    def labels(self) -> Dict[int, int]:
        return dict(self._labels)
