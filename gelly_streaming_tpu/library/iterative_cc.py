"""Iterative (label-emitting) connected components.

TPU-native re-design of ``example/IterativeConnectedComponents.java:56-168``,
the reference's feedback-loop CC variant: a streaming iteration whose keyed
state maps component-id -> member set, emitting corrected ``(vertex,
componentId)`` pairs as labels shrink (componentId = min raw vertex id in
the component, ``:116-121``).

Two paths produce that corrected-label stream:

- **Incremental host path** (default when the native toolchain is
  available): the reference's own state shape — an incremental
  union-find plus component member lists — run beside the parser. Every
  member of a component carries the same label (the component's raw
  min), so a window's emissions reduce to per-SIDE scalar tests: a
  constituent side of a merged component re-emits its members iff its
  window-start label differs from the final min, and new vertices always
  emit. Final minima come from two vectorized scatter-mins; member
  lists merge as chunk lists; emissions assemble as array concatenations
  with a last-wins dedupe; each window yields a LAZY batch (tuples
  materialize only when read). At ``CountWindow(1)`` this is per-RECORD
  corrected-label emission (round-4 verdict weak #3's granularity)
  without any device round trip.
- **Summary-diff path** (fallback; device-transformed streams, a mesh,
  or no native lib): the shared CC device carry with a full label-map
  diff per window — identical output, heavier per-window cost.

The engine's per-window ``lax.while_loop`` min-label propagation IS the
feedback iteration (SURVEY.md §2.5 P7); no feedback edge is needed.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Tuple

import numpy as np

from .connected_components import ConnectedComponents

_I64_MAX = np.iinfo(np.int64).max


class LabelBatch:
    """One window's corrected ``(vertex, component_id)`` pairs, LAZY:
    held as two aligned arrays (ascending by vertex); python tuples
    materialize on first read (iteration / indexing), so unread windows
    cost nothing. List-like: len/iter/getitem/eq all behave like the
    summary-diff path's plain pair lists."""

    __slots__ = ("_v", "_c", "_items")

    def __init__(self, v: np.ndarray, c: np.ndarray):
        self._v = v
        self._c = c
        self._items = None

    def _list(self) -> list:
        if self._items is None:
            self._items = list(zip(self._v.tolist(), self._c.tolist()))
        return self._items

    def __iter__(self):
        return iter(self._list())

    def __len__(self) -> int:
        return len(self._v)

    def __getitem__(self, i):
        return self._list()[i]

    def __eq__(self, other):
        try:
            return self._list() == list(other)
        except TypeError:
            return NotImplemented

    def __repr__(self) -> str:
        return repr(self._list())


_EMPTY = LabelBatch(np.zeros(0, np.int64), np.zeros(0, np.int64))


class IterativeConnectedComponents:
    """``run(stream)`` yields, per window, the changed ``(vertex,
    component_id)`` pairs; ``labels()`` returns the full current mapping."""

    def __init__(self, mesh=None):
        self._agg = ConnectedComponents(mesh=mesh)
        self._labels: Dict[int, int] = {}
        self._mesh = mesh
        # incremental host state: compact root -> list of member-id array
        # chunks (compact ids); per-root raw-min label; per-vertex last
        # emitted label; the touched bitmap
        self._uf = None
        self._members: Dict[int, list] = {}
        self._rmin_arr = np.zeros(0, np.int64)
        self._label_arr = np.zeros(0, np.int64)
        self._seen = np.zeros(0, bool)
        self._vdict = None
        self._mode = None  # None | "incremental" | "diff"

    # ------------------------------------------------------------------ #
    def _try_incremental(self, stream) -> bool:
        # a mesh from EITHER the constructor or the stream context means
        # sharded execution was requested — the host path would silently
        # bypass it (and recreate per-host the carry the mesh avoids)
        if self._agg._resolve_mesh(stream) is not None:
            return False
        try:
            from .. import native

            self._uf = native.CompactUnionFind()
            return True
        except Exception:
            return False

    def _grow(self, vcap: int) -> None:
        if len(self._seen) >= vcap:
            return
        grown = np.zeros(vcap, bool)
        grown[: len(self._seen)] = self._seen
        self._seen = grown
        # sentinel must be unreachable as a LABEL: labels are raw vertex
        # ids and raw ids may be negative, so -1 would collide; no real
        # component can have min raw id I64_MAX
        glab = np.full(vcap, _I64_MAX, np.int64)
        glab[: len(self._label_arr)] = self._label_arr
        self._label_arr = glab
        grmin = np.full(vcap, _I64_MAX, np.int64)
        grmin[: len(self._rmin_arr)] = self._rmin_arr
        self._rmin_arr = grmin

    def _incremental_window(self, src, dst, vcap, vdict) -> LabelBatch:
        tids, roots, changed, chroots = self._uf.fold(src, dst, vcap)
        self._grow(vcap)
        new_mask = ~self._seen[tids]
        self._seen[tids] = True
        nids = tids[new_mask].astype(np.int64)
        nroots = roots[new_mask]
        rmin = self._rmin_arr
        # affected FINAL roots: merge targets + new vertices' homes.
        # (A demoted root never coincides with a final root — chroots are
        # post-window finds — so pre-window side snapshots are exact.)
        afr = np.unique(np.concatenate([chroots, nroots])).astype(np.int64)
        old_afr = rmin[afr].copy()       # +inf where fr had no pre-window side
        old_side = rmin[changed].copy()  # demoted sides' window-start labels
        pre_sides = {
            int(fr): self._members.get(int(fr)) for fr in afr.tolist()
        }
        # final minima: two vectorized scatter-mins
        if len(nids):
            nraw = vdict.decode(nids).astype(np.int64)
            np.minimum.at(rmin, nroots, nraw)
        if len(changed):
            np.minimum.at(rmin, chroots, old_side)
        out_ids: list = []
        out_lab: list = []
        # 1. surviving pre-window sides that lost the min
        for fr, old in zip(afr.tolist(), old_afr.tolist()):
            chunks = pre_sides[fr]
            if chunks and old != rmin[fr]:
                ids_arr = np.concatenate(chunks)
                out_ids.append(ids_arr)
                out_lab.append(np.full(len(ids_arr), rmin[fr], np.int64))
        # 2. demoted sides: emit iff their label lost; move the chunks
        for i, (r, fr) in enumerate(zip(changed.tolist(), chroots.tolist())):
            chunks = self._members.pop(r, None)
            if chunks is None:
                continue  # never a carried component (fresh this window)
            if old_side[i] != rmin[fr]:
                ids_arr = np.concatenate(chunks)
                out_ids.append(ids_arr)
                out_lab.append(np.full(len(ids_arr), rmin[fr], np.int64))
            home = self._members.get(fr)
            if home is None:
                self._members[fr] = chunks
            else:
                home.extend(chunks)
        # 3. new vertices: always emit; register one chunk per root group
        if len(nids):
            out_ids.append(nids)
            out_lab.append(rmin[nroots])
            order = np.argsort(nroots, kind="stable")
            uniq, starts = np.unique(nroots[order], return_index=True)
            for r, grp in zip(
                uniq.tolist(), np.split(nids[order], starts[1:])
            ):
                home = self._members.get(int(r))
                if home is None:
                    self._members[int(r)] = [grp]
                else:
                    home.append(grp)
        if not out_ids:
            return _EMPTY
        vs = np.concatenate(out_ids)
        ls = np.concatenate(out_lab)
        # last-wins dedupe (a side can move and re-label in one window):
        # unique over the REVERSED array keeps the final assignment
        _, ridx = np.unique(vs[::-1], return_index=True)
        last = len(vs) - 1 - ridx
        vs_u = vs[last]
        ls_u = ls[last]
        keep = self._label_arr[vs_u] != ls_u
        vs_k = vs_u[keep]
        ls_k = ls_u[keep]
        if len(vs_k) == 0:
            return _EMPTY
        self._label_arr[vs_k] = ls_k
        raw_vs = vdict.decode(vs_k).astype(np.int64)
        order = np.argsort(raw_vs, kind="stable")
        return LabelBatch(raw_vs[order], ls_k[order])

    # ------------------------------------------------------------------ #
    def _downgrade_to_diff(self) -> None:
        """Convert the union-find state into the summary-diff path's
        carry (a cache-less block arrived mid-stream): canonical flat
        compact labels restore into the shared CC aggregation, and the
        emitted-label map materializes into the diff dict."""
        vcap = len(self._seen)
        if vcap and self._uf is not None:
            self._agg.restore_state(
                {
                    "labels": self._uf.flatten(vcap).astype(np.int32),
                    "touched": self._seen.copy(),
                },
                vcap=vcap,
            )
            self._labels = self.labels()
        self._mode = "diff"

    def run(self, stream) -> Iterator[List[Tuple[int, int]]]:
        vdict = stream.vertex_dict
        self._vdict = vdict
        blocks = stream.blocks()
        pending = None
        if self._mode != "diff":
            for block in blocks:
                cache = getattr(block, "_host_cache", None)
                if self._mode is None:
                    self._mode = (
                        "incremental"
                        if cache is not None and self._try_incremental(stream)
                        else "diff"
                    )
                    if self._mode == "diff":
                        pending = block
                        break
                if cache is None:
                    # device-transformed continuation: hand the carried
                    # state to the summary-diff path and keep streaming
                    self._downgrade_to_diff()
                    pending = block
                    break
                yield self._incremental_window(
                    cache[0], cache[1], block.n_vertices, vdict
                )
            else:
                return
        from itertools import chain

        from ..core.stream import SimpleEdgeStream

        rest = (
            chain([pending], blocks) if pending is not None else blocks
        )
        shim = SimpleEdgeStream(
            _blocks=lambda: rest, _vdict=vdict,
            context=stream.get_context(),
        )
        for comps in self._agg.run(shim):
            new_labels: Dict[int, int] = {}
            for root, members in comps.components.items():
                for v in members:
                    new_labels[v] = root
            changed = [
                (v, c) for v, c in sorted(new_labels.items())
                if self._labels.get(v) != c
            ]
            self._labels = new_labels
            yield changed

    def labels(self) -> Dict[int, int]:
        if self._mode == "incremental":
            idx = np.nonzero(self._seen)[0]
            if len(idx) == 0:
                return {}
            raws = self._vdict.decode(idx).astype(np.int64)
            labs = self._label_arr[idx]
            return {int(v): int(c) for v, c in zip(raws, labs)}
        return dict(self._labels)
