"""Incremental PageRank over the streaming graph (BASELINE config #4).

Not present in the reference — BASELINE.json adds it as a new algorithm on
the TPU path, cast as ``applyOnNeighbors``-style message passing. Design:

- The accumulated graph is carried as device edge arrays (compact ids,
  capacity-bucketed, like the triangle path).
- Per window, power iteration runs **warm-started from the previous
  window's ranks** — that is the "incremental" part: after a small batch of
  new edges the previous ranks are near the new fixpoint and few iterations
  are needed, vs. cold-start O(log(1/tol)/log(1/d)) every window.
- One iteration = scatter-add of ``d * rank[src]/outdeg[src]`` messages
  over the edge list (``jax.ops``-style ``segment_sum``: P2 vertex-keyed
  parallelism) + teleport and dangling mass terms; convergence by L1 delta.

Semantics: ranks over the *undirected-as-given* directed edge set; dangling
vertices (out-degree 0) redistribute their mass uniformly, the standard
convention, so ranks sum to 1.

Performance shape (the round-1 lesson): the whole window — edge append,
warm-start renormalization, and the fixpoint — is ONE jitted dispatch with
the carry buffers donated. The first build of this workload issued ~8 eager
device ops per window (``to_host`` → accumulator append → rank pad/where →
fixpoint), which through a remote-TPU tunnel (0.03–90 ms per dispatch)
bounded the stream at ~1.1e5 edges/s no matter how fast the kernel was.
Early exit from the power iteration is a ``lax.while_loop`` over fixed
``chunk``-length ``lax.scan`` bodies: trip count stays data-dependent (no
wasted full-edge passes after convergence) but the executable is still one
program per (edge-capacity, vertex-capacity) bucket pair.
"""

from __future__ import annotations

import functools
from typing import Iterator, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.edgeblock import bucket_capacity
from ..summaries.groupfold import GroupFoldable


class PageRankEmission(NamedTuple):
    """Per-window emission. ``iterations``/``l1_delta`` are device scalars
    (sync on first read) so successive windows pipeline on device instead
    of blocking per emission; ``int()``/``float()`` them to materialize."""

    window: int
    num_vertices: int
    iterations: "jax.Array"
    l1_delta: "jax.Array"


@functools.lru_cache(maxsize=None)
def _make_pr_window_body(mesh, chunk: int, max_chunks: int):
    """Build the UN-jitted one-window fold ``step(carry, bsrc, bdst,
    n_edges0, n_new, n_seen, damping, tol) -> (carry, delta, iters)``.

    Shared verbatim by the per-window jit (:func:`_build_pr_step`) and
    the superbatch scan body (:func:`_build_pr_group_step`) so the two
    paths cannot drift — the group fold's value-identity contract
    (``summaries/groupfold.py``) rests on this being ONE function.

    One window = append + warm-start + chunked fixpoint, one dispatch.
    ``carry`` is ``(src, dst, ranks)`` device arrays at bucketed capacity,
    donated so the buffers are reused in place. ``bsrc``/``bdst`` are the
    window's padded block columns; only the first ``n_new`` entries are
    real — the padding is written into the carry too, but always beyond
    ``n_edges0 + n_new`` (the host guarantees edge capacity >= n_edges0 +
    block capacity) and masked out of every reduction, then overwritten by
    the next window's append.

    With ``mesh``, the fixpoint runs inside ``shard_map``: the edge
    columns split over the ``"edges"`` axis, each shard scatters its
    slice's rank messages into a replicated vertex table, and the
    partials ``psum`` over ICI per iteration (P1 + P3, the same shape as
    the CC engine's sharded fold). The while_loop trip count stays
    consistent across shards because every per-iteration decision reads
    post-psum (replicated) values.
    """
    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        from ..parallel import comm
        from ..parallel.mesh import EDGE_AXIS

    def fixpoint(src, dst, mask, ranks, active, n, damping, tol,
                 axis_name=None):
        num_vertices = ranks.shape[0]
        m = mask.astype(ranks.dtype)
        ones = jnp.zeros(num_vertices, ranks.dtype).at[src].add(m)
        if axis_name is not None:
            ones = jax.lax.psum(ones, axis_name)
        out_deg = jnp.maximum(ones, 1.0)
        dangling = active & (ones == 0.0)

        def one_iter(r):
            contrib = jnp.where(mask, r[src] / out_deg[src], 0.0)
            new = jnp.zeros(num_vertices, r.dtype).at[dst].add(contrib)
            if axis_name is not None:
                new = jax.lax.psum(new, axis_name)
            dangling_mass = jnp.sum(jnp.where(dangling, r, 0.0))
            new = (1.0 - damping) / n + damping * (new + dangling_mass / n)
            new = jnp.where(active, new, 0.0)
            return new, jnp.abs(new - r).sum()

        # Early exit at chunk granularity: a while_loop whose body is a
        # fixed `chunk`-length scan with a converged-freeze flag. Data-
        # dependent trip count without per-iteration host sync; at most
        # chunk-1 frozen (wasted) passes after convergence.
        def scan_body(c, _):
            r, delta, iters, done = c
            new, dl = one_iter(r)
            r = jnp.where(done, r, new)
            delta = jnp.where(done, delta, dl)
            iters = iters + (~done).astype(jnp.int32)
            done = done | (dl <= tol)
            return (r, delta, iters, done), None

        def chunk_body(state):
            k, inner = state
            inner, _ = jax.lax.scan(scan_body, inner, None, length=chunk)
            return k + 1, inner

        def chunk_cond(state):
            k, (_, _, _, done) = state
            return (~done) & (k < max_chunks)

        init = (ranks, jnp.asarray(jnp.inf, ranks.dtype), jnp.int32(0),
                jnp.bool_(False))
        _, (ranks, delta, iters, _) = jax.lax.while_loop(
            chunk_cond, chunk_body, (jnp.int32(0), init)
        )
        return ranks, delta, iters

    def step(carry, bsrc, bdst, n_edges0, n_new, n_seen, damping, tol):
        src, dst, ranks = carry
        ecap = src.shape[0]
        num_vertices = ranks.shape[0]
        src = jax.lax.dynamic_update_slice(src, bsrc, (n_edges0,))
        dst = jax.lax.dynamic_update_slice(dst, bdst, (n_edges0,))
        n_edges = n_edges0 + n_new

        # Warm start: never-ranked active vertices enter at uniform mass,
        # then renormalize so the seen ranks sum to 1. (Padding slots stay
        # 0: the `active` mask keeps them out of teleport/dangling terms.)
        active = jnp.arange(num_vertices) < n_seen
        n = jnp.maximum(n_seen, 1).astype(ranks.dtype)
        ranks = jnp.where(active & (ranks == 0.0), 1.0 / n, ranks)
        ranks = ranks / jnp.maximum(ranks.sum(), 1e-30)
        mask = jnp.arange(ecap) < n_edges

        if mesh is None:
            ranks, delta, iters = fixpoint(
                src, dst, mask, ranks, active, n, damping, tol
            )
        else:
            def shard_fn(src_s, dst_s, mask_s, ranks, active, n, damping,
                         tol):
                return fixpoint(
                    src_s, dst_s, mask_s, ranks, active, n, damping, tol,
                    axis_name=EDGE_AXIS,
                )

            ranks, delta, iters = comm.shard_map(
                shard_fn, mesh,
                in_specs=(P(EDGE_AXIS), P(EDGE_AXIS), P(EDGE_AXIS),
                          P(), P(), P(), P(), P()),
                out_specs=(P(), P(), P()),
            )(src, dst, mask, ranks, active, n, damping, tol)
        return (src, dst, ranks), delta, iters

    return step


@functools.lru_cache(maxsize=None)
def _build_pr_step(mesh, chunk: int, max_chunks: int):
    """The jitted per-window step over the shared window body, carry
    donated (in-place HBM reuse; see :func:`_make_pr_window_body`)."""
    return jax.jit(
        _make_pr_window_body(mesh, chunk, max_chunks), donate_argnums=(0,)
    )


@functools.lru_cache(maxsize=None)
def _build_pr_group_step(mesh, chunk: int, max_chunks: int):
    """K window steps fused into ONE jitted ``lax.scan`` dispatch — the
    :class:`~gelly_streaming_tpu.summaries.groupfold.GroupFoldable`
    fold for PageRank, mirroring the engine's ``_superbatch_step``.

    ``superstep(carry, bsrc, bdst, n_edges0, n_new, n_seen, damping,
    tol)`` scans the shared window body over the stacked ``[K, cap]``
    block columns with per-window ``n_new``/``n_seen`` scalars riding
    the scan's xs and the edge watermark carried as a traced scalar
    (window k appends where windows < k left off — sequential window
    semantics preserved inside one dispatch). The carry is DONATED like
    the per-window step's; the stacked per-window ``(delta, iters)``
    outputs are fresh buffers backing the group's lazy emissions."""
    window_body = _make_pr_window_body(mesh, chunk, max_chunks)

    def superstep(carry, bsrc, bdst, n_edges0, n_new, n_seen, damping,
                  tol):
        def body(c, xs):
            cr, n_e = c
            bs, bd, nn, ns = xs
            cr, delta, iters = window_body(
                cr, bs, bd, n_e, nn, ns, damping, tol
            )
            return (cr, n_e + nn), (delta, iters)

        (carry, _n_end), (deltas, iters) = jax.lax.scan(
            body, (carry, n_edges0), (bsrc, bdst, n_new, n_seen)
        )
        return carry, deltas, iters

    return jax.jit(superstep, donate_argnums=(0,))


class IncrementalPageRank(GroupFoldable):
    """``run(stream)`` folds each window's edges into the carried graph and
    re-converges ranks from the previous fixpoint.

    ``max_iter`` bounds total power iterations per window (rounded up to a
    multiple of ``chunk``, the early-exit granularity).

    ``superbatch=K`` fuses K consecutive windows into ONE scanned
    dispatch (the :class:`GroupFoldable` declaration — the same
    small-window latency-cliff fix the engine and CC carries got in
    PR 2): the shared window body scans over the group's stacked
    columns with the rank/edge carry donated, per-window
    ``(iterations, l1_delta)`` surfacing as lazy device slices of the
    scan's stacked outputs. Emission VALUES are per-window identical
    (the per-window seen-vertex counts reconstruct exactly from the
    group encode — ``SuperbatchGroup.n_seen_per_window``); a group's K
    emissions surface together after its dispatch, and checkpoint
    barriers land on group boundaries (:meth:`checkpoint_granularity`).
    """

    def __init__(
        self,
        damping: float = 0.85,
        tol: float = 1e-6,
        max_iter: int = 100,
        chunk: int = 10,
        mesh=None,
        superbatch: int = 1,
    ):
        self.damping = damping
        self.tol = tol
        self.chunk = chunk
        self.max_chunks = max(1, -(-max_iter // chunk))
        #: optional device mesh: the per-window fixpoint shards the edge
        #: columns over the ``"edges"`` axis with per-iteration psum
        self.mesh = mesh
        #: ``superbatch="auto"``: the controller drives the fused path
        #: exactly like CC/bipartiteness — and because this carry's
        #: per-window cost is the fixpoint (which fusion cannot
        #: remove), the controller's JOB here is to hold K=1. That
        #: negative control is committed bench evidence
        #: (``BENCH_AUTOTUNE_CPU.json`` ``pagerank_hold`` cell): a
        #: controller that starts paying for fusion that buys nothing
        #: regresses a benchguard-watched cell.
        self.superbatch_auto = superbatch == "auto"
        if self.superbatch_auto:
            superbatch = 1
        elif isinstance(superbatch, str):
            raise ValueError(
                f'superbatch must be an int >= 1 or "auto", '
                f"got {superbatch!r}"
            )
        elif superbatch < 1:
            raise ValueError(f"superbatch must be >= 1, got {superbatch}")
        self.superbatch = int(superbatch)
        #: the live ControlPlane of an auto run (None otherwise) — same
        #: seam as ``SummaryAggregation.control``
        self.control = None
        self._step = _build_pr_step(mesh, self.chunk, self.max_chunks)
        self._group_step = None  # built on first group fold
        self._carry = None  # (src, dst, ranks) device arrays
        self._n_edges = 0  # host mirror of the append position
        self._vdict = None
        self._w = 0  # next emission's window index (run-scoped)
        #: carried seen-vertex watermark: ``max(restored, 1 + max compact
        #: id streamed so far)``. Derived from the STREAM's ids, not from
        #: ``len(vertex_dict)`` — the live dict runs ahead of consumption
        #: under prefetch/group packing (and a group-boundary checkpoint
        #: therefore restores an over-full dict), so dict length is not a
        #: per-window value; the id watermark is, for both dictionary
        #: kinds (sequential first-seen assignment / identity observe).
        self._n_seen = 0

    # ------------------------------------------------------------------ #
    def _ensure_capacity(self, block_cap: int, vcap: int) -> None:
        """Grow carry buffers (host-side, log-many times over the stream).

        Edge capacity must hold n_edges + the whole padded block so the
        in-step ``dynamic_update_slice`` never clamps into real edges.
        """
        # the sharded step splits the edge columns over the mesh's edge
        # axis: capacity must be divisible by (>= and pow2 covers) it
        min_cap = 8
        if self.mesh is not None:
            min_cap = max(min_cap, dict(self.mesh.shape).get("edges", 1))
        if self._carry is None:
            ecap = bucket_capacity(self._n_edges + block_cap, minimum=min_cap)
            self._carry = (
                jnp.zeros(ecap, jnp.int32),
                jnp.zeros(ecap, jnp.int32),
                jnp.zeros(vcap, jnp.float32),
            )
            return
        src, dst, ranks = self._carry
        ecap = bucket_capacity(self._n_edges + block_cap, minimum=min_cap)
        if ecap > src.shape[0]:
            grow = ecap - src.shape[0]
            src = jnp.pad(src, (0, grow))
            dst = jnp.pad(dst, (0, grow))
        if vcap > ranks.shape[0]:
            ranks = jnp.pad(ranks, (0, vcap - ranks.shape[0]))
        self._carry = (src, dst, ranks)

    def run(self, stream) -> Iterator[PageRankEmission]:
        self._vdict = stream.vertex_dict
        self._w = 0
        if self.superbatch > 1 or self.superbatch_auto:
            from ..summaries.groupfold import drive_group_folded

            yield from drive_group_folded(
                self, stream, self.superbatch,
                controller=self._attach_control(self.superbatch),
            )
            return
        for block in stream.blocks():
            yield self._one_window(block)

    def _attach_control(self, k: int):
        """The shared controller-attach rule (mirrors
        ``SummaryAggregation._attach_control`` — this class declares
        :class:`GroupFoldable` directly rather than through the
        aggregation base): None unless auto; a pre-set plane is
        honored; otherwise the stock default plane is built and kept
        on ``self.control``."""
        if not self.superbatch_auto:
            return None
        if self.control is None:
            from ..control import default_plane

            self.control = default_plane(k)
        return self.control

    def _one_window(self, block) -> PageRankEmission:
        """The per-window fold (shared by the plain run loop and the
        group-fold fallback for groups packed without column views)."""
        n_new = int(np.asarray(block.to_host()[0]).shape[0])
        cache = getattr(block, "_host_cache", None)
        if cache is not None and len(cache[0]):
            self._n_seen = max(
                self._n_seen,
                1 + int(max(cache[0].max(), cache[1].max())),
            )
        elif cache is None:
            # device-transformed block: no host ids to advance the
            # watermark from; the live dict is the only source
            self._n_seen = max(self._n_seen, len(self._vdict))
        n_seen = self._n_seen
        self._ensure_capacity(block.capacity, block.n_vertices)
        self._carry, delta, iters = self._step(
            self._carry, block.src, block.dst,
            jnp.int32(self._n_edges), jnp.int32(n_new),
            jnp.int32(n_seen), self.damping, self.tol,
        )
        self._n_edges += n_new
        w = self._w
        self._w += 1
        return PageRankEmission(w, n_seen, iters, delta)

    # ---- GroupFoldable declaration (summaries/groupfold.py) ---------- #
    def group_supported(self, group) -> bool:
        """The fused path needs the packer's host column views (the
        per-window seen-vertex watermark reconstructs from their compact
        ids); groups packed from pre-built blocks fall back."""
        return group.cols is not None

    def fold_group(self, group) -> Iterator[PageRankEmission]:
        """K windows as ONE scanned dispatch (see class docstring): pad
        the group's columns to one ``[K, wcap]`` stack, advance the
        carried seen-vertex watermark per member window, scan the shared
        window body with the carry donated, and emit the K per-window
        ``(iterations, l1_delta)`` as lazy device slices of the scan's
        stacked outputs."""
        from ..core.emission import iter_unstacked
        from ..obs import trace as _trace

        k = len(group)
        cols = group.cols
        lens = [len(c[0]) for c in cols]
        # per-window seen counts from the carried watermark + each
        # window's compact ids — exactly the per-window path's sequence
        # (SuperbatchGroup.n_seen_per_window applies the same rule from
        # the packer's side; the carried form survives checkpoint
        # restore, where the dict itself may have run ahead)
        n_seen_w = []
        n = self._n_seen
        for s, d, _v in cols:
            if len(s):
                n = max(n, 1 + int(max(s.max(), d.max())))
            n_seen_w.append(n)
        self._n_seen = n
        wmin = 8
        if self.mesh is not None:
            wmin = max(wmin, dict(self.mesh.shape).get("edges", 1))
        wcap = bucket_capacity(max(lens), minimum=wmin)
        total_new = int(sum(lens))
        # edge capacity must hold every member window's padded append:
        # the LAST window writes [wcap] at n_edges + (total_new - its
        # own length), the deepest offset of the group
        self._ensure_capacity(
            total_new - lens[-1] + wcap, group.n_vertices
        )
        bsrc = np.zeros((k, wcap), np.int32)
        bdst = np.zeros((k, wcap), np.int32)
        for i, (s, d, _v) in enumerate(cols):
            bsrc[i, : lens[i]] = s
            bdst[i, : lens[i]] = d
        if self._group_step is None:
            self._group_step = _build_pr_group_step(
                self.mesh, self.chunk, self.max_chunks
            )
        with _trace.span(
            "pagerank.group",
            {"k": k, "edges": total_new,
             "n_vertices": int(group.n_vertices)}
            if _trace.on() else None,
        ):
            self._carry, deltas, iters = self._group_step(
                self._carry, jnp.asarray(bsrc), jnp.asarray(bdst),
                jnp.int32(self._n_edges),
                jnp.asarray(np.asarray(lens, np.int32)),
                jnp.asarray(np.asarray(n_seen_w, np.int32)),
                self.damping, self.tol,
            )
        self._n_edges += total_new
        w0 = self._w
        self._w += k
        for i, (delta_i, iters_i) in enumerate(
            iter_unstacked((deltas, iters), k)
        ):
            yield PageRankEmission(
                w0 + i, int(n_seen_w[i]), iters_i, delta_i
            )

    def fold_group_fallback(self, group) -> Iterator[PageRankEmission]:
        """Per-window fold of a group without usable column views —
        correctness never depends on how a group was packed. Cache-less
        (device-transformed) blocks carry no host ids, so their seen
        count falls back to the live dict, which may run AHEAD of
        consumption under the drive loop's group prefetch — the same
        documented looseness every prefetched per-window stream has
        (``SimpleEdgeStream.prefetched``); streams that need exact
        per-window teleport mass keep host column views."""
        for block in group.blocks():
            yield self._one_window(block)

    def sync(self) -> None:
        """Block until the carried (edges, ranks) device state is complete
        — the end-of-stream barrier for throughput timing."""
        jax.block_until_ready(self._carry)

    # ------------------------------------------------------------------ #
    @property
    def _ranks(self):
        """Rank vector (or None before the first window) — kept as a
        property for checkpoint/test compatibility with the round-1 class."""
        return None if self._carry is None else self._carry[2]

    def state_dict(self) -> dict:
        """Checkpoint surface (``aggregate/checkpoint.py:save_workload``).
        The vertex dictionary is saved alongside by ``save_workload``."""
        if self._carry is None:
            return {"edges": {"src": np.zeros(0, np.int32),
                              "dst": np.zeros(0, np.int32)},
                    "ranks": None}
        src, dst, ranks = self._carry
        n = self._n_edges
        return {
            "edges": {"src": np.asarray(src)[:n], "dst": np.asarray(dst)[:n]},
            "ranks": np.asarray(ranks),
            "n_seen": int(self._n_seen),
        }

    def load_state_dict(self, d: dict) -> None:
        if d["ranks"] is None:
            self._carry = None
            self._n_edges = 0
            self._n_seen = 0
            return
        s = np.asarray(d["edges"]["src"], np.int32)
        t = np.asarray(d["edges"]["dst"], np.int32)
        self._n_edges = len(s)
        ecap = bucket_capacity(self._n_edges)
        ranks = np.asarray(d["ranks"], np.float32)
        # legacy checkpoints predate the carried watermark: every seen
        # vertex holds strictly positive mass after a fixpoint (teleport
        # term), padding slots hold exactly 0 — the count reconstructs
        self._n_seen = int(d.get("n_seen", np.count_nonzero(ranks)))
        self._carry = (
            jnp.asarray(np.pad(s, (0, ecap - len(s)))),
            jnp.asarray(np.pad(t, (0, ecap - len(t)))),
            jnp.asarray(ranks),
        )

    # ---- serving surface (serving/server.py Servable contract) ------- #
    def servable(self, vdict=None) -> "RankServable":
        """Adapter publishing the rank vector per window for
        ``RankQuery`` point lookups. Unlike the CC/degree carries, the
        PageRank step DONATES its carry buffers (the published array
        would be invalidated by the next window's dispatch), so the
        adapter snapshots ranks with one device-side copy per window."""
        return RankServable(self, vdict)

    def ranks(self) -> dict:
        """Current (raw vertex id -> rank), seen vertices only."""
        if self._carry is None:
            return {}
        n = len(self._vdict)
        r = np.asarray(self._carry[2])[:n]
        raw = self._vdict.decode(np.arange(n))
        return {int(v): float(x) for v, x in zip(raw, r)}


class RankServable:
    """:class:`~gelly_streaming_tpu.serving.server.Servable` adapter for
    :class:`IncrementalPageRank`. The window step donates its carry, so
    each published snapshot is ``jnp.copy`` of the rank vector — one
    device-side copy per window; readers must never hold a donated
    buffer (accessing it after the next dispatch raises). With
    ``superbatch=K`` a group's K emissions surface together, so all K
    publishes copy the END-of-group ranks and snapshots advance at
    group granularity (the CCServable caveat; run ``superbatch=1`` for
    per-window snapshot pinning)."""

    def __init__(self, workload: IncrementalPageRank, vdict=None):
        from ..serving import RankQuery

        self.query_classes = (RankQuery,)
        self._workload = workload
        self._vdict = vdict

    def payloads(self, stream):
        pr = self._workload
        vdict = stream.vertex_dict
        self._vdict = vdict
        for _ in pr.run(stream):
            yield (
                {"ranks": jnp.copy(pr._carry[2]), "vdict": vdict},
                pr._n_edges,
            )

    def boot_payload(self):
        pr = self._workload
        if pr._carry is None or self._vdict is None:
            return None
        return (
            {"ranks": jnp.copy(pr._carry[2]), "vdict": self._vdict},
            pr._n_edges,
        )
