"""Incremental PageRank over the streaming graph (BASELINE config #4).

Not present in the reference — BASELINE.json adds it as a new algorithm on
the TPU path, cast as ``applyOnNeighbors``-style message passing. Design:

- The accumulated graph is carried as device edge arrays (compact ids,
  capacity-bucketed, like the triangle path).
- Per window, power iteration runs inside a ``lax.while_loop``
  **warm-started from the previous window's ranks** — that is the
  "incremental" part: after a small batch of new edges the previous ranks
  are near the new fixpoint and few iterations are needed, vs. cold-start
  O(log(1/tol)/log(1/d)) every window.
- One iteration = scatter-add of ``d * rank[src]/outdeg[src]`` messages
  over the edge list (``jax.ops``-style ``segment_sum``: P2 vertex-keyed
  parallelism) + teleport and dangling mass terms; convergence by L1 delta.

Semantics: ranks over the *undirected-as-given* directed edge set; dangling
vertices (out-degree 0) redistribute their mass uniformly, the standard
convention, so ranks sum to 1.
"""

from __future__ import annotations

import functools
from typing import Iterator, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.edgeblock import EdgeAccumulator


class PageRankEmission(NamedTuple):
    """Per-window emission. ``iterations``/``l1_delta`` are device scalars
    (sync on first read) so successive windows pipeline on device instead
    of blocking per emission; ``int()``/``float()`` them to materialize."""

    window: int
    num_vertices: int
    iterations: "jax.Array"
    l1_delta: "jax.Array"


@functools.partial(jax.jit, static_argnums=(5,), static_argnames=("max_iter",))
def _pagerank_fixpoint(
    ranks, src, dst, n_edges, n_seen, num_vertices: int,
    damping=0.85, tol=1e-6, max_iter: int = 100,
):
    """Warm-started power iteration to fixpoint on the accumulated edges.

    ``num_vertices`` is the (static) capacity; ``n_seen``/``n_edges`` the
    dynamic real counts — capacity slots beyond them are held at rank 0 /
    masked out and get neither teleport nor dangling mass, so ranks over
    the seen vertices sum to 1 regardless of padding.
    """
    mask = jnp.arange(src.shape[0]) < n_edges
    m = mask.astype(ranks.dtype)
    active = jnp.arange(num_vertices) < n_seen
    n = jnp.maximum(n_seen, 1).astype(ranks.dtype)
    ones = jnp.zeros(num_vertices, ranks.dtype).at[src].add(m)
    out_deg = jnp.maximum(ones, 1.0)
    dangling = active & (ones == 0.0)

    # Fixed-trip lax.scan with a converged-freeze flag instead of a
    # while_loop: trip count is static, so every window reuses one
    # executable regardless of how many iterations actually apply, and a
    # frozen step costs only the already-paid vector work. (Data-dependent
    # while_loop trip counts also interact badly with this environment's
    # remote-TPU runtime.)
    def body(carry, _):
        r, done = carry
        contrib = jnp.where(mask, r[src] / out_deg[src], 0.0)
        new = jnp.zeros(num_vertices, r.dtype).at[dst].add(contrib)
        dangling_mass = jnp.sum(jnp.where(dangling, r, 0.0))
        new = (1.0 - damping) / n + damping * (new + dangling_mass / n)
        new = jnp.where(active, new, 0.0)
        delta = jnp.abs(new - r).sum()
        applied = ~done
        r_out = jnp.where(done, r, new)
        done = done | (delta <= tol)
        return (r_out, done), (delta, applied)

    (ranks, _), (deltas, applied) = jax.lax.scan(
        body, (ranks, jnp.bool_(False)), None, length=max_iter
    )
    iters = applied.sum().astype(jnp.int32)
    last = jnp.maximum(iters - 1, 0)
    return ranks, deltas[last], iters


class IncrementalPageRank:
    """``run(stream)`` folds each window's edges into the carried graph and
    re-converges ranks from the previous fixpoint."""

    def __init__(
        self,
        damping: float = 0.85,
        tol: float = 1e-6,
        max_iter: int = 100,
    ):
        self.damping = damping
        self.tol = tol
        self.max_iter = max_iter
        self._edges = EdgeAccumulator()
        self._ranks = None
        self._vdict = None

    def run(self, stream) -> Iterator[PageRankEmission]:
        self._vdict = stream.vertex_dict
        for w, block in enumerate(stream.blocks()):
            s, d, _ = block.to_host()
            self._edges.append(s, d)
            vcap = block.n_vertices
            n_seen = len(self._vdict)
            if self._ranks is None:
                init = (np.arange(vcap) < n_seen) / max(n_seen, 1)
                self._ranks = jnp.asarray(init, jnp.float32)
            else:
                if vcap > self._ranks.shape[0]:
                    pad = jnp.zeros(vcap - self._ranks.shape[0], jnp.float32)
                    self._ranks = jnp.concatenate([self._ranks, pad])
                # newly-seen vertices warm-start at uniform mass, then
                # renormalize so the seen ranks sum to 1
                active = jnp.arange(vcap) < n_seen
                self._ranks = jnp.where(
                    active & (self._ranks == 0.0), 1.0 / n_seen, self._ranks
                )
                self._ranks = self._ranks / self._ranks.sum()
            self._ranks, delta, iters = _pagerank_fixpoint(
                self._ranks,
                self._edges.src,
                self._edges.dst,
                jnp.int32(self._edges.n_edges),
                jnp.int32(n_seen),
                vcap,
                damping=self.damping,
                tol=self.tol,
                max_iter=self.max_iter,
            )
            yield PageRankEmission(w, len(self._vdict), iters, delta)

    def state_dict(self) -> dict:
        """Checkpoint surface (``aggregate/checkpoint.py:save_workload``).
        The vertex dictionary is saved alongside by ``save_workload``."""
        return {
            "edges": self._edges.state_dict(),
            "ranks": None if self._ranks is None else np.asarray(self._ranks),
        }

    def load_state_dict(self, d: dict) -> None:
        self._edges.load_state_dict(d["edges"])
        self._ranks = None if d["ranks"] is None else jnp.asarray(d["ranks"])

    def ranks(self) -> dict:
        """Current (raw vertex id -> rank), seen vertices only."""
        if self._ranks is None:
            return {}
        n = len(self._vdict)
        r = np.asarray(self._ranks)[:n]
        raw = self._vdict.decode(np.arange(n))
        return {int(v): float(x) for v, x in zip(raw, r)}
