"""Fully-dynamic degree distribution (±edge events).

TPU-native re-design of ``example/DegreeDistribution.java:42-131``, the
reference's only fully-dynamic (addition + deletion) workload. Its pipeline —
flatMap to (vertex, ±1), keyed degree counts, keyed histogram counts — runs
one boxed record at a time with two HashMap states. Here each window of
events is ONE compiled step:

- Per-vertex ordered degree folds are batched with a segmented associative
  scan: the reference's clamped sequential update ``deg' = max(0, deg + d)``
  (degree ≤ 0 removes the vertex, ``DegreeDistribution.java:93-100``)
  composes as ``g(x) = max(m, x + s)``; two such updates fuse to
  ``(s1+s2, max(m2, m1+s2))`` — associative, so in-window event order per
  vertex is preserved exactly while all vertices fold in parallel.
- The histogram is derived state: subtract old-degree counts of touched
  vertices, add new-degree counts (degree 0 never tracked, matching the
  reference's remove-on-zero).

Emission semantics (documented delta, SURVEY.md §7): the reference emits
(degree, count) per record update; here per window, change-only. Final
histograms are identical for any windowing.
"""

from __future__ import annotations

import functools
from typing import Iterable, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.edgeblock import bucket_capacity
from ..core.emission import LazyListBatch
from ..core.types import EventType
from ..core.window import CountWindow, WindowPolicy, Windower
from ..ops.segment import segmented_reduce_generic


def _combine(a, b):
    """Compose clamped degree updates g(x) = max(m, x+s): b AFTER a."""
    s1, m1 = a
    s2, m2 = b
    return s1 + s2, jnp.maximum(m2, m1 + s2)


@functools.partial(jax.jit, static_argnums=(5,))
def _degree_step(deg, hist, verts, deltas, mask, vcap: int):
    s0 = deltas.astype(jnp.int32)
    m0 = jnp.zeros_like(s0)
    (s, m), nonempty = segmented_reduce_generic(
        (s0, m0), verts, mask, vcap, _combine
    )
    old = deg
    new = jnp.where(nonempty, jnp.maximum(m, old + s), old)
    hcap = hist.shape[0]
    dec = (nonempty & (old > 0)).astype(jnp.int32)
    inc = (nonempty & (new > 0)).astype(jnp.int32)
    hist = hist.at[jnp.clip(old, 0, hcap - 1)].add(-dec)
    hist = hist.at[jnp.clip(new, 0, hcap - 1)].add(inc)
    return new, hist


class DegreeDistribution:
    """Streaming (degree -> vertex count) histogram over ±edge events.

    ``run(events)`` consumes ``(src, dst, change)`` records — ``change`` an
    :class:`EventType`, ``"+"``/``"-"``, or ±1 — and yields, per window, the
    change-only list of ``(degree, count)`` histogram entries.
    """

    def __init__(self, window: Optional[WindowPolicy] = None, vertex_dict=None):
        self.window = window or CountWindow(1 << 16)
        # the windower (and its VertexDict) persists across run() calls so
        # a resumed stream keeps the same compact-id space as the carried
        # degree vector
        self._windower = Windower(self.window, vertex_dict, val_dtype=np.int32)
        self._deg = None  # device int32[vcap]
        self._hist = None  # device int32[hcap]; index = degree, [0] unused
        # host shadow for histogram-capacity growth (zero device reads in
        # the producer loop): per window, no degree can rise by more than
        # that window's max per-vertex event count (host bincount on the
        # cached columns), so the running sum upper-bounds the max degree;
        # materializing any emission tightens it to the downloaded truth.
        self._max_deg_ub = 0
        # monotone sum of all shadow increments ever applied (NEVER
        # tightened): lazy batches record it at creation, so a stale
        # read can reconstruct "increments since this batch" exactly —
        # (shadow_now - batch_ub) is NOT that quantity once a newer read
        # tightened and the shadow regrew (round-5 review repro)
        self._inc_total = 0
        self._lineage = 0  # bumped on restore; stale-lineage batches skip
        self._events_total = 0
        self._emit_base = 0  # event watermark of the last materialized batch
        self._emit_prev = None  # host hist at the last materialized batch

    @classmethod
    def sliding(cls, size: int, slide: Optional[int] = None, **kwargs):
        """The EVENT-TIME shape of this workload: exact decremental
        degrees + heavy hitters over a sliding window that retracts
        expired panes (ISSUE 18) — a configured
        :class:`~gelly_streaming_tpu.eventtime.SlidingGraphAggregator`
        restricted to the degree summary. ``size``/``slide`` are event
        time units; extra kwargs pass through (``allowed_lateness``,
        ``nshards``, ``commit_dir``, ...)."""
        from ..eventtime import SlidingGraphAggregator

        return SlidingGraphAggregator(
            size, slide, summaries=("degree",), **kwargs
        )

    def run(self, events: Iterable[Tuple]) -> Iterator["HistogramBatch"]:
        """Yields one lazy :class:`HistogramBatch` per window — list-like
        ``(degree, count)`` change-only entries, downloaded on first read
        (the round-3 version downloaded two full histograms per window).
        Materializing batches in stream order reproduces per-window
        change-only emission exactly; skipping windows folds their
        changes into the next batch read."""
        windower = self._windower
        rows = ((s, d, _delta(c), *rest) for s, d, c, *rest in events)
        for block in windower.blocks(rows):
            vcap = block.n_vertices
            cache = getattr(block, "_host_cache", None)
            if cache is not None:
                s_h, d_h = cache[0], cache[1]
            else:  # non-windower block (rare): one download
                mask_h = np.asarray(block.mask)
                s_h = np.asarray(block.src)[mask_h]
                d_h = np.asarray(block.dst)[mask_h]
            n_events = len(s_h)
            if n_events:
                # max per-vertex event count this window bounds how far
                # any degree (hence the histogram support) can rise
                both = np.concatenate([s_h, d_h])
                inc = int(np.unique(both, return_counts=True)[1].max())
                self._max_deg_ub += inc
                self._inc_total += inc
            if self._deg is None:
                self._deg = jnp.zeros(vcap, jnp.int32)
            elif vcap > self._deg.shape[0]:
                self._deg = jnp.concatenate(
                    [self._deg,
                     jnp.zeros(vcap - self._deg.shape[0], jnp.int32)]
                )
            hcap = bucket_capacity(self._max_deg_ub + 1)
            if self._hist is None:
                self._hist = jnp.zeros(hcap, jnp.int32)
            elif hcap > self._hist.shape[0]:
                self._hist = jnp.concatenate(
                    [self._hist,
                     jnp.zeros(hcap - self._hist.shape[0], jnp.int32)]
                )
            # interleave [s0, d0, s1, d1, ...] — the reference emits
            # (src, ±1) then (dst, ±1) PER EVENT (``DegreeDistribution.
            # java:73-77``), and per-vertex clamp order matters when a
            # degree crosses zero; a plain [all srcs, all dsts] concat
            # would reorder a vertex's src-role vs dst-role updates
            verts = jnp.stack([block.src, block.dst], axis=1).ravel()
            deltas = jnp.stack([block.val, block.val], axis=1).ravel()
            mask = jnp.stack([block.mask, block.mask], axis=1).ravel()
            self._deg, self._hist = _degree_step(
                self._deg, self._hist, verts, deltas, mask, vcap
            )
            self._events_total += n_events
            yield HistogramBatch(
                self, self._hist, self._events_total, self._inc_total
            )

    def state_dict(self) -> dict:
        """Checkpoint surface (``aggregate/checkpoint.py:save_workload``);
        self-contained: includes the vertex dictionary so the compact-id
        space survives the resume."""
        hist = None if self._hist is None else np.asarray(self._hist)
        max_deg = (
            0 if hist is None or not hist.any()
            else int(np.nonzero(hist)[0][-1])
        )
        # checkpoint = a natural sync point: snap the shadow exactly
        self._max_deg_ub = min(self._max_deg_ub, max_deg)
        return {
            "deg": None if self._deg is None else np.asarray(self._deg),
            "hist": hist,
            "max_deg": max_deg,
            "vdict_raw": self._windower.vertex_dict.raw_ids(),
        }

    def load_state_dict(self, d: dict) -> None:
        self._deg = None if d["deg"] is None else jnp.asarray(d["deg"])
        self._hist = None if d["hist"] is None else jnp.asarray(d["hist"])
        self._max_deg_ub = int(d["max_deg"])
        # fresh lineage: batches minted before the restore hold a counter
        # from the old lineage and must not pass the _compute guard
        self._inc_total = 0
        self._lineage += 1
        self._events_total = 0
        self._emit_base = 0
        self._emit_prev = None if d["hist"] is None else np.asarray(d["hist"]).copy()
        vd = self._windower.vertex_dict
        if len(vd) == 0:
            vd.encode(d["vdict_raw"])
        elif vd.raw_ids().tolist() != d["vdict_raw"].tolist():
            raise ValueError(
                "restoring into a DegreeDistribution whose vertex dictionary "
                "already diverged from the checkpoint"
            )

    # ---- serving surface (serving/server.py Servable contract) ------- #
    def servable(self, vdict=None) -> "DegreeServable":
        """Adapter publishing the carried degree vector per window for
        ``DegreeQuery`` point lookups (``vdict`` is only consulted for
        the checkpoint boot payload; live windows use the windower's
        dict)."""
        return DegreeServable(self, vdict)

    def histogram(self) -> dict:
        """Current (degree -> count) map, degree >= 1 entries only.
        A natural sync point: snaps the capacity shadow to the truth."""
        if self._hist is None:
            return {}
        h = np.asarray(self._hist)
        nz = np.nonzero(h)[0]
        self._max_deg_ub = min(
            self._max_deg_ub, int(nz[-1]) if len(nz) else 0
        )
        return {int(d): int(h[d]) for d in nz if d > 0}

    def degrees(self) -> np.ndarray:
        return np.zeros(0, np.int32) if self._deg is None else np.asarray(self._deg)


class HistogramBatch(LazyListBatch):
    """One window's change-only histogram emission, LAZY (the degree
    analog of :class:`~gelly_streaming_tpu.library.triangles.TriangleBatch`):
    the device histogram downloads on first read, changes are reported
    against the histogram at the previous materialized batch, and the
    workload's capacity shadow tightens from what the download reveals.
    Materializing in stream order reproduces per-window change-only
    emission exactly; an out-of-order read diffs against whatever was
    materialized last WITHOUT regressing the workload's watermarks."""

    __slots__ = ("_workload", "_hist", "_ev", "_inc", "_lin", "_items")

    def __init__(self, workload, hist, ev, inc):
        self._workload = workload
        self._hist = hist
        self._ev = ev
        self._inc = inc  # workload._inc_total at batch creation
        self._lin = workload._lineage
        self._items = None

    def _compute(self) -> list:
        w = self._workload
        h = np.asarray(self._hist)
        prev = w._emit_prev
        if prev is None or len(prev) < len(h):
            grown = np.zeros(len(h), h.dtype)
            if prev is not None:
                grown[: len(prev)] = prev
            prev = grown
        changed = np.nonzero(h != prev[: len(h)])[0]
        items = [(int(d), int(h[d])) for d in changed]
        if self._ev >= w._emit_base:
            # newest materialization wins; an older batch read later must
            # not clobber the diff base or the watermark
            w._emit_prev = h
            w._emit_base = self._ev
        # capacity shadow: true max NOW <= true max AT THIS BATCH plus
        # the increments applied since. "Increments since" is measured on
        # the MONOTONE counter (w._inc_total - self._inc), never on the
        # shadow itself — (shadow - batch_ub) understates the increments
        # once a newer read tightened the shadow and it regrew, which
        # dragged the shadow below the true max (round-5 review repro:
        # degree-18 vertex clipped into bin 15). The monotone form is a
        # sound bound under ANY read order; the guard only skips batches
        # from a pre-restore lineage, whose counter is incomparable.
        if self._lin == w._lineage and self._inc <= w._inc_total:
            nz = np.nonzero(h)[0]
            true_max = int(nz[-1]) if len(nz) else 0
            w._max_deg_ub = min(
                w._max_deg_ub, true_max + (w._inc_total - self._inc)
            )
        return items


class DegreeServable:
    """:class:`~gelly_streaming_tpu.serving.server.Servable` adapter for
    :class:`DegreeDistribution`: one ``deg`` table per window (the
    jitted step returns fresh buffers, so published tables are
    immutable), watermark = cumulative events folded."""

    def __init__(self, workload: DegreeDistribution, vdict=None):
        from ..serving import DegreeQuery

        self.query_classes = (DegreeQuery,)
        self._workload = workload
        self._vdict = vdict

    def payloads(self, events):
        w = self._workload
        vdict = w._windower.vertex_dict
        self._vdict = vdict
        for _ in w.run(events):
            yield {"deg": w._deg, "vdict": vdict}, w._events_total

    def boot_payload(self):
        w = self._workload
        if w._deg is None:
            return None
        vdict = self._vdict or w._windower.vertex_dict
        return {"deg": w._deg, "vdict": vdict}, w._events_total


def _delta(change) -> int:
    if isinstance(change, EventType):
        return 1 if change is EventType.EDGE_ADDITION else -1
    if change in ("+", 1, True):
        return 1
    if change in ("-", -1, False):
        return -1
    raise ValueError(f"bad event change {change!r}")
