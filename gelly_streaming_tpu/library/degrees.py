"""Fully-dynamic degree distribution (±edge events).

TPU-native re-design of ``example/DegreeDistribution.java:42-131``, the
reference's only fully-dynamic (addition + deletion) workload. Its pipeline —
flatMap to (vertex, ±1), keyed degree counts, keyed histogram counts — runs
one boxed record at a time with two HashMap states. Here each window of
events is ONE compiled step:

- Per-vertex ordered degree folds are batched with a segmented associative
  scan: the reference's clamped sequential update ``deg' = max(0, deg + d)``
  (degree ≤ 0 removes the vertex, ``DegreeDistribution.java:93-100``)
  composes as ``g(x) = max(m, x + s)``; two such updates fuse to
  ``(s1+s2, max(m2, m1+s2))`` — associative, so in-window event order per
  vertex is preserved exactly while all vertices fold in parallel.
- The histogram is derived state: subtract old-degree counts of touched
  vertices, add new-degree counts (degree 0 never tracked, matching the
  reference's remove-on-zero).

Emission semantics (documented delta, SURVEY.md §7): the reference emits
(degree, count) per record update; here per window, change-only. Final
histograms are identical for any windowing.
"""

from __future__ import annotations

import functools
from typing import Iterable, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.edgeblock import bucket_capacity
from ..core.types import EventType
from ..core.window import CountWindow, WindowPolicy, Windower
from ..ops.segment import segmented_reduce_generic


def _combine(a, b):
    """Compose clamped degree updates g(x) = max(m, x+s): b AFTER a."""
    s1, m1 = a
    s2, m2 = b
    return s1 + s2, jnp.maximum(m2, m1 + s2)


@functools.partial(jax.jit, static_argnums=(5,))
def _degree_step(deg, hist, verts, deltas, mask, vcap: int):
    s0 = deltas.astype(jnp.int32)
    m0 = jnp.zeros_like(s0)
    (s, m), nonempty = segmented_reduce_generic(
        (s0, m0), verts, mask, vcap, _combine
    )
    old = deg
    new = jnp.where(nonempty, jnp.maximum(m, old + s), old)
    hcap = hist.shape[0]
    dec = (nonempty & (old > 0)).astype(jnp.int32)
    inc = (nonempty & (new > 0)).astype(jnp.int32)
    hist = hist.at[jnp.clip(old, 0, hcap - 1)].add(-dec)
    hist = hist.at[jnp.clip(new, 0, hcap - 1)].add(inc)
    return new, hist


class DegreeDistribution:
    """Streaming (degree -> vertex count) histogram over ±edge events.

    ``run(events)`` consumes ``(src, dst, change)`` records — ``change`` an
    :class:`EventType`, ``"+"``/``"-"``, or ±1 — and yields, per window, the
    change-only list of ``(degree, count)`` histogram entries.
    """

    def __init__(self, window: Optional[WindowPolicy] = None, vertex_dict=None):
        self.window = window or CountWindow(1 << 16)
        # the windower (and its VertexDict) persists across run() calls so
        # a resumed stream keeps the same compact-id space as the carried
        # degree vector
        self._windower = Windower(self.window, vertex_dict, val_dtype=np.int32)
        self._deg = None  # device int32[vcap]
        self._hist = None  # device int32[hcap]; index = degree, [0] unused
        self._max_deg = 0

    def run(self, events: Iterable[Tuple]) -> Iterator[List[Tuple[int, int]]]:
        windower = self._windower
        rows = ((s, d, _delta(c), *rest) for s, d, c, *rest in events)
        for block in windower.blocks(rows):
            vcap = block.n_vertices
            n_events = int(np.asarray(block.mask).sum())
            if self._deg is None:
                self._deg = jnp.zeros(vcap, jnp.int32)
            elif vcap > self._deg.shape[0]:
                self._deg = jnp.concatenate(
                    [self._deg,
                     jnp.zeros(vcap - self._deg.shape[0], jnp.int32)]
                )
            # histogram capacity: degrees this window cannot exceed
            # old max + events in the window
            hcap = bucket_capacity(self._max_deg + n_events + 1)
            if self._hist is None:
                self._hist = jnp.zeros(hcap, jnp.int32)
            elif hcap > self._hist.shape[0]:
                self._hist = jnp.concatenate(
                    [self._hist,
                     jnp.zeros(hcap - self._hist.shape[0], jnp.int32)]
                )
            # interleave [s0, d0, s1, d1, ...] — the reference emits
            # (src, ±1) then (dst, ±1) PER EVENT (``DegreeDistribution.
            # java:73-77``), and per-vertex clamp order matters when a
            # degree crosses zero; a plain [all srcs, all dsts] concat
            # would reorder a vertex's src-role vs dst-role updates
            verts = jnp.stack([block.src, block.dst], axis=1).ravel()
            deltas = jnp.stack([block.val, block.val], axis=1).ravel()
            mask = jnp.stack([block.mask, block.mask], axis=1).ravel()
            old_hist = self._hist
            self._deg, self._hist = _degree_step(
                self._deg, self._hist, verts, deltas, mask, vcap
            )
            self._max_deg = int(self._deg.max())
            changed = np.nonzero(
                np.asarray(self._hist) != np.asarray(old_hist)
            )[0]
            new_hist = np.asarray(self._hist)
            yield [(int(d), int(new_hist[d])) for d in changed]

    def state_dict(self) -> dict:
        """Checkpoint surface (``aggregate/checkpoint.py:save_workload``);
        self-contained: includes the vertex dictionary so the compact-id
        space survives the resume."""
        return {
            "deg": None if self._deg is None else np.asarray(self._deg),
            "hist": None if self._hist is None else np.asarray(self._hist),
            "max_deg": self._max_deg,
            "vdict_raw": self._windower.vertex_dict.raw_ids(),
        }

    def load_state_dict(self, d: dict) -> None:
        self._deg = None if d["deg"] is None else jnp.asarray(d["deg"])
        self._hist = None if d["hist"] is None else jnp.asarray(d["hist"])
        self._max_deg = int(d["max_deg"])
        vd = self._windower.vertex_dict
        if len(vd) == 0:
            vd.encode(d["vdict_raw"])
        elif vd.raw_ids().tolist() != d["vdict_raw"].tolist():
            raise ValueError(
                "restoring into a DegreeDistribution whose vertex dictionary "
                "already diverged from the checkpoint"
            )

    def histogram(self) -> dict:
        """Current (degree -> count) map, degree >= 1 entries only."""
        if self._hist is None:
            return {}
        h = np.asarray(self._hist)
        return {int(d): int(h[d]) for d in np.nonzero(h)[0] if d > 0}

    def degrees(self) -> np.ndarray:
        return np.zeros(0, np.int32) if self._deg is None else np.asarray(self._deg)


def _delta(change) -> int:
    if isinstance(change, EventType):
        return 1 if change is EventType.EDGE_ADDITION else -1
    if change in ("+", 1, True):
        return 1
    if change in ("-", -1, False):
        return -1
    raise ValueError(f"bad event change {change!r}")
